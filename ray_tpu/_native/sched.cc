// Scheduling kernel (C++ native).
//
// Behavioral parity with the reference's scheduling hot path
// (reference: src/ray/common/scheduling/cluster_resource_data.h NodeResources
// + src/ray/raylet/scheduling/policy/hybrid_scheduling_policy.h:50 and the
// autoscaler's bin-packing resource_demand_scheduler.py): fixed-point
// resource vectors, best-node selection with the hybrid utilization score,
// and first-fit-decreasing packing of pending demands onto node types.
//
// Resources are dense double vectors over an interned name space the Python
// side maintains (scheduling_ids.h analog); one call scores the whole
// cluster without Python-loop overhead, which is what the GCS actor
// scheduler and the autoscaler grind on at scale.
//
// C ABI consumed via ctypes (ray_tpu/_native/__init__.py).

#include <cmath>
#include <cstdint>
#include <cstring>
#include <vector>

namespace {

inline bool fits(const double* avail, const double* req, int n_res) {
  for (int r = 0; r < n_res; r++) {
    if (req[r] > 0 && avail[r] + 1e-9 < req[r]) return false;
  }
  return true;
}

inline bool feasible(const double* total, const double* req, int n_res) {
  return fits(total, req, n_res);
}

// Hybrid score (reference: hybrid_scheduling_policy.h design notes lines
// 29-49): prefer nodes under the spread threshold by lowest utilization;
// above it, prefer lowest utilization anyway but after every under-threshold
// node (top-k behavior collapses to best-node here).
inline double utilization(const double* avail, const double* total,
                          int n_res) {
  double worst = 0.0;
  for (int r = 0; r < n_res; r++) {
    if (total[r] > 0) {
      double u = 1.0 - avail[r] / total[r];
      if (u > worst) worst = u;
    }
  }
  return worst;
}

}  // namespace

extern "C" {

// Pick the best node for `req`.
// avail/total: row-major [n_nodes][n_res]. Returns node index or -1.
int tpu_sched_best_node(const double* avail, const double* total,
                        int n_nodes, int n_res, const double* req,
                        double spread_threshold) {
  int best = -1;
  double best_score = 1e18;
  for (int i = 0; i < n_nodes; i++) {
    const double* a = avail + (size_t)i * n_res;
    const double* t = total + (size_t)i * n_res;
    if (!feasible(t, req, n_res) || !fits(a, req, n_res)) continue;
    double u = utilization(a, t, n_res);
    // under-threshold nodes sort before over-threshold ones
    double score = (u < spread_threshold ? 0.0 : 1e9) + u;
    if (score < best_score) {
      best_score = score;
      best = i;
    }
  }
  return best;
}

// Feasibility-only variant (ignores current availability) for autoscaler
// "could this node type ever host it" checks. Returns first feasible type
// index or -1.
int tpu_sched_first_feasible(const double* totals, int n_types, int n_res,
                             const double* req) {
  for (int i = 0; i < n_types; i++) {
    if (feasible(totals + (size_t)i * n_res, req, n_res)) return i;
  }
  return -1;
}

// First-fit-decreasing bin-packing of demands onto existing pools plus new
// nodes of given types (the autoscaler core, resource_demand_scheduler.py).
//
//  demands:        [n_demands][n_res], pre-sorted by the caller (largest
//                  first for FFD; order is respected as given)
//  pools:          [n_pools][n_res] — existing nodes' availability;
//                  MUTATED in place as demands land on them
//  type_caps:      [n_types][n_res] — full capacity per launchable type
//  type_max_new:   [n_types] — per-type launch headroom (already accounts
//                  for existing counts); MUTATED as launches are decided
//  budget:         max total new nodes; MUTATED
//  out_launch:     [n_types] — launch counts per type (+=)
//  out_unfulfilled:[n_demands] — 1 where a demand could not be placed
//
// New nodes' remaining capacity participates in packing for later demands.
// Returns number of new nodes launched.
int tpu_sched_bin_pack(const double* demands, int n_demands,
                       double* pools, int n_pools,
                       const double* type_caps, int n_types,
                       int* type_max_new, int* budget, int n_res,
                       int* out_launch, uint8_t* out_unfulfilled) {
  std::vector<std::vector<double>> fresh;  // remaining capacity of launches
  std::vector<int> fresh_type;
  int launched = 0;
  for (int d = 0; d < n_demands; d++) {
    const double* req = demands + (size_t)d * n_res;
    out_unfulfilled[d] = 0;
    // 1) existing pools
    bool placed = false;
    for (int p = 0; p < n_pools && !placed; p++) {
      double* pool = pools + (size_t)p * n_res;
      if (fits(pool, req, n_res)) {
        for (int r = 0; r < n_res; r++) pool[r] -= req[r];
        placed = true;
      }
    }
    // 2) capacity remaining on already-decided launches
    for (size_t f = 0; f < fresh.size() && !placed; f++) {
      if (fits(fresh[f].data(), req, n_res)) {
        for (int r = 0; r < n_res; r++) fresh[f][r] -= req[r];
        placed = true;
      }
    }
    if (placed) continue;
    // 3) launch a new node of the first feasible type with headroom
    int chosen = -1;
    for (int ty = 0; ty < n_types; ty++) {
      if (type_max_new[ty] <= 0) continue;
      if (feasible(type_caps + (size_t)ty * n_res, req, n_res)) {
        chosen = ty;
        break;
      }
    }
    if (chosen < 0 || *budget <= 0) {
      out_unfulfilled[d] = 1;
      continue;
    }
    std::vector<double> cap(type_caps + (size_t)chosen * n_res,
                            type_caps + (size_t)(chosen + 1) * n_res);
    for (int r = 0; r < n_res; r++) cap[r] -= req[r];
    fresh.push_back(std::move(cap));
    fresh_type.push_back(chosen);
    out_launch[chosen] += 1;
    type_max_new[chosen] -= 1;
    *budget -= 1;
    launched += 1;
  }
  return launched;
}

}  // extern "C"
