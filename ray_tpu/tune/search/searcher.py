"""Searcher plugin ABC (reference: python/ray/tune/search/searcher.py) and
ConcurrencyLimiter (reference: tune/search/concurrency_limiter.py)."""

from __future__ import annotations

import pickle
from typing import Any, Dict, Optional


class Searcher:
    """Suggest configs for new trials; observe results to adapt.

    Subclasses implement ``suggest`` (return a config dict, ``None`` when
    temporarily out of suggestions, or ``Searcher.FINISHED`` when the space
    is exhausted) and optionally the observation hooks.
    """

    FINISHED = "FINISHED"

    def __init__(self, metric: Optional[str] = None, mode: Optional[str] = None):
        self.metric = metric
        self.mode = mode or "max"

    def set_search_properties(self, metric: Optional[str],
                              mode: Optional[str],
                              config: Optional[Dict]) -> bool:
        if metric:
            self.metric = metric
        if mode:
            self.mode = mode
        return True

    def suggest(self, trial_id: str) -> Optional[Dict]:
        raise NotImplementedError

    def on_trial_result(self, trial_id: str, result: Dict) -> None:
        pass

    def on_trial_complete(self, trial_id: str,
                          result: Optional[Dict] = None,
                          error: bool = False) -> None:
        pass

    # ------------------------------------------------- experiment state
    def save_state(self) -> bytes:
        return pickle.dumps(self.__dict__)

    def restore_state(self, data: bytes) -> None:
        self.__dict__.update(pickle.loads(data))


class ConcurrencyLimiter(Searcher):
    """Cap in-flight suggestions from a wrapped searcher
    (reference: tune/search/concurrency_limiter.py:21)."""

    def __init__(self, searcher: Searcher, max_concurrent: int):
        super().__init__(searcher.metric, searcher.mode)
        self.searcher = searcher
        self.max_concurrent = max_concurrent
        self._live: set = set()

    def set_search_properties(self, metric, mode, config) -> bool:
        super().set_search_properties(metric, mode, config)
        return self.searcher.set_search_properties(metric, mode, config)

    def suggest(self, trial_id: str) -> Optional[Dict]:
        if len(self._live) >= self.max_concurrent:
            return None
        suggestion = self.searcher.suggest(trial_id)
        if suggestion is not None and suggestion != Searcher.FINISHED:
            self._live.add(trial_id)
        return suggestion

    def on_trial_result(self, trial_id: str, result: Dict) -> None:
        self.searcher.on_trial_result(trial_id, result)

    def on_trial_complete(self, trial_id, result=None, error=False) -> None:
        self._live.discard(trial_id)
        self.searcher.on_trial_complete(trial_id, result, error)

    def save_state(self) -> bytes:
        return pickle.dumps((self.max_concurrent, self.searcher.save_state()))

    def restore_state(self, data: bytes) -> None:
        self.max_concurrent, inner = pickle.loads(data)
        self._live = set()
        self.searcher.restore_state(inner)
