"""C++ driver client e2e (reference: cpp/ — the reference ships a C++
worker API; here a native driver speaks the msgpack control plane:
KV through the head, worker leases from the agent, direct PushTask with
cross-language specs executed by Python workers). The binary is built
with bare g++ (no third-party deps) and driven against a live local
cluster; the cross-language spec hooks are also covered Python-side so
the contract is pinned even where g++ is unavailable."""

import os
import shutil
import subprocess
import sys

import pytest

import ray_tpu

CPP_DIR = os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "cpp")
HAVE_GXX = shutil.which("g++") is not None


class TestXlangSpecHooks:
    """Python-side contract for non-Python drivers."""

    def test_load_pyref_colon_and_dotted(self):
        from ray_tpu._private.function_table import load_pyref

        assert load_pyref("operator:add")(2, 3) == 5
        assert load_pyref("os.path.join")("a", "b") == os.path.join("a", "b")
        with pytest.raises(Exception):
            load_pyref("nonexistent_module_xyz:fn")

    def test_xlang_fid_resolves_by_name(self):
        from ray_tpu._private.function_table import (
            XLANG_PYREF_FID, load_function)

        fn = load_function(XLANG_PYREF_FID, None, None, name="operator:mul")
        assert fn(6, 7) == 42

    def test_xlang_task_end_to_end_from_python(self):
        """Submit a spec shaped exactly like the C++ client's through a
        real worker: by-name function, 'x' msgpack args, msgpack return."""
        import msgpack

        from ray_tpu._private.function_table import XLANG_PYREF_FID

        if not ray_tpu.is_initialized():
            ray_tpu.init(num_cpus=2)
        try:
            worker = ray_tpu._private.worker.global_worker
            import asyncio

            async def push():
                reply = await worker.agent.call("RequestWorkerLease", {
                    "resources": {"CPU": 10000},
                    "owner": "xlang-test", "retriable": False,
                })
                grant = reply["grant"]
                from ray_tpu._private.protocol import AsyncRpcClient

                client = AsyncRpcClient()
                await client.connect_tcp(grant["addr"]["host"],
                                         grant["addr"]["port"])
                spec = {
                    "task_id": os.urandom(16), "job_id": b"xlg0",
                    "task_type": 0, "function_id": XLANG_PYREF_FID,
                    "function_name": "operator:add",
                    "args": [("x", msgpack.packb(19)),
                             ("x", msgpack.packb(23))],
                    "kwargs": {}, "num_returns": 1, "resources": {},
                    "owner_addr": {"host": "", "port": 0,
                                   "worker_id": "00" * 16},
                }
                result = await client.call("PushTask", spec)
                await worker.agent.call(
                    "ReturnWorker", {"lease_id": grant["lease_id"]})
                client.close()
                return result

            result = worker._acall(push(), timeout=120)
            assert not result.get("error")
            assert msgpack.unpackb(result["returns"][0]["xlang"]) == 42
        finally:
            ray_tpu.shutdown()


@pytest.mark.skipif(not HAVE_GXX, reason="no g++ on this box")
class TestCppDriver:
    def test_build_and_run_against_live_cluster(self):
        subprocess.run(["make", "-s"], cwd=CPP_DIR, check=True, timeout=300)
        binary = os.path.join(CPP_DIR, "build", "example_driver")
        assert os.path.exists(binary)
        if not ray_tpu.is_initialized():
            ray_tpu.init(num_cpus=2)
        try:
            node = ray_tpu._global_node
            out = subprocess.run(
                [binary, "127.0.0.1", str(node.head_port)],
                capture_output=True, text=True, timeout=240)
            sys.stdout.write(out.stdout)
            sys.stderr.write(out.stderr)
            assert out.returncode == 0
            assert "KV from-cpp" in out.stdout
            assert "SUM 42" in out.stdout
            assert "TOTAL 30" in out.stdout
            assert "CAUGHT" in out.stdout and "int" in out.stdout
            assert "CPP_DRIVER_OK" in out.stdout
            # the KV write from C++ is visible to Python clients too
            from ray_tpu.experimental import internal_kv

            assert internal_kv._internal_kv_get(b"cpp_key") == b"from-cpp"
        finally:
            ray_tpu.shutdown()


def _agent_tcp_port():
    w = ray_tpu._private.worker.global_worker
    view = w._acall(w.head.call("GetClusterView", {}))
    return list(view.values())[0]["addr"]["port"]


@pytest.mark.skipif(not HAVE_GXX, reason="no g++ on this box")
class TestCppWorker:
    """C++ task EXECUTION (VERDICT r3 next #7; reference:
    cpp/src/ray/runtime/task/task_executor.cc): an external C++ worker
    registers native functions, the agent routes language:cpp leases to
    it, and Python drivers call the functions by name."""

    @pytest.fixture()
    def cpp_worker(self):
        import subprocess as sp
        import time

        sp.run(["make", "-s"], cwd=CPP_DIR, check=True, timeout=300)
        binary = os.path.join(CPP_DIR, "build", "example_worker")
        if not ray_tpu.is_initialized():
            ray_tpu.init(num_cpus=2)
        proc = sp.Popen([binary, "127.0.0.1", str(_agent_tcp_port())],
                        stdout=sp.PIPE, stderr=sp.STDOUT, text=True)
        time.sleep(1.0)
        yield proc
        proc.terminate()
        ray_tpu.shutdown()

    def test_python_calls_cpp_function(self, cpp_worker):
        from ray_tpu.cross_language import cpp_function

        assert ray_tpu.get(cpp_function("cpp.add").remote(2, 3, 5),
                           timeout=60) == 10
        assert ray_tpu.get(cpp_function("cpp.fib").remote(20),
                           timeout=60) == 6765
        # structured values survive the msgpack round trip
        assert ray_tpu.get(
            cpp_function("cpp.echo").remote({"k": [1, 2, "three"]}),
            timeout=60) == {"k": [1, 2, "three"]}

    def test_cpp_error_propagates(self, cpp_worker):
        from ray_tpu.cross_language import cpp_function

        with pytest.raises(Exception, match="deliberate C\\+\\+ failure"):
            ray_tpu.get(cpp_function("cpp.fail").remote(), timeout=60)
        with pytest.raises(Exception, match="no such C"):
            ray_tpu.get(cpp_function("cpp.nope").remote(), timeout=60)

    def test_burst_rides_stream_batches(self, cpp_worker):
        from ray_tpu.cross_language import cpp_function

        refs = [cpp_function("cpp.add").remote(i, i) for i in range(60)]
        assert sum(ray_tpu.get(refs, timeout=120)) == sum(
            2 * i for i in range(60))

    def test_roundtrip_python_task_calls_cpp(self, cpp_worker):
        """Py driver -> Py worker task -> C++ worker -> back: the full
        cross-language chain in one object graph."""

        @ray_tpu.remote
        def via_python(x):
            from ray_tpu.cross_language import cpp_function

            return ray_tpu.get(cpp_function("cpp.fib").remote(x)) + 1

        assert ray_tpu.get(via_python.remote(10), timeout=120) == 56
