// Example / test driver: exercises the C++ client against a live local
// cluster. Used by tests/test_cpp_client.py; also the template for user
// code. Usage: example_driver <head_host> <head_port>

#include <cstdlib>
#include <iostream>

#include "ray_tpu/client.hpp"

using ray_tpu::RayClient;
using ray_tpu::msgpack::Value;

int main(int argc, char** argv) {
  if (argc < 3) {
    std::cerr << "usage: example_driver <head_host> <head_port>\n";
    return 2;
  }
  RayClient ray;
  ray.Connect(argv[1], std::atoi(argv[2]));

  // 1. KV round trip through the head.
  ray.KvPut("cpp_key", "from-cpp");
  Value got = ray.KvGet("cpp_key");
  std::cout << "KV " << got.AsStr() << "\n";

  // 2. Cluster view.
  Value view = ray.ClusterView();
  std::cout << "NODES " << view.map.size() << "\n";

  // 3. Submit a Python task by module reference with msgpack args.
  std::vector<Value> args;
  args.push_back(Value::Int(20));
  args.push_back(Value::Int(22));
  Value sum = ray.SubmitPyTask("operator:add", args);
  std::cout << "SUM " << sum.AsInt() << "\n";

  // 4. A task returning a structured value.
  std::vector<Value> args2;
  Value lst = Value::Array();
  for (int k = 1; k <= 4; ++k) lst.arr.push_back(Value::Int(k * k));
  args2.push_back(lst);
  Value total = ray.SubmitPyTask("builtins:sum", args2);
  std::cout << "TOTAL " << total.AsInt() << "\n";

  // 5. Remote errors surface as exceptions with the worker's message.
  try {
    std::vector<Value> bad;
    bad.push_back(Value::Str("nope"));
    ray.SubmitPyTask("builtins:int", bad);  // int("nope") raises
    std::cout << "ERROR missing-exception\n";
    return 1;
  } catch (const std::exception& e) {
    std::cout << "CAUGHT " << e.what() << "\n";
  }
  std::cout << "CPP_DRIVER_OK\n";
  return 0;
}
