"""DD-PPO — decentralized distributed PPO (reference:
rllib/algorithms/ddppo/ddppo.py:16: rollout workers compute gradients
locally and allreduce them with no central learner bottleneck).

TPU-native mapping: "decentralized data parallel" is the native execution
model here, at two scales —

- across PROCESSES: ``num_learners=N`` learner actors each grad their
  batch shard and allreduce through ``ray_tpu.util.collective`` before
  applying (params stay bitwise identical; see
  core/learner_group.py _RemoteLearner);
- across CHIPS: a single learner jitted over a device mesh ``data`` axis,
  where GSPMD inserts the gradient psum over ICI — the role the
  reference's torch.distributed gloo/nccl allreduce plays. The 8-device
  dryrun exercises this path (__graft_entry__.dryrun_multichip).
"""

from __future__ import annotations

from ray_tpu.rllib.algorithms.ppo.ppo import PPO, PPOConfig


class DDPPOConfig(PPOConfig):
    def __init__(self, algo_class=None):
        super().__init__(algo_class=algo_class or DDPPO)
        # decentralized by default: two grad-syncing learner actors
        self.num_learners = 2


class DDPPO(PPO):
    @classmethod
    def get_default_config(cls):
        return DDPPOConfig(algo_class=cls)
