"""R4 — fire-and-forget ``asyncio.create_task`` / ``ensure_future``.

Invariant: every spawned task handle must be retained somewhere that
(a) keeps it alive (the loop holds only a *weak* reference — an
unreferenced task can be garbage-collected mid-flight, the source of
"Task was destroyed but it is pending!") and (b) surfaces its exception
(an unobserved failed task dies silently; the daemon it implemented is
simply gone).

Motivating bugs: the leaked read-loop tasks of PRs 1/3 (bench-tail "Task
was destroyed" spam traced to an overwritten client whose read task
nobody held), and the GCS loops that died silently until PR 5 put them
under a restart-on-crash supervisor with ``_hold_task``.

Detection: a ``create_task``/``ensure_future`` call whose result is
discarded — a bare expression statement, or assigned to ``_``. Passing
the task to a tracker (``self._hold_task(loop.create_task(...))``),
assigning it to an attribute, or appending it to a collection all count
as retained and are not flagged.
"""

from __future__ import annotations

import ast
from typing import List

from ..callgraph import _call_name
from ..model import ModuleInfo, Violation

RULE_ID = "R4"
SUMMARY = ("create_task/ensure_future result discarded — the loop keeps "
           "only a weak ref (task can vanish mid-flight) and exceptions "
           "are never observed; retain the handle in a tracked group")

_SPAWNERS = {"create_task", "ensure_future"}


def _is_spawn(call: ast.Call) -> bool:
    base, attr = _call_name(call.func)
    return attr in _SPAWNERS


def check_module(mod: ModuleInfo, index) -> List[Violation]:
    out: List[Violation] = []
    for node in ast.walk(mod.tree):
        if not isinstance(node, ast.Call) or not _is_spawn(node):
            continue
        parent = mod.parent(node)
        discarded = False
        if isinstance(parent, ast.Expr):
            discarded = True
        elif isinstance(parent, ast.Assign):
            targets = parent.targets
            if all(isinstance(t, ast.Name) and t.id == "_"
                   for t in targets):
                discarded = True
        if not discarded:
            continue
        base, attr = _call_name(node.func)
        out.append(mod.violation(
            RULE_ID, node,
            f"'{attr}' result discarded in '{mod.qualname(node)}': the "
            f"event loop holds the task only weakly (GC can destroy it "
            f"pending) and a raised exception is never observed — keep "
            f"the handle in a tracked set with a done-callback, or await "
            f"it"))
    return out
