"""Developer-facing correctness tooling for the ray_tpu runtime.

The runtime's kernel-layer analogs in the reference get their invariant
guarantees from C++ review and sanitizers (TSAN for the lock discipline,
ASAN for lifetime); this pure-Python runtime gets them from
``ray_tpu.devtools.lint`` — an AST/CFG checker whose rules are distilled
from the repo's own shipped-bug history. See ``lint/rules/`` for the
catalog and README "Correctness tooling" for the workflow.
"""
