"""Pallas TPU kernels. Each kernel has an XLA reference twin in ray_tpu.ops
used for CPU testing and as the custom-VJP recompute path."""
