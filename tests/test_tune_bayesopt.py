"""Native GP Bayesian-optimization searcher (reference:
tune/search/bayesopt/bayesopt_search.py wraps an external package; this
dependency-free GP must concentrate suggestions near the optimum of a
smooth surface far better than random search)."""

import math
import random

import numpy as np

from ray_tpu import tune
from ray_tpu.tune.search.bayesopt import (
    BayesOptSearcher, _expected_improvement, _GP)


def _surface(x, y):
    return -((x - 0.7) ** 2) - ((y + 0.3) ** 2)


def test_gp_posterior_interpolates():
    X = np.array([[0.1], [0.5], [0.9]])
    y = np.array([1.0, 3.0, 2.0])
    gp = _GP(X, y, length_scale=0.3, noise=1e-6)
    mu, sigma = gp.posterior(X)
    np.testing.assert_allclose(mu, y, atol=0.05)
    assert (sigma < 0.1).all()
    # far from data the posterior reverts toward the mean with wide bands
    mu_far, sigma_far = gp.posterior(np.array([[5.0]]))
    assert abs(mu_far[0] - y.mean()) < 0.5
    assert sigma_far[0] > sigma.max()


def test_ei_prefers_high_mean_and_high_uncertainty():
    mu = np.array([1.0, 2.0, 1.0])
    sigma = np.array([0.1, 0.1, 2.0])
    ei = _expected_improvement(mu, sigma, best=1.5)
    assert ei[1] > ei[0]           # better mean wins at equal sigma
    assert ei[2] > ei[0]           # uncertainty adds exploration value


def test_bayesopt_concentrates_near_optimum():
    space = {"x": tune.uniform(-2.0, 2.0), "y": tune.uniform(-2.0, 2.0)}
    searcher = BayesOptSearcher(space=space, metric="score", mode="max",
                                n_initial_points=10, seed=11)
    for i in range(45):
        tid = f"t{i}"
        cfg = searcher.suggest(tid)
        searcher.on_trial_complete(
            tid, {"score": _surface(cfg["x"], cfg["y"])})
    tail = []
    for i in range(8):
        tid = f"probe{i}"
        cfg = searcher.suggest(tid)
        tail.append(math.hypot(cfg["x"] - 0.7, cfg["y"] + 0.3))
        searcher.on_trial_complete(
            tid, {"score": _surface(cfg["x"], cfg["y"])})
    rng = random.Random(3)
    random_dist = [math.hypot(rng.uniform(-2, 2) - 0.7,
                              rng.uniform(-2, 2) + 0.3)
                   for _ in range(1000)]
    avg_random = sum(random_dist) / len(random_dist)
    avg_tail = sum(tail) / len(tail)
    assert avg_tail < avg_random * 0.5, (avg_tail, avg_random)


def test_bayesopt_minimize_mode_and_mixed_dims():
    space = {"lr": tune.loguniform(1e-5, 1e-1),
             "layers": tune.randint(1, 8),
             "act": tune.choice(["relu", "gelu"])}
    searcher = BayesOptSearcher(space=space, metric="loss", mode="min",
                                n_initial_points=6, seed=0)
    # loss minimized at lr = 1e-3, more layers help slightly
    for i in range(30):
        tid = f"t{i}"
        cfg = searcher.suggest(tid)
        assert cfg["act"] in ("relu", "gelu")
        assert 1 <= cfg["layers"] < 8
        loss = (math.log10(cfg["lr"]) + 3.0) ** 2 - 0.05 * cfg["layers"]
        searcher.on_trial_complete(tid, {"loss": loss})
    probes = []
    for i in range(6):
        cfg = searcher.suggest(f"p{i}")
        probes.append(abs(math.log10(cfg["lr"]) + 3.0))
        searcher.on_trial_complete(
            f"p{i}", {"loss": (math.log10(cfg["lr"]) + 3.0) ** 2})
    # suggestions should hover within one decade of the optimum
    assert sum(probes) / len(probes) < 1.0, probes


def test_bayesopt_state_roundtrip():
    space = {"x": tune.uniform(0.0, 1.0)}
    searcher = BayesOptSearcher(space=space, metric="score", mode="max",
                                seed=1)
    for i in range(12):
        cfg = searcher.suggest(f"t{i}")
        searcher.on_trial_complete(f"t{i}", {"score": -abs(cfg["x"] - 0.4)})
    blob = searcher.save_state()
    fresh = BayesOptSearcher(space=space, metric="score", mode="max")
    fresh.restore_state(blob)
    assert len(fresh._obs) == len(searcher._obs)
    assert fresh.suggest("next") is not None
