"""Off-policy estimators (reference: rllib/offline/estimators/ —
ImportanceSampling, WeightedImportanceSampling, DirectMethod,
DoublyRobust; SURVEY §2.4 "offline data ... off-policy estimators").

Estimate a target policy's value from logged behavior-policy episodes
without running it (OPE). Input format: episodes as dicts with
``rewards`` [T], behavior ``logp`` [T], and the target policy's
``target_logp`` [T] on the logged actions (computed by the caller from
its module — keeps the estimators framework-agnostic math).
"""

from __future__ import annotations

from typing import Dict, List

import numpy as np


def _per_episode_rho(ep: Dict, clip: float) -> np.ndarray:
    """Cumulative importance ratios rho_{0..t} for one episode."""
    log_ratio = np.asarray(ep["target_logp"], np.float64) - \
        np.asarray(ep["logp"], np.float64)
    rho = np.exp(np.cumsum(log_ratio))
    return np.clip(rho, 0.0, clip)


class ImportanceSampling:
    """Per-decision IS estimator (reference: estimators/
    importance_sampling.py): V = E[ sum_t gamma^t rho_{0..t} r_t ]."""

    def __init__(self, gamma: float = 0.99, rho_clip: float = 100.0):
        self.gamma = gamma
        self.rho_clip = rho_clip

    def estimate(self, episodes: List[Dict]) -> Dict[str, float]:
        vals = []
        for ep in episodes:
            rho = _per_episode_rho(ep, self.rho_clip)
            r = np.asarray(ep["rewards"], np.float64)
            disc = self.gamma ** np.arange(len(r))
            vals.append(float(np.sum(disc * rho * r)))
        v = np.asarray(vals)
        return {"v_target": float(v.mean()),
                "v_target_std": float(v.std()),
                "num_episodes": len(vals)}


class WeightedImportanceSampling:
    """Per-decision WIS (reference: estimators/weighted_importance_
    sampling.py): ratios normalized by their per-step mean across
    episodes — biased but much lower variance than IS."""

    def __init__(self, gamma: float = 0.99, rho_clip: float = 100.0):
        self.gamma = gamma
        self.rho_clip = rho_clip

    def estimate(self, episodes: List[Dict]) -> Dict[str, float]:
        T = max(len(ep["rewards"]) for ep in episodes)
        rhos = np.zeros((len(episodes), T))
        alive = np.zeros((len(episodes), T))
        for i, ep in enumerate(episodes):
            r = _per_episode_rho(ep, self.rho_clip)
            rhos[i, :len(r)] = r
            alive[i, :len(r)] = 1.0
        # per-step normalizer: mean rho over episodes still running
        denom = np.where(alive.sum(0) > 0,
                         rhos.sum(0) / np.maximum(alive.sum(0), 1), 1.0)
        vals = []
        for i, ep in enumerate(episodes):
            r = np.asarray(ep["rewards"], np.float64)
            t = len(r)
            w = rhos[i, :t] / np.maximum(denom[:t], 1e-12)
            disc = self.gamma ** np.arange(t)
            vals.append(float(np.sum(disc * w * r)))
        v = np.asarray(vals)
        return {"v_target": float(v.mean()),
                "v_target_std": float(v.std()),
                "num_episodes": len(vals)}


class DirectMethod:
    """DM estimator (reference: estimators/direct_method.py): value is the
    critic's estimate at initial states; no importance ratios. Needs
    ``v0`` per episode (the target policy's value prediction at s_0)."""

    def estimate(self, episodes: List[Dict]) -> Dict[str, float]:
        v = np.asarray([float(ep["v0"]) for ep in episodes])
        return {"v_target": float(v.mean()),
                "v_target_std": float(v.std()),
                "num_episodes": len(v)}


class DoublyRobust:
    """DR estimator (reference: estimators/doubly_robust.py): DM baseline
    plus per-decision IS correction of the critic's residuals. Needs
    per-step ``values`` (V(s_t)) and ``q_values`` (Q(s_t, a_t)) from the
    target policy's critic in each episode dict."""

    def __init__(self, gamma: float = 0.99, rho_clip: float = 100.0):
        self.gamma = gamma
        self.rho_clip = rho_clip

    def estimate(self, episodes: List[Dict]) -> Dict[str, float]:
        vals = []
        for ep in episodes:
            r = np.asarray(ep["rewards"], np.float64)
            v_t = np.asarray(ep["values"], np.float64)
            q_t = np.asarray(ep["q_values"], np.float64)
            rho = _per_episode_rho(ep, self.rho_clip)
            rho_prev = np.concatenate([[1.0], rho[:-1]])
            disc = self.gamma ** np.arange(len(r))
            # per-decision DR: V = sum_t gamma^t
            #   (rho_{t-1} V(s_t) - rho_t Q(s_t,a_t) + rho_t r_t)
            dr = np.sum(disc * (rho_prev * v_t - rho * q_t + rho * r))
            vals.append(float(dr))
        v = np.asarray(vals)
        return {"v_target": float(v.mean()),
                "v_target_std": float(v.std()),
                "num_episodes": len(vals)}
