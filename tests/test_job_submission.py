"""Job submission SDK (reference: dashboard/modules/job/ —
JobSubmissionClient.submit_job sdk.py:39, JobManager spawning a detached
JobSupervisor actor job_manager.py:525; VERDICT r1 weak #5)."""

import pytest

import ray_tpu
from ray_tpu.job_submission import JobStatus, JobSubmissionClient


@pytest.fixture(scope="module")
def client(ray_start_regular):
    return JobSubmissionClient()


def test_submit_and_succeed(client):
    job_id = client.submit_job(
        entrypoint="python -c \"print('hello from job')\"")
    status = client.wait_until_finish(job_id, timeout_s=120)
    assert status == JobStatus.SUCCEEDED
    logs = client.get_job_logs(job_id)
    assert "hello from job" in logs
    info = client.get_job_info(job_id)
    assert info["status"] == "SUCCEEDED"
    assert info["entrypoint"].startswith("python -c")


def test_failing_entrypoint_reports_failed(client):
    job_id = client.submit_job(
        entrypoint="python -c 'import sys; sys.exit(3)'")
    status = client.wait_until_finish(job_id, timeout_s=120)
    assert status == JobStatus.FAILED


def test_submit_with_env_vars(client):
    job_id = client.submit_job(
        entrypoint="python -c \"import os; print('V=' + os.environ['X1'])\"",
        runtime_env={"env_vars": {"X1": "42"}})
    assert client.wait_until_finish(job_id, timeout_s=120) == JobStatus.SUCCEEDED
    assert "V=42" in client.get_job_logs(job_id)


def test_list_jobs_contains_submissions(client):
    jobs = client.list_jobs()
    assert len(jobs) >= 2
    assert all("status" in j and "entrypoint" in j for j in jobs)


def test_stop_running_job(client):
    job_id = client.submit_job(
        entrypoint="python -c 'import time; time.sleep(600)'")
    # wait for it to leave PENDING so there is a process to stop
    import time as _t
    deadline = _t.time() + 60
    while _t.time() < deadline:
        st = client.get_job_status(job_id)
        if st == JobStatus.RUNNING:
            break
        _t.sleep(0.3)
    assert client.stop_job(job_id)
    deadline = _t.time() + 30
    while _t.time() < deadline:
        st = client.get_job_status(job_id)
        if st is not None and st.is_terminal():
            break
        _t.sleep(0.3)
    assert st == JobStatus.STOPPED
