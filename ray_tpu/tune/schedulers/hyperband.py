"""HyperBand — synchronous successive halving in brackets (reference:
python/ray/tune/schedulers/hyperband.py HyperBandScheduler; Li 2016).

Unlike ASHA (async_hyperband.py) which promotes/stops trials the moment
they report, HyperBand synchronizes each bracket at its rung milestone:
trials PAUSE when they reach the current milestone and the controller
holds them (via the ``may_resume`` protocol) until every live trial of the
bracket has reported; then the top 1/eta continue from their checkpoints
and the rest stop.

Bracket sizing follows Li 2016: bracket s (of s_max..0) admits
``n_s = ceil((s_max+1)/(s+1) * eta^s)`` trials starting at budget
``max_t / eta^s``; new trials fill the current bracket and roll over to
the next template when it's full.
"""

from __future__ import annotations

import math
from typing import Dict, List, Optional

from ray_tpu.tune.schedulers.trial_scheduler import TrialScheduler


class _SyncBracket:
    def __init__(self, rung_iters: List[int], quota: int, eta: float):
        self.rung_iters = rung_iters  # cumulative iteration milestones
        self.quota = quota
        self.eta = eta
        self.rung = 0
        self.trial_ids: List[str] = []
        self.scores: Dict[str, float] = {}   # scores at the current rung
        self.waiting: set = set()            # reached milestone, held
        self.dropped: set = set()

    @property
    def milestone(self) -> Optional[int]:
        return (self.rung_iters[self.rung]
                if self.rung < len(self.rung_iters) else None)

    @property
    def full(self) -> bool:
        return len(self.trial_ids) >= self.quota

    def live(self) -> List[str]:
        return [t for t in self.trial_ids if t not in self.dropped]

    def all_reported(self) -> bool:
        live = self.live()
        return bool(live) and all(t in self.waiting for t in live)

    def cut(self) -> List[str]:
        """Close the rung: return trial ids to STOP; survivors unheld."""
        live = self.live()
        keep_n = max(1, int(len(live) / self.eta))
        ranked = sorted(live, key=lambda t: self.scores.get(
            t, float("-inf")), reverse=True)
        stop = ranked[keep_n:]
        self.dropped.update(stop)
        self.rung += 1
        self.scores.clear()
        self.waiting.clear()
        return stop


class HyperBandScheduler(TrialScheduler):
    def __init__(self, metric: Optional[str] = None,
                 mode: Optional[str] = None, *,
                 time_attr: str = "training_iteration",
                 max_t: int = 81, reduction_factor: float = 3):
        super().__init__(metric, mode)
        self.time_attr = time_attr
        self.max_t = max_t
        self.eta = reduction_factor
        # bracket templates, most-aggressive first: (rung milestones, quota)
        s_max = int(math.log(max_t) / math.log(self.eta))
        self._templates: List[tuple] = []
        for s in range(s_max, -1, -1):
            rungs = [int(round(max_t / (self.eta ** i)))
                     for i in range(s, -1, -1)]
            quota = int(math.ceil(
                (s_max + 1) / (s + 1) * (self.eta ** s)))
            self._templates.append((rungs, quota))
        self._brackets: List[_SyncBracket] = []
        self._next_template = 0
        self._trial_bracket: Dict[str, _SyncBracket] = {}

    # ------------------------------------------------------------ protocol
    def may_resume(self, trial) -> bool:
        """Controller hook: a PAUSED trial stays held while its bracket
        rung is still filling."""
        bracket = self._trial_bracket.get(trial.trial_id)
        if bracket is None:
            return True
        return trial.trial_id not in bracket.waiting

    # ----------------------------------------------------------- lifecycle
    def on_trial_add(self, controller, trial) -> None:
        if not self._brackets or self._brackets[-1].full:
            rungs, quota = self._templates[
                self._next_template % len(self._templates)]
            self._brackets.append(_SyncBracket(list(rungs), quota,
                                               self.eta))
            self._next_template += 1
        bracket = self._brackets[-1]
        bracket.trial_ids.append(trial.trial_id)
        self._trial_bracket[trial.trial_id] = bracket

    def _cut_if_ready(self, controller, bracket,
                      reporting_trial=None) -> str:
        """When every live bracket member reached the milestone, close the
        rung: early-stop the laggards, release the survivors."""
        if not bracket.all_reported():
            return TrialScheduler.PAUSE
        stop_ids = set(bracket.cut())
        # Final rung closed (no further milestone): the bracket's budget is
        # spent, so survivors finish now instead of training one extra
        # iteration past max_t before the milestone-is-None check catches
        # them on their next report.
        survivors_done = set(bracket.live()) if bracket.milestone is None \
            else set()
        for other in controller.live_trials():
            if other is reporting_trial:
                continue
            if other.trial_id in stop_ids:
                controller._complete_trial(  # noqa: SLF001
                    other, other.last_result, early_stopped=True)
            elif other.trial_id in survivors_done:
                controller._complete_trial(  # noqa: SLF001
                    other, other.last_result, early_stopped=False)
        if reporting_trial is not None and \
                reporting_trial.trial_id in (stop_ids | survivors_done):
            return TrialScheduler.STOP
        return TrialScheduler.CONTINUE

    def on_trial_result(self, controller, trial, result: Dict) -> str:
        bracket = self._trial_bracket.get(trial.trial_id)
        if bracket is None:
            return TrialScheduler.CONTINUE
        t = result.get(self.time_attr, 0)
        milestone = bracket.milestone
        if milestone is None:
            # past the last rung: the bracket's budget is spent at max_t
            return (TrialScheduler.STOP if t >= self.max_t
                    else TrialScheduler.CONTINUE)
        if t < milestone:
            return TrialScheduler.CONTINUE
        bracket.scores[trial.trial_id] = self._score(result)
        bracket.waiting.add(trial.trial_id)
        return self._cut_if_ready(controller, bracket,
                                  reporting_trial=trial)

    def on_trial_complete(self, controller, trial, result: Dict) -> None:
        bracket = self._trial_bracket.get(trial.trial_id)
        if bracket:
            bracket.dropped.add(trial.trial_id)
            bracket.waiting.discard(trial.trial_id)
            # a finished/errored member must not deadlock the barrier
            if bracket.live():
                self._cut_if_ready(controller, bracket)

    def on_trial_error(self, controller, trial) -> None:
        self.on_trial_complete(controller, trial, trial.last_result or {})

    def debug_string(self) -> str:
        return (f"HyperBand: {len(self._brackets)} brackets, "
                f"eta={self.eta}, max_t={self.max_t}")


class HyperBandForBOHB(HyperBandScheduler):
    """BOHB's scheduler half (reference: schedulers/hb_bohb.py): the
    synchronized HyperBand bracket machinery, paired with the model-based
    ``TuneBOHB`` searcher that fills each bracket from a TPE fitted on the
    highest-fidelity observations. The bracket mechanics here already
    admit searcher-driven trials, so the subclass exists for API parity
    and as the documented BOHB entry point."""
