"""JaxConfig + JaxBackend — the north-star backend the reference lacks
(SURVEY §2.4 Train row: "a JaxTrainer is absent — the north star adds it as
a sibling of _TorchBackend calling jax.distributed.initialize";
reference structure: python/ray/train/torch/config.py:47-132).

Setup per worker:

1. Rank-0 publishes a coordinator address; every worker gets it plus its
   (process_id, num_processes) — the ``jax.distributed.initialize``
   rendezvous triple, mirroring the torch backend's TCP store rendezvous.
2. With ``use_jax_distributed=True`` (real multi-host TPU), workers call
   ``jax.distributed.initialize`` so the slice forms ONE global device mesh
   and all gradient traffic lowers to XLA collectives over ICI — no
   host-side allreduce exists at all.
3. Otherwise (CPU tests, single-host), each worker keeps its local devices
   and a host-level collective group ("train_default", DCN-analog) provides
   cross-worker psum for the DDP-style path.
"""

from __future__ import annotations

import dataclasses
from typing import Optional

from ray_tpu.train._internal.backend_executor import Backend, WorkerGroup


@dataclasses.dataclass
class JaxConfig:
    use_jax_distributed: bool = False
    collective_backend: str = "cpu"  # host-fallback group backend
    group_name: str = "train_default"
    # Platform overrides for the worker processes. On a real pod slice all
    # three stay None (the TPU runtime discovers its own topology); tests
    # form a genuine multi-process global mesh out of CPU devices the way
    # jax's own multiprocess CPU tests do: pin the platform, give each
    # process `num_local_devices` devices, and let gloo carry the
    # cross-process collectives.
    jax_platform: Optional[str] = None          # e.g. "cpu" in tests
    num_local_devices: Optional[int] = None     # devices per worker process
    cpu_collectives: Optional[str] = None       # e.g. "gloo"

    @property
    def backend_cls(self):
        return JaxBackend


def _setup_worker(rank: int, world_size: int, coordinator: str,
                  cfg_wire: dict) -> None:
    import os

    os.environ["RAY_TPU_TRAIN_RANK"] = str(rank)
    os.environ["RAY_TPU_TRAIN_WORLD_SIZE"] = str(world_size)
    os.environ["RAY_TPU_TRAIN_COORDINATOR"] = coordinator
    if cfg_wire["use_jax_distributed"]:
        import jax

        # Order matters: platform/device-count/collectives config must land
        # before the first backend touch, and a worker process recycled from
        # a previous group incarnation must drop its old coordination-service
        # connection before re-forming the mesh.
        if cfg_wire.get("jax_platform"):
            jax.config.update("jax_platforms", cfg_wire["jax_platform"])
        if cfg_wire.get("num_local_devices"):
            try:
                jax.config.update("jax_num_cpu_devices",
                                  cfg_wire["num_local_devices"])
            except AttributeError:
                # older jax: the config option doesn't exist yet — the
                # XLA flag does the same thing if it lands before the
                # first backend touch (we are before it by construction).
                # XLA's parser honors the FIRST occurrence, so an
                # inherited setting (e.g. the test harness's =8) must be
                # stripped, not shadowed.
                from ray_tpu._private.xla_flags import normalize_xla_flags

                kept = [f for f in os.environ.get("XLA_FLAGS", "").split()
                        if not f.startswith(
                            "--xla_force_host_platform_device_count")]
                kept.append("--xla_force_host_platform_device_count="
                            f"{cfg_wire['num_local_devices']}")
                # normalize: a bare token (e.g. intra_op_parallelism_
                # threads=1) left LEADING reads as a flags-file name and
                # FATALs the worker (parse_flags_from_env.cc:169)
                os.environ["XLA_FLAGS"] = normalize_xla_flags(" ".join(kept))
        if cfg_wire.get("cpu_collectives"):
            jax.config.update("jax_cpu_collectives_implementation",
                              cfg_wire["cpu_collectives"])
        from jax._src import distributed as _jax_dist

        if _jax_dist.global_state.client is not None:
            jax.distributed.shutdown()
        # Bounded rendezvous (the rc-124 hang class): a peer dying between
        # actor creation and its initialize() call used to park everyone
        # else on the coordination-service barrier forever. The timeout
        # turns that into a typed, retryable failure.
        jax.distributed.initialize(
            coordinator_address=coordinator,
            num_processes=world_size,
            process_id=rank,
            initialization_timeout=int(
                cfg_wire.get("rendezvous_timeout_s") or 300),
        )
        expected = cfg_wire.get("num_local_devices")
        if expected and jax.local_device_count() != expected:
            raise RuntimeError(
                f"worker {rank}: wanted {expected} local devices, got "
                f"{jax.local_device_count()} — platform config landed too "
                "late (backend already initialized in this process)")
    if world_size > 1:
        from ray_tpu.util import collective as col

        col.init_collective_group(
            world_size, rank, backend=cfg_wire["collective_backend"],
            group_name=cfg_wire["group_name"],
            store_key=cfg_wire["store_key"])


class JaxBackend(Backend):
    def __init__(self):
        self._store_key: Optional[str] = None

    def on_start(self, worker_group: WorkerGroup, backend_config: JaxConfig):
        """Form the collective group with a bounded, retrying rendezvous.

        Two historical failure classes die here: (1) the free-port race —
        the port rank-0 probed can be rebound by another process before
        ``jax.distributed.initialize`` binds it, so each attempt probes a
        FRESH port instead of failing the whole start; (2) the rc-124
        hang — a peer dying mid-rendezvous parked everyone on the
        coordination barrier forever, so every attempt is bounded by
        ``train_rendezvous_timeout_s`` and peer death surfaces as a typed
        (restartable) :class:`TrainingWorkerError`. Attempts pace with
        decorrelated jitter; exhaustion raises
        :class:`TrainRendezvousError`.
        """
        import time as _time
        import uuid

        import ray_tpu
        from ray_tpu._private.async_util import DecorrelatedJitterBackoff
        from ray_tpu._private.config import CONFIG
        from ray_tpu.exceptions import (
            ActorUnavailableError, GetTimeoutError, NodeDiedError,
            RayActorError, TrainingWorkerError, TrainRendezvousError,
            WorkerCrashedError)
        from ray_tpu.train._internal.util import find_free_port

        metas = worker_group.node_metas()
        timeout_s = float(CONFIG.train_rendezvous_timeout_s)
        attempts = max(1, int(CONFIG.train_rendezvous_max_retries))
        backoff = DecorrelatedJitterBackoff(base_s=0.2, cap_s=2.0)
        coordinator = ""
        last: Optional[BaseException] = None
        for attempt in range(1, attempts + 1):
            port = worker_group.execute_single(0, find_free_port)
            coordinator = f"{metas[0]['hostname']}:{port}"
            cfg_wire = {
                "use_jax_distributed": backend_config.use_jax_distributed,
                "collective_backend": backend_config.collective_backend,
                "group_name": backend_config.group_name,
                "jax_platform": backend_config.jax_platform,
                "num_local_devices": backend_config.num_local_devices,
                "cpu_collectives": backend_config.cpu_collectives,
                "rendezvous_timeout_s": timeout_s,
                # per-incarnation store: a restarted group must not inherit
                # a dead predecessor's staged contributions
                "store_key":
                    f"{backend_config.group_name}:{uuid.uuid4().hex[:8]}",
            }
            self._store_key = cfg_wire["store_key"]
            try:
                ray_tpu.get([
                    w.execute.remote(_setup_worker, i, len(worker_group),
                                     coordinator, cfg_wire)
                    for i, w in enumerate(worker_group.workers)
                ], timeout=timeout_s + 30.0)
                return
            except (RayActorError, ActorUnavailableError, WorkerCrashedError,
                    NodeDiedError) as e:
                # a peer died mid-rendezvous: no point retrying at this
                # world size — hand the typed error to the recovery loop
                ctx = getattr(e, "context", None)
                self._cleanup_partial(worker_group,
                                      backend_config.group_name)
                raise TrainingWorkerError(
                    node_id=getattr(ctx, "node_id", ""),
                    incarnation=getattr(ctx, "incarnation", 0),
                    reason="peer died during rendezvous",
                    timeline=getattr(ctx, "timeline", None)) from e
            except GetTimeoutError as e:
                last = e
                self._cleanup_partial(worker_group,
                                      backend_config.group_name)
            except Exception as e:  # bind race, stale client, task error
                last = e
                self._cleanup_partial(worker_group,
                                      backend_config.group_name)
            if attempt < attempts:
                _time.sleep(backoff.next_delay())
        raise TrainRendezvousError(
            coordinator=coordinator, attempts=attempts,
            reason=str(last)[:300] if last else "unknown") from last

    def _cleanup_partial(self, worker_group: WorkerGroup,
                         group_name: str = "train_default") -> None:
        """Best-effort teardown of a half-formed incarnation so the next
        attempt starts clean: drop worker-side jax clients / group state,
        kill the staging store actor (unblocks peers parked on it)."""
        def reset(group_name: str):
            try:
                from ray_tpu.util import collective as col

                col.destroy_collective_group(group_name)
            except Exception:
                pass
            try:
                from jax._src import distributed as _jax_dist

                if _jax_dist.global_state.client is not None:
                    import jax

                    jax.distributed.shutdown()
            except Exception:
                pass

        import ray_tpu

        if self._store_key:
            try:
                ray_tpu.kill(ray_tpu.get_actor(
                    f"_collective_store:{self._store_key}"))
            except Exception:
                pass
        try:
            ray_tpu.get(
                [w.execute.remote(reset, group_name)
                 for w in worker_group.workers],
                timeout=10.0)
        except Exception:
            pass

    def on_shutdown(self, worker_group: WorkerGroup, backend_config: JaxConfig):
        def teardown(group_name: str):
            try:
                from ray_tpu.util import collective as col

                col.destroy_collective_group(group_name)
            except Exception:
                pass
            try:
                from jax._src import distributed as _jax_dist

                if _jax_dist.global_state.client is not None:
                    import jax

                    jax.distributed.shutdown()
            except Exception:
                pass

        import ray_tpu as _ray

        try:
            # BOUNDED: a worker wedged in a collective with a dead peer
            # only unblocks at jax's coordination heartbeat timeout
            # (~100s); waiting for it delays the elastic restart past the
            # next incarnation's actor-creation deadline. The group is
            # being torn down anyway — force-kill is the backstop.
            _ray.get([w.execute.remote(teardown, backend_config.group_name)
                      for w in worker_group.workers], timeout=10.0)
        except Exception:
            pass
        # Driver-side backstop: dead workers can't deregister, which would
        # strand the detached store actor of this incarnation forever.
        if self._store_key:
            import ray_tpu

            try:
                ray_tpu.kill(
                    ray_tpu.get_actor(f"_collective_store:{self._store_key}"))
            except Exception:
                pass
