from ray_tpu.rllib.algorithms.impala.impala import IMPALA, IMPALAConfig

__all__ = ["IMPALA", "IMPALAConfig"]
