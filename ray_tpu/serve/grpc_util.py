"""gRPC ingress (reference: python/ray/serve/grpc_util.py + the gRPC
server inside _private/proxy.py ProxyActor — requests routed to
applications by the ``application`` invocation metadata key).

The reference serves user-generated protobuf servicers; here the ingress
is a generic byte service so no generated stubs are needed:

- method: ``/ray_tpu.serve.ServeAPIService/Predict`` (unary-unary, raw
  bytes in/out)
- metadata: ``application`` (required) — the target app;
  ``multiplexed_model_id`` (optional) — forwarded to the handle
- request bytes are cloudpickle-deserialized and passed to the ingress
  deployment's ``__call__``; the return value is cloudpickle'd back

``ServeGrpcClient`` wraps the channel plumbing for Python callers.
"""

from __future__ import annotations

from typing import Any, Optional

HEALTH_METHOD = "/ray_tpu.serve.ServeAPIService/Healthz"
PREDICT_METHOD = "/ray_tpu.serve.ServeAPIService/Predict"


def make_generic_handler(get_handle, list_routes):
    """A grpc GenericRpcHandler serving Predict/Healthz without generated
    stubs (raw-bytes serializers)."""
    import cloudpickle
    import grpc

    async def predict(request: bytes, context) -> bytes:
        md = dict(context.invocation_metadata())
        app = md.get("application")
        if not app:
            await context.abort(grpc.StatusCode.INVALID_ARGUMENT,
                                "missing 'application' metadata")
        routes = list_routes()
        target = None
        for prefix, (app_name, ingress) in routes.items():
            if app_name == app:
                target = (app_name, ingress)
                break
        if target is None:
            await context.abort(grpc.StatusCode.NOT_FOUND,
                                f"no application {app!r}")
        payload = cloudpickle.loads(request) if request else None
        handle = get_handle(*target)
        model_id = md.get("multiplexed_model_id")
        if model_id:
            handle = handle.options(multiplexed_model_id=model_id)
        import asyncio

        # honor the client's RPC deadline instead of a fixed 60s so a
        # timed-out call doesn't pin a to_thread worker afterwards
        remaining = context.time_remaining()
        timeout_s = remaining if remaining is not None else 60.0
        response = handle.remote(payload)
        try:
            result = await asyncio.to_thread(
                response.result, max(0.1, timeout_s))
        except TimeoutError:
            await context.abort(grpc.StatusCode.DEADLINE_EXCEEDED,
                                "backend timed out")
        return cloudpickle.dumps(result)

    async def healthz(request: bytes, context) -> bytes:
        return b"success"

    class _Handler(grpc.GenericRpcHandler):
        def service(self, call_details):
            if call_details.method == PREDICT_METHOD:
                return grpc.unary_unary_rpc_method_handler(predict)
            if call_details.method == HEALTH_METHOD:
                return grpc.unary_unary_rpc_method_handler(healthz)
            return None

    return _Handler()


class ServeGrpcClient:
    """Convenience client for the generic gRPC ingress."""

    def __init__(self, address: str):
        import grpc

        self._channel = grpc.insecure_channel(address)
        self._predict = self._channel.unary_unary(PREDICT_METHOD)
        self._healthz = self._channel.unary_unary(HEALTH_METHOD)

    def predict(self, application: str, payload: Any,
                multiplexed_model_id: Optional[str] = None,
                timeout: float = 60.0) -> Any:
        import cloudpickle

        md = [("application", application)]
        if multiplexed_model_id:
            md.append(("multiplexed_model_id", multiplexed_model_id))
        out = self._predict(cloudpickle.dumps(payload), metadata=md,
                            timeout=timeout)
        return cloudpickle.loads(out)

    def healthz(self, timeout: float = 10.0) -> bool:
        return self._healthz(b"", timeout=timeout) == b"success"

    def close(self) -> None:
        self._channel.close()
