"""R1 — plain ``threading.Lock`` reachable from GC context.

Invariant: any critical section reachable from ``__del__`` or a weakref
callback must use ``RLock`` (or a documented GC-safe pattern), because
the garbage collector may run the destructor on *any* thread at *any*
bytecode boundary — including while that same thread already holds the
lock.

Motivating bug (PR 5): ``MemoryStore`` used a plain ``Lock``;
``ObjectRef.__del__`` fired inside a GC pass while the owning thread was
inside ``MemoryStore.wait()``'s critical section, re-entered
``delete()`` via the reference counter, and deadlocked the whole driver
(three modules between the destructor and the lock — no single-file
review could see it).

Detection: fixpoint reachability over the project call graph from every
``__del__`` / ``weakref.ref|finalize`` callback; every reached function's
sync lock acquisitions are checked. The violation message carries the
call path so the reader can judge the chain.
"""

from __future__ import annotations

import ast
from typing import List

from ..callgraph import FunctionInfo, ProjectIndex
from ..model import Violation

RULE_ID = "R1"
SUMMARY = ("threading.Lock (non-reentrant) acquired in code reachable "
           "from __del__/weakref callbacks — GC re-entry deadlocks; "
           "use RLock or a GC-safe pattern")


def check(index: ProjectIndex) -> List[Violation]:
    roots: List[FunctionInfo] = []
    for mod in index.modules:
        for node in ast.walk(mod.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                    and node.name == "__del__":
                qn = mod.qualname(node)
                cls = qn.split(".")[0] if "." in qn else None
                roots.append(FunctionInfo(node.name, qn, mod, node,
                                          class_name=cls))
    for expr, mod in index.weakref_callbacks:
        roots.extend(index.function_for_expr(expr, mod))
    if not roots:
        return []
    reached = index.reachable(roots)
    out: List[Violation] = []
    seen_sites = set()
    for ref, (fn, path) in reached.items():
        for site in index.lock_sites(fn):
            if site.kind != "Lock":
                continue
            site_key = (fn.module.relpath, site.node.lineno, site.name)
            if site_key in seen_sites:
                continue
            seen_sites.add(site_key)
            chain = " -> ".join(p.split("::")[-1] for p in path)
            out.append(fn.module.violation(
                RULE_ID, site.node,
                f"plain threading.Lock '{site.name}' is acquired in "
                f"'{fn.qualname}', which is reachable from GC context "
                f"via {chain}; a destructor firing on the owning thread "
                f"mid-critical-section deadlocks — use RLock or defer "
                f"the GC-path work off-lock"))
    return out
