"""BackendExecutor: drives the worker group through backend setup and the
user train loop (reference: python/ray/train/_internal/backend_executor.py —
start :124 → Backend.on_start :190, start_training :438,
get_next_results :552)."""

from __future__ import annotations

from collections import defaultdict
from typing import Any, Callable, Dict, List, Optional

from ray_tpu.train._internal.session import TrainingResult
from ray_tpu.train._internal.worker_group import WorkerGroup


class TrainingWorkerError(RuntimeError):
    pass


class Backend:
    """Framework plugin ABC (reference: train/backend.py:27)."""

    def on_start(self, worker_group: WorkerGroup, backend_config) -> None:
        pass

    def on_training_start(self, worker_group: WorkerGroup, backend_config) -> None:
        pass

    def on_shutdown(self, worker_group: WorkerGroup, backend_config) -> None:
        pass


class BackendExecutor:
    def __init__(self, backend_config, num_workers: int,
                 resources_per_worker: Dict[str, float],
                 placement_group=None):
        self._backend_config = backend_config
        self._backend: Backend = backend_config.backend_cls()
        self._num_workers = num_workers
        self._resources = resources_per_worker
        self._pg = placement_group
        self.worker_group: Optional[WorkerGroup] = None
        self._ranks: List[Dict] = []
        self._done_workers: set = set()

    def start(self) -> None:
        self.worker_group = WorkerGroup(
            self._num_workers, self._resources, self._pg)
        metas = self.worker_group.node_metas()
        # rank assignment: stable by (node, order) — local ranks group by node
        per_node: Dict[str, int] = defaultdict(int)
        node_order: Dict[str, int] = {}
        self._ranks = []
        for world_rank, meta in enumerate(metas):
            node = meta["node_id"]
            if node not in node_order:
                node_order[node] = len(node_order)
            self._ranks.append({
                "world_rank": world_rank,
                "local_rank": per_node[node],
                "node_rank": node_order[node],
                "node_id": node,
            })
            per_node[node] += 1
        for r in self._ranks:
            r["local_world_size"] = per_node[r["node_id"]]
        self._backend.on_start(self.worker_group, self._backend_config)

    @property
    def ranks(self) -> List[Dict]:
        return self._ranks

    def start_training(
        self,
        train_fn: Callable,
        config: Dict,
        experiment_name: str,
        storage_path: str,
        trial_dir: str,
        checkpoint_path: Optional[str] = None,
        dataset_shards: Optional[List[Dict[str, Any]]] = None,
    ) -> None:
        from ray_tpu._private import serialization as ser

        import ray_tpu

        blob = ser.dumps(train_fn)
        inits = []
        for i, (w, r) in enumerate(zip(self.worker_group.workers, self._ranks)):
            shards = dataset_shards[i] if dataset_shards else {}
            inits.append(w.init_train_session.remote(
                world_rank=r["world_rank"],
                world_size=self._num_workers,
                local_rank=r["local_rank"],
                local_world_size=r["local_world_size"],
                node_rank=r["node_rank"],
                experiment_name=experiment_name,
                storage_path=storage_path,
                trial_dir=trial_dir,
                config=config,
                checkpoint_path=checkpoint_path,
                dataset_shards=shards,
            ))
        ray_tpu.get(inits)
        self._done_workers = set()
        self._backend.on_training_start(self.worker_group, self._backend_config)
        ray_tpu.get([w.start_training.remote(blob)
                     for w in self.worker_group.workers])

    def get_next_results(self, timeout: float = 3600.0) -> Optional[List[TrainingResult]]:
        """One result from every still-running worker — a sync barrier per
        report round. Returns None once all workers are DONE. Workers that
        already returned DONE are not re-polled (their queues are empty;
        uneven report counts across ranks must not wedge the round)."""
        import ray_tpu

        live = [i for i in range(len(self.worker_group.workers))
                if i not in self._done_workers]
        if not live:
            return None
        wire = ray_tpu.get(
            [self.worker_group.workers[i].get_next.remote(timeout)
             for i in live],
            timeout=timeout)
        results = [TrainingResult.from_wire(d) for d in wire]
        for i, r in zip(live, results):
            r.world_rank = self._ranks[i]["world_rank"]
        errors = [r for r in results if r.kind == TrainingResult.ERROR]
        if errors:
            raise TrainingWorkerError(errors[0].error)
        for i, r in zip(live, results):
            if r.kind == TrainingResult.DONE:
                self._done_workers.add(i)
        reports = [r for r in results if r.kind == TrainingResult.REPORT]
        if not reports and len(self._done_workers) == len(self.worker_group.workers):
            return None
        return reports or None

    def shutdown(self) -> None:
        if self.worker_group is not None:
            try:
                self._backend.on_shutdown(self.worker_group, self._backend_config)
            except Exception:
                pass
            self.worker_group.shutdown()
            self.worker_group = None
