"""Connector pipelines (reference: rllib/connectors/ tests — obs/action
transforms between env and module)."""

import numpy as np
import pytest

import ray_tpu
from ray_tpu.rllib.connectors import (
    ActionClip, ConnectorPipeline, FlattenObs, FrameStack, NormalizeObs)


@pytest.fixture(scope="module")
def ray2():
    if not ray_tpu.is_initialized():
        ray_tpu.init(num_cpus=2)
    yield
    ray_tpu.shutdown()


def test_normalize_obs_converges_to_unit_scale():
    c = NormalizeObs()
    rng = np.random.default_rng(0)
    out = None
    for _ in range(50):
        out = c.on_obs(rng.normal(5.0, 3.0, size=(32, 4)))
    assert abs(float(out.mean())) < 0.5
    assert 0.5 < float(out.std()) < 2.0
    # state round-trip
    c2 = NormalizeObs()
    c2.set_state(c.state())
    x = rng.normal(5.0, 3.0, size=(8, 4))
    np.testing.assert_allclose(c.on_obs(x), c2.on_obs(x), rtol=1e-3)


def test_frame_stack_widens_features():
    c = FrameStack(k=3)
    c.on_episode_start()
    o1 = c.on_obs(np.ones((2, 4)))
    assert o1.shape == (2, 12)
    assert (o1[:, :8] == 0).all()  # zero-padded history
    c.on_obs(2 * np.ones((2, 4)))
    o3 = c.on_obs(3 * np.ones((2, 4)))
    assert (o3[:, :4] == 1).all() and (o3[:, 8:] == 3).all()


def test_pipeline_order_and_action_reverse():
    calls = []

    class A(ActionClip):
        def on_action(self, action):
            calls.append("A")
            return super().on_action(action)

    class B(ActionClip):
        def on_action(self, action):
            calls.append("B")
            return super().on_action(action)

    pipe = ConnectorPipeline([A(), B()])
    pipe.on_action(np.asarray([2.5]))
    assert calls == ["B", "A"]  # reverse order on the action path
    assert pipe.obs_multiplier == 1
    assert ConnectorPipeline([FrameStack(4)]).obs_multiplier == 4
    flat = FlattenObs().on_obs(np.ones((2, 3, 5)))
    assert flat.shape == (2, 15)


def test_ppo_with_connector_pipeline_e2e(ray2):
    """PPO trains through a NormalizeObs+FrameStack pipeline; the module's
    obs_dim accounts for the stacking multiplier."""
    from ray_tpu.rllib import PPOConfig

    pipe = ConnectorPipeline([NormalizeObs(), FrameStack(k=2)])
    cfg = (PPOConfig()
           .environment("CartPole-v1")
           .env_runners(num_env_runners=1, num_envs_per_env_runner=4,
                        rollout_fragment_length=32, connector=pipe)
           .training(lr=1e-3, train_batch_size=128, minibatch_size=64,
                     num_epochs=2))
    spec = cfg.module_spec()
    assert spec.obs_dim == 8  # 4 features x 2 stacked frames
    algo = cfg.build()
    try:
        r = algo.step()
        assert np.isfinite(r["policy_loss"])
        assert r["env_steps_this_iter"] >= 128
    finally:
        algo.stop()
