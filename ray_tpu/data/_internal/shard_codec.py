"""Packed-shard codec: one Block <-> ONE contiguous uint8 ndarray.

The streaming shuffle (ISSUE 12) ships every map-output shard as a bare
contiguous array so the store serializes it on the ``ZeroCopyArray`` typed
fast path (``_private/serialization.py``): a single memcpy into the shm
segment on the producing node, and on the pulling node the reducer decodes
COLUMN VIEWS aliasing the store mmap — no pickle pass in either direction
and no intermediate copies of multi-MB shard payloads.

Wire layout (little-endian, payloads 64-byte aligned so decoded views
satisfy any dtype's alignment):

    [u32 magic 'RTSB'][u8 version][u32 header_len][header pickle]
    [pad to 64][col 0 payload][pad to 64][col 1 payload]...

The header is a plain-pickle list of column descriptors
``(name, kind, dtype_tag, shape, nbytes)``; payload offsets are NOT
stored — encoder and decoder walk the same deterministic
align-and-advance sequence. ``kind`` is ``"nd"`` for numeric columns
stored raw, or ``"pkl"`` for object-dtype / untaggable-dtype columns
stored as a pickle blob (strings survive, they just do not get the
zero-copy view). This module must stay importable without jax
(MULTICHIP gate: shuffle workers never touch the device runtime).
"""

from __future__ import annotations

import pickle
import struct
from typing import Dict, List, Tuple

import numpy as np

from ray_tpu.data.block import Block, BlockAccessor
from ray_tpu._private.serialization import _dtype_tag, _resolve_dtype

_MAGIC = 0x52545342  # 'RTSB'
_VERSION = 1
_ALIGN = 64
_PREFIX = "<IBI"  # magic, version, header_len
_PREFIX_LEN = struct.calcsize(_PREFIX)


def _align(n: int) -> int:
    return (n + _ALIGN - 1) & ~(_ALIGN - 1)


def encode_shard(block: Block) -> np.ndarray:
    """Pack ``block`` into one contiguous uint8 array (see module doc)."""
    nd = BlockAccessor(block).to_numpy_dict()
    cols: List[Tuple[str, str, str, tuple, int]] = []
    payloads: List[np.ndarray] = []
    for name, arr in nd.items():
        tag = None if arr.dtype.hasobject else _dtype_tag(arr.dtype)
        if tag is None:
            raw = np.frombuffer(
                pickle.dumps(arr, protocol=5), dtype=np.uint8)
            cols.append((name, "pkl", "", (), raw.nbytes))
        else:
            a = np.ascontiguousarray(arr)
            raw = (a.reshape(-1).view(np.uint8) if a.nbytes
                   else np.empty(0, np.uint8))
            cols.append((name, "nd", tag, a.shape, a.nbytes))
        payloads.append(raw)
    header = pickle.dumps(cols, protocol=4)
    off = _align(_PREFIX_LEN + len(header))
    total = off
    for raw in payloads:
        total = _align(total) + raw.nbytes
    out = np.zeros(max(total, off), dtype=np.uint8)
    struct.pack_into(_PREFIX, out, 0, _MAGIC, _VERSION, len(header))
    out[_PREFIX_LEN:_PREFIX_LEN + len(header)] = np.frombuffer(
        header, dtype=np.uint8)
    for raw in payloads:
        off = _align(off)
        out[off:off + raw.nbytes] = raw
        off += raw.nbytes
    return out


def is_packed_shard(arr) -> bool:
    if not isinstance(arr, np.ndarray) or arr.dtype != np.uint8 \
            or arr.ndim != 1 or arr.nbytes < _PREFIX_LEN:
        return False
    magic, version, _ = struct.unpack_from(_PREFIX, arr)
    return magic == _MAGIC and version == _VERSION


def decode_shard(arr: np.ndarray) -> Dict[str, np.ndarray]:
    """Unpack a packed shard into a tensor block (dict of columns).

    Numeric columns come back as VIEWS into ``arr`` — when ``arr`` is a
    zero-copy get() result they alias the store mmap directly (read-only,
    which is fine: every consumer copies on concat/permute). Object
    columns are unpickled.
    """
    if not is_packed_shard(arr):
        raise ValueError("not a packed shard (bad magic/version)")
    _, _, header_len = struct.unpack_from(_PREFIX, arr)
    cols = pickle.loads(
        arr[_PREFIX_LEN:_PREFIX_LEN + header_len].tobytes())
    out: Dict[str, np.ndarray] = {}
    off = _align(_PREFIX_LEN + header_len)
    for name, kind, tag, shape, nbytes in cols:
        off = _align(off)
        payload = arr[off:off + nbytes]
        off += nbytes
        if kind == "pkl":
            out[name] = pickle.loads(payload.tobytes())
        else:
            out[name] = payload.view(_resolve_dtype(tag)).reshape(shape)
    return out
