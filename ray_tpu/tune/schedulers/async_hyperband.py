"""ASHA — asynchronous successive halving (reference:
python/ray/tune/schedulers/async_hyperband.py:19 AsyncHyperBandScheduler;
bracket/rung logic mirrors its ``_Bracket.on_result`` cutoff rule)."""

from __future__ import annotations

import math
from typing import Dict, List, Optional

from ray_tpu.tune.schedulers.trial_scheduler import TrialScheduler


class _Rung:
    """One promotion rung: trials record their score when they reach
    ``milestone`` iterations; laggards below the top-1/rf quantile stop."""

    def __init__(self, milestone: float):
        self.milestone = milestone
        self.recorded: Dict[str, float] = {}

    def cutoff(self, reduction_factor: float) -> Optional[float]:
        if not self.recorded:
            return None
        import numpy as np

        # interpolated percentile, like the reference's nanpercentile-based
        # cutoff: survive only the top 1/rf fraction (NaN scores from
        # diverged trials must not poison the rung)
        return float(np.nanpercentile(
            list(self.recorded.values()),
            (1 - 1 / reduction_factor) * 100))


class _Bracket:
    def __init__(self, min_t: float, max_t: float, reduction_factor: float,
                 stop_last_trials: bool):
        self.rf = reduction_factor
        self.stop_last_trials = stop_last_trials
        self.rungs: List[_Rung] = []
        t = min_t
        while t < max_t:
            self.rungs.append(_Rung(t))
            t *= reduction_factor
        self.rungs.reverse()  # highest milestone first, like the reference

    def on_result(self, trial_id: str, cur_iter: float,
                  score: float) -> str:
        action = TrialScheduler.CONTINUE
        for rung in self.rungs:
            if cur_iter < rung.milestone or trial_id in rung.recorded:
                continue
            rung.recorded[trial_id] = score
            cutoff = rung.cutoff(self.rf)
            # strict <: a trial tying the cutoff (e.g. plateaued metrics)
            # is in the surviving fraction, like the reference
            if cutoff is not None and score < cutoff:
                action = TrialScheduler.STOP
            break
        return action


class AsyncHyperBandScheduler(TrialScheduler):
    def __init__(self, metric: Optional[str] = None,
                 mode: Optional[str] = None,
                 time_attr: str = "training_iteration",
                 max_t: float = 100, grace_period: float = 1,
                 reduction_factor: float = 4, brackets: int = 1,
                 stop_last_trials: bool = True):
        super().__init__(metric, mode)
        if grace_period < 1:
            raise ValueError("grace_period must be >= 1")
        self.time_attr = time_attr
        self.max_t = max_t
        self._brackets = [
            _Bracket(grace_period * reduction_factor ** s, max_t,
                     reduction_factor, stop_last_trials)
            for s in range(brackets)
        ]
        self._trial_bracket: Dict[str, _Bracket] = {}
        self._counter = 0

    def on_trial_add(self, controller, trial) -> None:
        # round-robin bracket assignment (reference randomizes by size;
        # round-robin is deterministic for tests)
        b = self._brackets[self._counter % len(self._brackets)]
        self._counter += 1
        self._trial_bracket[trial.trial_id] = b

    def on_trial_result(self, controller, trial, result: Dict) -> str:
        cur = result.get(self.time_attr, 0)
        if cur >= self.max_t:
            return TrialScheduler.STOP
        bracket = self._trial_bracket.get(trial.trial_id)
        if bracket is None:
            return TrialScheduler.CONTINUE
        return bracket.on_result(trial.trial_id, cur, self._score(result))

    def debug_string(self) -> str:
        sizes = [sum(len(r.recorded) for r in b.rungs) for b in self._brackets]
        return f"ASHA: bracket sizes {sizes}"


ASHAScheduler = AsyncHyperBandScheduler
