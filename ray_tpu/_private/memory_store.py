"""In-process memory store for small objects.

Parity with the reference's core-worker memory store (reference:
``src/ray/core_worker/store_provider/memory_store/memory_store.h``): small
task returns and errors skip shared memory entirely and resolve ``get``/
``wait`` directly in the owner process.
"""

from __future__ import annotations

import threading
import time
from typing import Any, Dict, List, Optional, Tuple


class _Entry:
    __slots__ = ("data", "is_exception")

    def __init__(self, data: bytes, is_exception: bool):
        self.data = data
        self.is_exception = is_exception


class MemoryStore:
    def __init__(self):
        self._lock = threading.Lock()
        self._objects: Dict[bytes, _Entry] = {}
        self._cv = threading.Condition(self._lock)

    def put(self, object_id: bytes, data: bytes, is_exception: bool = False) -> None:
        with self._cv:
            self._objects[object_id] = _Entry(data, is_exception)
            self._cv.notify_all()

    def contains(self, object_id: bytes) -> bool:
        with self._lock:
            return object_id in self._objects

    def get(self, object_id: bytes) -> Optional[Tuple[bytes, bool]]:
        with self._lock:
            e = self._objects.get(object_id)
            return (e.data, e.is_exception) if e else None

    def delete(self, object_id: bytes) -> None:
        with self._lock:
            self._objects.pop(object_id, None)

    def wait(
        self, object_ids: List[bytes], num_returns: int, timeout: Optional[float]
    ) -> Tuple[List[bytes], List[bytes]]:
        """Block until num_returns of object_ids are present (or timeout)."""
        deadline = None if timeout is None else time.monotonic() + timeout
        with self._cv:
            while True:
                ready = [oid for oid in object_ids if oid in self._objects]
                if len(ready) >= num_returns:
                    ready = ready[:num_returns]
                    remaining = [oid for oid in object_ids if oid not in set(ready)]
                    return ready, remaining
                if deadline is not None:
                    left = deadline - time.monotonic()
                    if left <= 0:
                        remaining = [oid for oid in object_ids if oid not in set(ready)]
                        return ready, remaining
                    self._cv.wait(left)
                else:
                    self._cv.wait()

    def size(self) -> int:
        with self._lock:
            return len(self._objects)
