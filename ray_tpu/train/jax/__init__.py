from ray_tpu.train.jax.config import JaxBackend, JaxConfig
from ray_tpu.train.jax.jax_trainer import JaxTrainer

__all__ = ["JaxBackend", "JaxConfig", "JaxTrainer"]
