"""Frozen R10 shape: the grow-only ledger in a long-lived service class.

The leak class behind several in-PR fixes (the agent demand ledger and
pool waiters of PR 11, the GCS task-event list of PR 13, the owned-table
resurrection ISSUE 15's ref-leak gate caught): a resident process keys a
dict by per-traffic ids (objects, tasks, workers) and nothing ever
prunes it, so memory grows with cumulative load, not live state.

Must keep tripping R10 exactly on the marked lines; the bounded and
pruned shapes below must stay clean.
"""

import asyncio


class LeakyAgentShape:
    """Service class (async while-loop marker) with three ledgers: one
    grow-only (flagged), one pruned (clean), one escaping (clean)."""

    def __init__(self):
        self._seen_objects = {}  # expect-R10: grown per seal, never pruned
        self._leases = {}        # pruned on release: clean
        self._delegated = []     # handed to a pruner: clean
        self._bounded = None     # reassigned wholesale: not an empty ctor

    async def _service_loop(self):
        while True:
            await asyncio.sleep(1)

    def on_sealed(self, hex_id, size):
        self._seen_objects[hex_id] = size

    def on_lease(self, lease_id, worker):
        self._leases[lease_id] = worker

    def on_release(self, lease_id):
        self._leases.pop(lease_id, None)

    def on_delegate(self, item, pruner):
        self._delegated.append(item)
        pruner(self._delegated)


class ShortLivedShape:
    """No service loop: a request-scoped object may accumulate freely."""

    def __init__(self):
        self._accumulator = {}

    def add(self, k, v):
        self._accumulator[k] = v


_MODULE_LEDGER = {}  # expect-R10: module-level, grown in a service module
_MODULE_PRUNED = {}


def note(key, value):
    _MODULE_LEDGER[key] = value


def note_pruned(key, value):
    _MODULE_PRUNED[key] = value
    if len(_MODULE_PRUNED) > 64:
        _MODULE_PRUNED.clear()
