"""LoRA-adapter llama generation on the continuous batching engine.

This is the serving shape the repo's ``models/`` path is meant to run at
production RPS (ROADMAP item 1; reference: Ray Serve LLM deployments —
multiplexed LoRA adapters over a shared base model, iteration-level
batching): one frozen base model per replica, per-request LoRA adapters
multiplexed by model id, greedy decode driven step-by-step by
:class:`~ray_tpu.serve._private.engine.ContinuousBatchingEngine` so
mixed-length generations share the compiled batch.

TPU notes: the per-step forward is jitted per (batch bucket, padded seq)
shape pair — the engine's ``allowed_batch_sizes`` snapping plus a seq-pad
bucket keep the compile-cache menu finite. Decoding here recomputes the
full prefix each step (tiny demo configs; a kv-cache paged-attention
variant slots into ``_step`` without touching the engine contract).

Usage::

    from ray_tpu.serve import llm
    app = llm.build_llama_app(config="debug_1l", adapters=("a1", "a2"))
    handle = serve.run(app, name="llama")
    toks = list(handle.options(stream=True).remote(
        {"prompt": [3, 5, 7], "max_new": 8, "adapter": "a1"}))
"""

from __future__ import annotations

import collections
import threading
import zlib
from typing import Any, Dict, List, Optional, Sequence

from ray_tpu.serve._private.engine import ContinuousBatchingEngine
from ray_tpu.serve.deployment import Application, Deployment


class LlamaGenerator:
    """Deployment callable: streaming greedy generation with multiplexed
    LoRA adapters, continuously batched."""

    def __init__(self, config: str = "tiny", lora_rank: int = 4,
                 max_batch_size: int = 4,
                 allowed_batch_sizes: Optional[Sequence[int]] = (1, 2, 4),
                 max_new_tokens: int = 16, seq_bucket: int = 32,
                 max_adapters: int = 4, seed: int = 0):
        import jax

        from ray_tpu.models.llama import (
            LlamaConfig, LoraConfig, init_llama)

        self._cfg = getattr(LlamaConfig, config)() \
            if isinstance(config, str) else config
        # adapt only the attention q/v projections: the cheap standard
        # LoRA target set, and enough for adapters to produce distinct
        # generations
        self._lcfg = LoraConfig(rank=lora_rank, targets=("wq", "wv"))
        self._params = init_llama(self._cfg, jax.random.PRNGKey(seed))
        self.max_new_tokens = max_new_tokens
        self.seq_bucket = max(8, int(seq_bucket))
        self._max_adapters = max_adapters
        self._adapters: "collections.OrderedDict[str, Any]" = \
            collections.OrderedDict()
        self._adapter_lock = threading.Lock()

        cfg, lcfg, params = self._cfg, self._lcfg, self._params

        def fwd(tokens, lora):
            from ray_tpu.models.llama import llama_forward

            return llama_forward(params, tokens, cfg,
                                 lora=lora, lora_cfg=lcfg)

        # one jit; the trace cache keys on (shape, adapter-pytree
        # structure), so base (lora=None) and adapted calls coexist
        self._fwd = jax.jit(fwd)
        self.engine = ContinuousBatchingEngine(
            self._step, prefill_fn=self._prefill,
            max_batch_size=max_batch_size,
            allowed_batch_sizes=allowed_batch_sizes,
            name="llama")

    # ------------------------------------------------------------- adapters
    def _adapter(self, model_id: str):
        """Deterministic per-id LoRA pytree, LRU-cached (the sync-path
        analog of ``@serve.multiplexed`` — loads happen in the stepper
        thread, so the cache is lock-guarded, not loop-bound)."""
        if not model_id:
            return None
        with self._adapter_lock:
            if model_id in self._adapters:
                self._adapters.move_to_end(model_id)
                return self._adapters[model_id]
        import jax

        from ray_tpu.models.llama import init_lora

        key = jax.random.PRNGKey(zlib.crc32(model_id.encode()) & 0x7FFFFFFF)
        lora = init_lora(self._cfg, self._lcfg, key)
        # B starts at 0 in real LoRA (adapted == base); nudge it so
        # distinct adapters actually generate distinct tokens in demos
        k2 = jax.random.split(key, 1)[0]
        lora["layers"] = {
            name: {"a": ab["a"],
                   "b": jax.random.normal(k2, ab["b"].shape,
                                          ab["b"].dtype) * 0.02}
            for name, ab in lora["layers"].items()}
        with self._adapter_lock:
            self._adapters[model_id] = lora
            while len(self._adapters) > self._max_adapters:
                self._adapters.popitem(last=False)
        return lora

    # -------------------------------------------------------------- serving
    @staticmethod
    def _normalize(payload: Any) -> Dict[str, Any]:
        if isinstance(payload, dict):
            return payload
        return {"prompt": list(payload)}

    def _prefill(self, payload: Any, model_id: str) -> Dict[str, Any]:
        p = self._normalize(payload)
        prompt = [int(t) for t in p.get("prompt", [0])] or [0]
        vocab = self._cfg.vocab_size
        prompt = [t % vocab for t in prompt]
        return {
            "tokens": prompt,
            "prompt_len": len(prompt),
            "max_new": min(int(p.get("max_new", self.max_new_tokens)),
                           self.max_new_tokens),
        }

    def _step(self, model_id: str, states: List[Optional[Dict]]) -> List:
        """One decode iteration for one adapter group: pad the live rows
        to (bucket, seq_bucket-multiple), one jitted forward, greedy next
        token per row."""
        import jax.numpy as jnp
        import numpy as np

        live = [(i, s) for i, s in enumerate(states) if s is not None]
        bucket = len(states)
        max_len = max(len(s["tokens"]) for _, s in live)
        pad_len = -(-max_len // self.seq_bucket) * self.seq_bucket
        pad_len = min(pad_len, self._cfg.max_seq_len)
        tokens = np.zeros((bucket, pad_len), np.int32)
        for row, (_, s) in enumerate(live):
            ts = s["tokens"][-pad_len:]
            tokens[row, :len(ts)] = ts
        logits = self._fwd(jnp.asarray(tokens), self._adapter(model_id))
        logits = np.asarray(logits)
        results: List[Optional[tuple]] = [None] * len(states)
        for row, (idx, s) in enumerate(live):
            last = min(len(s["tokens"]), pad_len) - 1
            nxt = int(np.argmax(logits[row, last]))
            s["tokens"].append(nxt)
            done = len(s["tokens"]) - s["prompt_len"] >= s["max_new"]
            results[idx] = (nxt, done)
        return results

    def __call__(self, payload: Any):
        """Streaming endpoint: yields generated token ids one at a time
        (sync generator → the replica's streaming path relays each token
        as it is produced)."""
        from ray_tpu.serve.multiplex import get_multiplexed_model_id

        p = self._normalize(payload)
        model_id = get_multiplexed_model_id() or str(p.get("adapter", ""))
        yield from self.engine.submit(p, model_id)

    def engine_stats(self) -> Dict[str, int]:
        return self.engine.stats()


def build_llama_app(*, config: str = "tiny", lora_rank: int = 4,
                    max_batch_size: int = 4,
                    allowed_batch_sizes: Optional[Sequence[int]] = (1, 2, 4),
                    max_new_tokens: int = 16, seq_bucket: int = 32,
                    num_replicas: int = 1,
                    max_ongoing_requests: int = 16,
                    max_queued_requests: int = 32,
                    autoscaling_config: Optional[Dict] = None,
                    ray_actor_options: Optional[Dict] = None) -> Application:
    """Bind a continuously-batched LoRA llama generator deployment.

    ``max_ongoing_requests`` must exceed the engine batch width: each
    in-flight generation holds a replica admission slot while the engine
    multiplexes them onto the compiled batch.
    """
    dep = Deployment(
        LlamaGenerator, "LlamaGenerator",
        num_replicas=num_replicas,
        max_ongoing_requests=max(max_ongoing_requests, 2 * max_batch_size),
        max_queued_requests=max_queued_requests,
        autoscaling_config=autoscaling_config,
        ray_actor_options=ray_actor_options or {},
    )
    return dep.bind(config=config, lora_rank=lora_rank,
                    max_batch_size=max_batch_size,
                    allowed_batch_sizes=allowed_batch_sizes,
                    max_new_tokens=max_new_tokens, seq_bucket=seq_bucket)
