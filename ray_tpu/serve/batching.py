"""@serve.batch — dynamic request batching (reference:
python/ray/serve/batching.py _BatchQueue/@serve.batch).

TPU note: jitted models compile per input shape, so ``allowed_batch_sizes``
lets the queue dispatch only at XLA-friendly sizes (pad-to-bucket happens in
user code or via ``pad_batch``); this replaces GPU-style "whatever
accumulated" batching with compiled-shape bucketing (SURVEY §7 hard part 7).
"""

from __future__ import annotations

import asyncio
import functools
import weakref
from typing import Any, Callable, List, Optional, Sequence

from ray_tpu._private.async_util import hold_task

# Per-instance batch queues keyed by the OWNER ITSELF, weakly: an id(owner)
# key is never evicted, and a GC'd instance's id can be reused by a new
# object — which would silently feed two instances' requests into one stale
# batch queue. Module-level (NOT decorator-closure state) on purpose:
# deployment classes are cloudpickled to replicas, and a WeakKeyDictionary
# reachable from the wrapper (closure cell OR captured global — cloudpickle
# serializes both by value for a by-value-pickled function) is unpicklable.
# The wrapper only ever touches it through ``_queues_for``, which IS
# importable from this module and therefore pickles by reference.
# Values are {method qualname: _BatchQueue} so two @serve.batch methods on
# one instance keep separate queues.
_owner_queues: "weakref.WeakKeyDictionary" = weakref.WeakKeyDictionary()


def _queues_for(owner) -> dict:
    """The per-owner {method qualname: _BatchQueue} dict, created weakly on
    first use. Raises TypeError for non-weakrefable owners."""
    per_owner = _owner_queues.get(owner)
    if per_owner is None:
        per_owner = _owner_queues[owner] = {}
    return per_owner


class _BatchQueue:
    def __init__(self, fn: Callable, max_batch_size: int,
                 batch_wait_timeout_s: float,
                 allowed_batch_sizes: Optional[Sequence[int]]):
        self.fn = fn
        self.max_batch_size = max_batch_size
        self.batch_wait_timeout_s = batch_wait_timeout_s
        self.allowed = (sorted(allowed_batch_sizes)
                        if allowed_batch_sizes else None)
        if self.allowed and self.allowed[-1] < max_batch_size:
            self.max_batch_size = self.allowed[-1]
        self.queue: List = []  # (item, future)
        self._flush_task: Optional[asyncio.Task] = None

    def put(self, item: Any) -> "asyncio.Future":
        fut = asyncio.get_running_loop().create_future()
        self.queue.append((item, fut))
        if len(self.queue) >= self.max_batch_size:
            self._flush_now()
        elif self._flush_task is None:
            self._flush_task = asyncio.get_running_loop().create_task(
                self._flush_after_timeout())
        return fut

    def _take(self) -> List:
        n = min(len(self.queue), self.max_batch_size)
        if self.allowed:
            # largest allowed size <= n; otherwise smallest allowed (the
            # timeout path dispatches a short batch the model must pad)
            fitting = [a for a in self.allowed if a <= n]
            n = fitting[-1] if fitting else n
        batch, self.queue = self.queue[:n], self.queue[n:]
        return batch

    def _flush_now(self):
        if self._flush_task is not None:
            self._flush_task.cancel()
            self._flush_task = None
        batch = self._take()
        if batch:
            hold_task(asyncio.get_running_loop().create_task(
                self._run(batch)), "serve-batch-run")
        if self.queue:
            self._flush_task = asyncio.get_running_loop().create_task(
                self._flush_after_timeout())

    async def _flush_after_timeout(self):
        try:
            await asyncio.sleep(self.batch_wait_timeout_s)
        except asyncio.CancelledError:
            return
        self._flush_task = None
        self._flush_now()

    async def _run(self, batch: List):
        items = [i for i, _ in batch]
        futs = [f for _, f in batch]
        try:
            results = await self.fn(items)
            if len(results) != len(items):
                raise ValueError(
                    f"batched function returned {len(results)} results for "
                    f"{len(items)} inputs")
            for f, r in zip(futs, results):
                if not f.done():
                    f.set_result(r)
        except Exception as e:
            for f in futs:
                if not f.done():
                    f.set_exception(e)


def batch(_fn=None, *, max_batch_size: int = 8,
          batch_wait_timeout_s: float = 0.01,
          allowed_batch_sizes: Optional[Sequence[int]] = None):
    """Decorator for async methods taking a list of inputs."""

    def deco(fn):
        qkey = f"{fn.__module__}.{fn.__qualname__}"
        ATTR = f"__serve_batch_queue_{fn.__name__}__"

        @functools.wraps(fn)
        async def wrapper(*args):
            if len(args) == 2:  # bound method: (self, item)
                owner, item = args
            else:  # plain-function deployment: anchor on the wrapper
                (item,) = args
                owner = wrapper
            try:
                per_owner = _queues_for(owner)
            except TypeError:  # non-weakrefable owner (e.g. __slots__)
                q = getattr(owner, ATTR, None)
                if q is None:
                    # a strong bound partial is fine HERE: the queue
                    # lives on the owner itself, so their lifetimes match
                    q = _BatchQueue(functools.partial(fn, owner),
                                    max_batch_size, batch_wait_timeout_s,
                                    allowed_batch_sizes)
                    try:
                        setattr(owner, ATTR, q)
                    except (AttributeError, TypeError):
                        raise TypeError(
                            f"@serve.batch owner {type(owner).__name__} "
                            "is neither weak-referenceable nor "
                            "attribute-assignable; batching needs one "
                            "place to anchor its per-instance queue")
                return await q.put(item)
            q = per_owner.get(qkey)
            if q is None:
                if owner is wrapper:
                    call = fn
                else:
                    # bind the owner WEAKLY: the registry's value must not
                    # strongly reference its weak key, or the owner (and
                    # its queue) would live forever anyway
                    ref = weakref.ref(owner)

                    async def call(items, _ref=ref):
                        o = _ref()
                        if o is None:
                            raise RuntimeError(
                                "@serve.batch owner was garbage collected "
                                "with requests still queued")
                        return await fn(o, items)

                q = per_owner[qkey] = _BatchQueue(
                    call, max_batch_size, batch_wait_timeout_s,
                    allowed_batch_sizes)
            return await q.put(item)

        return wrapper

    if _fn is not None:
        return deco(_fn)
    return deco


def pad_batch(arrays, target: int, pad_value=0):
    """Pad a list of equal-shape numpy arrays to ``target`` rows — helper
    for allowed_batch_sizes bucketing on TPU."""
    import numpy as np

    n = len(arrays)
    if n >= target:
        return arrays
    pad = [np.full_like(arrays[0], pad_value)] * (target - n)
    return list(arrays) + pad
