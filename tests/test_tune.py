"""Tune tests (reference analog: python/ray/tune/tests/test_tune_*.py,
test_trial_scheduler.py, test_basic_variant.py)."""

import json
import os
import random
import tempfile

import pytest

import ray_tpu
from ray_tpu import tune
from ray_tpu.air.config import CheckpointConfig, FailureConfig, RunConfig
from ray_tpu.train import Checkpoint
from ray_tpu.tune import TuneConfig, Tuner
from ray_tpu.tune.schedulers import (
    ASHAScheduler, MedianStoppingRule, PopulationBasedTraining)
from ray_tpu.tune.search.basic_variant import generate_variants
from ray_tpu.tune.search.searcher import ConcurrencyLimiter, Searcher


@pytest.fixture(scope="module")
def ray4():
    if not ray_tpu.is_initialized():
        ray_tpu.init(num_cpus=4)
    yield
    ray_tpu.shutdown()


# --------------------------------------------------------------- unit tests
def test_variant_generation_grid_and_domains():
    space = {
        "lr": tune.grid_search([0.1, 0.01]),
        "wd": tune.loguniform(1e-5, 1e-2),
        "layers": tune.choice([2, 4]),
        "nested": {"units": tune.grid_search([8, 16])},
    }
    variants = generate_variants(space, num_samples=2, rng=random.Random(0))
    assert len(variants) == 8  # 2 grid x 2 grid x 2 samples
    for v in variants:
        assert v["lr"] in (0.1, 0.01)
        assert 1e-5 <= v["wd"] <= 1e-2
        assert v["layers"] in (2, 4)
        assert v["nested"]["units"] in (8, 16)


def test_sample_domains_deterministic():
    rng = random.Random(42)
    assert 1 <= tune.randint(1, 10).sample(rng) < 10
    v = tune.quniform(0, 1, 0.25).sample(rng)
    assert v in (0.0, 0.25, 0.5, 0.75, 1.0)
    assert tune.choice(["a"]).sample(rng) == "a"


def test_concurrency_limiter():
    class Seq(Searcher):
        def __init__(self):
            super().__init__()
            self.n = 0

        def suggest(self, trial_id):
            self.n += 1
            return {"i": self.n}

    lim = ConcurrencyLimiter(Seq(), max_concurrent=2)
    assert lim.suggest("a") == {"i": 1}
    assert lim.suggest("b") == {"i": 2}
    assert lim.suggest("c") is None
    lim.on_trial_complete("a")
    assert lim.suggest("c") == {"i": 3}


# ---------------------------------------------------------------- e2e sweeps
def test_function_trainable_sweep(ray4, tmp_path):
    def objective(config):
        for i in range(3):
            tune.report({"loss": config["x"] ** 2 + i * 0.01})

    tuner = Tuner(
        objective,
        param_space={"x": tune.grid_search([-2.0, -1.0, 0.0, 1.0])},
        tune_config=TuneConfig(metric="loss", mode="min"),
        run_config=RunConfig(name="sweep", storage_path=str(tmp_path)),
    )
    results = tuner.fit()
    assert len(results) == 4
    assert results.num_errors == 0
    best = results.get_best_result()
    assert best.metrics["loss"] == pytest.approx(0.02)
    # experiment state was persisted
    assert os.path.exists(
        os.path.join(tmp_path, "sweep", "experiment_state.json"))


def test_class_trainable(ray4, tmp_path):
    class MyTrainable(tune.Trainable):
        def setup(self, config):
            self.acc = 0.0

        def step(self):
            self.acc += self.config["rate"]
            return {"acc": self.acc, "done": self.acc >= 1.0}

        def save_checkpoint(self, d):
            with open(os.path.join(d, "state.json"), "w") as f:
                json.dump({"acc": self.acc}, f)

        def load_checkpoint(self, d):
            with open(os.path.join(d, "state.json")) as f:
                self.acc = json.load(f)["acc"]

    results = Tuner(
        MyTrainable,
        param_space={"rate": tune.grid_search([0.5, 0.25])},
        tune_config=TuneConfig(metric="acc", mode="max"),
        run_config=RunConfig(name="cls", storage_path=str(tmp_path)),
    ).fit()
    assert len(results) == 2
    assert results.num_errors == 0
    by_iters = sorted(r.metrics["training_iteration"] for r in results)
    assert by_iters == [2, 4]


def test_stop_criteria(ray4, tmp_path):
    def objective(config):
        for i in range(100):
            tune.report({"score": i})

    results = Tuner(
        objective,
        param_space={},
        run_config=RunConfig(name="stop", storage_path=str(tmp_path),
                             stop={"score": 5}),
    ).fit()
    assert results[0].metrics["score"] == 5


def test_asha_early_stops(ray4, tmp_path):
    def objective(config):
        import time as _time

        for i in range(20):
            # good trials report fast and record at rungs first, so the
            # laggards see a populated cutoff (ASHA is async: stop decisions
            # only fire once a rung has peers)
            _time.sleep(0.005 if config["q"] > 0.5 else 0.03)
            tune.report({"reward": config["q"] * (i + 1)})

    results = Tuner(
        objective,
        param_space={"q": tune.grid_search([0.1, 0.2, 1.0, 2.0])},
        tune_config=TuneConfig(
            metric="reward", mode="max", max_concurrent_trials=4,
            scheduler=ASHAScheduler(max_t=20, grace_period=2,
                                    reduction_factor=2)),
        run_config=RunConfig(name="asha", storage_path=str(tmp_path)),
    ).fit()
    iters = sorted(r.metrics["training_iteration"] for r in results)
    assert iters[0] < 20          # at least one trial stopped early
    assert iters[-1] == 20        # the best ran to max_t
    best = results.get_best_result()
    assert best.metrics["reward"] == pytest.approx(40.0)


def test_fault_tolerance_retries_from_checkpoint(ray4, tmp_path):
    marker = str(tmp_path / "fail_once")

    def objective(config):
        start = 0
        ckpt = tune.get_checkpoint()
        if ckpt is not None:
            with open(os.path.join(ckpt.path, "it.txt")) as f:
                start = int(f.read()) + 1
        for i in range(start, 6):
            if i == 3 and not os.path.exists(marker):
                open(marker, "w").close()
                raise RuntimeError("injected failure")
            with tempfile.TemporaryDirectory() as d:
                with open(os.path.join(d, "it.txt"), "w") as f:
                    f.write(str(i))
                tune.report({"i": i}, checkpoint=Checkpoint(d))

    results = Tuner(
        objective,
        param_space={},
        run_config=RunConfig(
            name="ft", storage_path=str(tmp_path),
            failure_config=FailureConfig(max_failures=2)),
    ).fit()
    assert results.num_errors == 0
    assert results[0].metrics["i"] == 5


def test_failed_trial_reports_error(ray4, tmp_path):
    def objective(config):
        raise ValueError("boom")

    results = Tuner(
        objective,
        param_space={},
        run_config=RunConfig(name="err", storage_path=str(tmp_path)),
    ).fit()
    assert results.num_errors == 1
    assert "boom" in str(results.errors[0])


def test_pbt_runs_and_perturbs(ray4, tmp_path):
    def objective(config):
        score = 0.0
        ckpt = tune.get_checkpoint()
        if ckpt is not None:
            with open(os.path.join(ckpt.path, "s.txt")) as f:
                score = float(f.read())
        for i in range(12):
            score += config["lr"]
            with tempfile.TemporaryDirectory() as d:
                with open(os.path.join(d, "s.txt"), "w") as f:
                    f.write(str(score))
                tune.report({"score": score}, checkpoint=Checkpoint(d))

    pbt = PopulationBasedTraining(
        time_attr="training_iteration", perturbation_interval=3,
        hyperparam_mutations={"lr": tune.uniform(0.1, 1.0)}, seed=7)
    results = Tuner(
        objective,
        param_space={"lr": tune.uniform(0.1, 1.0)},
        tune_config=TuneConfig(metric="score", mode="max", num_samples=4,
                               max_concurrent_trials=4, scheduler=pbt,
                               seed=3),
        run_config=RunConfig(name="pbt", storage_path=str(tmp_path)),
    ).fit()
    assert results.num_errors == 0
    assert len(results) == 4
    # every trial finished with a positive score
    assert all(r.metrics["score"] > 0 for r in results)


def test_median_stopping(ray4, tmp_path):
    def objective(config):
        for i in range(15):
            tune.report({"m": config["v"]})

    results = Tuner(
        objective,
        param_space={"v": tune.grid_search([1.0, 1.0, 1.0, 0.0])},
        tune_config=TuneConfig(
            metric="m", mode="max", max_concurrent_trials=4,
            scheduler=MedianStoppingRule(grace_period=3,
                                         min_samples_required=2)),
        run_config=RunConfig(name="med", storage_path=str(tmp_path)),
    ).fit()
    iters = [r.metrics["training_iteration"] for r in results]
    assert min(iters) < 15


def test_tuner_restore_resumes_unfinished(ray4, tmp_path):
    def objective(config):
        start = 0
        ckpt = tune.get_checkpoint()
        if ckpt is not None:
            with open(os.path.join(ckpt.path, "it.txt")) as f:
                start = int(f.read()) + 1
        for i in range(start, 4):
            with tempfile.TemporaryDirectory() as d:
                with open(os.path.join(d, "it.txt"), "w") as f:
                    f.write(str(i))
                tune.report({"i": i}, checkpoint=Checkpoint(d))

    exp_dir = str(tmp_path / "resume")
    results = Tuner(
        objective,
        param_space={"x": tune.grid_search([1, 2])},
        run_config=RunConfig(name="resume", storage_path=str(tmp_path)),
    ).fit()
    assert all(r.metrics["i"] == 3 for r in results)

    # simulate an interrupted run: mark one trial as mid-flight
    state_file = os.path.join(exp_dir, "experiment_state.json")
    with open(state_file) as f:
        state = json.load(f)
    state["trials"][0]["status"] = "RUNNING"
    with open(state_file, "w") as f:
        json.dump(state, f)

    assert Tuner.can_restore(exp_dir)
    results2 = Tuner.restore(exp_dir, objective).fit()
    assert len(results2) == 2
    assert all(r.metrics["i"] == 3 for r in results2)


def test_tune_wraps_trainer(ray4, tmp_path):
    from ray_tpu.train import JaxTrainer, ScalingConfig

    def loop(config):
        from ray_tpu import train

        val = 0.0
        for i in range(3):
            val += config["inc"]
            train.report({"val": val})

    trainer = JaxTrainer(
        loop,
        train_loop_config={"inc": 0.0},
        scaling_config=ScalingConfig(num_workers=1),
    )
    results = Tuner(
        trainer,
        param_space={"train_loop_config": {
            "inc": tune.grid_search([1.0, 2.0])}},
        tune_config=TuneConfig(metric="val", mode="max",
                               max_concurrent_trials=1),
        run_config=RunConfig(name="trainer_sweep",
                             storage_path=str(tmp_path)),
    ).fit()
    assert results.num_errors == 0
    best = results.get_best_result()
    assert best.metrics["val"] == pytest.approx(6.0)
