"""Trial model (reference: python/ray/tune/experiment/trial.py — status
machine PENDING/RUNNING/PAUSED/TERMINATED/ERROR)."""

from __future__ import annotations

import os
import uuid
from typing import Any, Dict, Optional


class Trial:
    PENDING = "PENDING"
    RUNNING = "RUNNING"
    PAUSED = "PAUSED"
    TERMINATED = "TERMINATED"
    ERROR = "ERROR"

    def __init__(self, config: Dict, experiment_dir: str,
                 trial_id: Optional[str] = None,
                 resources: Optional[Dict[str, float]] = None):
        self.trial_id = trial_id or uuid.uuid4().hex[:8]
        self.config = config
        self.resources = resources or {"CPU": 1.0}
        self.status = Trial.PENDING
        self.last_result: Dict[str, Any] = {}
        self.metric_history: list = []
        self.checkpoint_path: Optional[str] = None
        # set by PBT exploit / fault recovery: restore from here on (re)start
        self.restore_path: Optional[str] = None
        self.error_msg: Optional[str] = None
        self.num_failures = 0
        self.local_dir = os.path.join(experiment_dir, f"trial_{self.trial_id}")
        os.makedirs(self.local_dir, exist_ok=True)

    @property
    def is_finished(self) -> bool:
        return self.status in (Trial.TERMINATED, Trial.ERROR)

    def best_metric(self, metric: str, mode: str = "max") -> Optional[float]:
        vals = [r[metric] for r in self.metric_history if metric in r]
        if not vals:
            return None
        return max(vals) if mode == "max" else min(vals)

    def to_state(self) -> Dict:
        return {
            "trial_id": self.trial_id,
            "config": self.config,
            "resources": self.resources,
            "status": self.status,
            "last_result": self.last_result,
            "checkpoint_path": self.checkpoint_path,
            "error_msg": self.error_msg,
            "num_failures": self.num_failures,
        }

    @classmethod
    def from_state(cls, state: Dict, experiment_dir: str) -> "Trial":
        t = cls(state["config"], experiment_dir,
                trial_id=state["trial_id"], resources=state.get("resources"))
        t.status = state["status"]
        t.last_result = state.get("last_result", {})
        t.checkpoint_path = state.get("checkpoint_path")
        t.error_msg = state.get("error_msg")
        t.num_failures = state.get("num_failures", 0)
        # interrupted runs resume from their last checkpoint
        if t.status in (Trial.RUNNING, Trial.PENDING, Trial.PAUSED):
            t.restore_path = t.checkpoint_path
            t.status = Trial.PENDING
        return t

    def __repr__(self):
        return f"Trial({self.trial_id}, {self.status})"
