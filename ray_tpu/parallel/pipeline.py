"""Pipeline parallelism: GPipe-style microbatch schedule over a ``stage``
mesh axis.

The reference has no native pipeline parallelism — it defers to DeepSpeed
configs passed through Train (SURVEY §2.5: "PP via integrations only",
reference: python/ray/train/lightning/_lightning_utils.py:126). Here PP is a
first-class mesh axis: each device along ``stage`` holds one pipeline
stage's parameters, activations flow stage→stage over ICI with
``lax.ppermute``, and the whole schedule is a single ``lax.scan`` inside
``shard_map`` — one compiled SPMD program, no host round-trips between
microbatches.

Schedule: classic GPipe fill/drain. With S stages and M microbatches the
scan runs S+M-1 ticks; tick t has stage s working on microbatch t-s (idle
ticks compute on garbage and are masked out — on TPU a masked matmul costs
the same as control flow and keeps the program static). Bubble fraction is
(S-1)/(S+M-1); callers pick M >= 4*S to amortize.

Gradients flow through the same program via ``jax.grad`` — XLA reverses the
ppermute ring automatically, giving the backward pipeline for free.
"""

from __future__ import annotations

from functools import partial
from typing import Any, Callable, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

STAGE_AXIS = "stage"


def num_stages(mesh: Mesh, axis: str = STAGE_AXIS) -> int:
    if axis not in mesh.shape:
        raise ValueError(f"mesh {dict(mesh.shape)} has no '{axis}' axis")
    return mesh.shape[axis]


def init_stage_params(
    init_fn: Callable[[jax.Array], Any],
    n_stages: int,
    mesh: Mesh,
    *,
    axis: str = STAGE_AXIS,
    seed: int = 0,
) -> Any:
    """Initialize per-stage params stacked on a leading stage dim, sharded
    over the stage axis (each device materializes only its own stage)."""
    keys = jax.random.split(jax.random.key(seed), n_stages)

    def init_all(keys):
        return jax.vmap(init_fn)(keys)

    shardings = jax.tree.map(
        lambda s: NamedSharding(mesh, P(axis)),
        jax.eval_shape(init_all, keys))
    return jax.jit(init_all, out_shardings=shardings)(keys)


def stage_param_spec(params_stacked: Any, axis: str = STAGE_AXIS) -> Any:
    """in_specs pytree for stacked stage params: leading dim on ``axis``."""
    return jax.tree.map(lambda _: P(axis), params_stacked)


def pipeline_apply(
    stage_fn: Callable[[Any, jax.Array], jax.Array],
    stage_params: Any,
    x: jax.Array,
    mesh: Mesh,
    *,
    axis: str = STAGE_AXIS,
    data_axis: Optional[Sequence[str]] = ("data",),
    num_microbatches: Optional[int] = None,
) -> jax.Array:
    """Apply S pipeline stages to ``x`` with microbatch pipelining.

    Args:
      stage_fn: ``(params_for_one_stage, h) -> h`` with unchanged shape/dtype
        (the classic homogeneous-stage contract; embed/unembed live outside
        or inside stage_fn guarded by ``lax.cond`` on the stage index).
      stage_params: pytree stacked on a leading ``n_stages`` dim (see
        :func:`init_stage_params`).
      x: ``[batch, ...]`` activations. Split into ``num_microbatches`` equal
        microbatches on the leading dim.
      data_axis: mesh axes the batch dim is additionally sharded over
        (DP x PP meshes); None/() for pure PP.

    Returns ``[batch, ...]`` output, batch-sharded like the input.
    """
    S = num_stages(mesh, axis)
    data_axes = tuple(a for a in (data_axis or ()) if a in mesh.shape)
    data_size = 1
    for a in data_axes:
        data_size *= mesh.shape[a]

    def _valid(m: int) -> bool:
        return x.shape[0] % m == 0 and (x.shape[0] // m) % data_size == 0

    if num_microbatches is None:
        # Largest M <= 4*S that divides the batch and leaves each
        # microbatch divisible across the data axes.
        M = next((m for m in range(min(4 * S, x.shape[0]), 0, -1)
                  if _valid(m)), 1)
    else:
        M = num_microbatches
    if not _valid(M):
        raise ValueError(
            f"batch {x.shape[0]} not divisible into {M} microbatches "
            f"across data axes of size {data_size}")

    batch_spec = P(data_axes if data_axes else None)
    micro_spec = P(None, *batch_spec)  # [M, mb, ...]

    perm = [(i, (i + 1) % S) for i in range(S)]

    def staged(params_stk, xs):
        # Inside shard_map each device holds one stage: squeeze the
        # (sharded, now size-1) leading dim.
        params = jax.tree.map(lambda a: jnp.squeeze(a, 0), params_stk)
        stage = jax.lax.axis_index(axis)

        def tick(carry, t):
            act, outs = carry
            mb_in = jax.lax.dynamic_index_in_dim(
                xs, jnp.clip(t, 0, M - 1), axis=0, keepdims=False)
            h = jnp.where(stage == 0, mb_in, act)
            y = stage_fn(params, h)
            out_idx = jnp.clip(t - (S - 1), 0, M - 1)
            is_out = jnp.logical_and(stage == S - 1, t >= S - 1)
            cur = jax.lax.dynamic_index_in_dim(outs, out_idx, 0,
                                               keepdims=False)
            outs = jax.lax.dynamic_update_index_in_dim(
                outs, jnp.where(is_out, y, cur), out_idx, 0)
            act_next = jax.lax.ppermute(y, axis, perm)
            return (act_next, outs), None

        act0 = jnp.zeros_like(xs[0])
        outs0 = jnp.zeros_like(xs)
        (_, outs), _ = jax.lax.scan(
            tick, (act0, outs0), jnp.arange(S + M - 1))
        # Only the last stage holds real outputs; psum replicates them
        # across the stage ring (activation-sized, rides ICI once).
        outs = jax.lax.psum(
            jnp.where(stage == S - 1, outs, jnp.zeros_like(outs)), axis)
        return outs

    from ray_tpu.parallel.sharding import compat_shard_map

    shard = compat_shard_map(
        staged, mesh=mesh,
        in_specs=(stage_param_spec(stage_params, axis), micro_spec),
        out_specs=micro_spec,
        check_vma=False,
    )

    mb = x.shape[0] // M
    xs = x.reshape((M, mb) + x.shape[1:])
    ys = shard(stage_params, xs)
    return ys.reshape(x.shape[0:1] + ys.shape[2:])


def make_pipeline_train_step(
    stage_fn: Callable[[Any, jax.Array], jax.Array],
    loss_fn: Callable[[jax.Array, Any], jax.Array],
    tx,
    mesh: Mesh,
    stage_params: Any,
    *,
    axis: str = STAGE_AXIS,
    data_axis: Optional[Sequence[str]] = ("data",),
    num_microbatches: Optional[int] = None,
):
    """Jitted ``step((params, opt_state), (x, target)) -> ((params, opt),
    metrics)`` where the forward is the microbatch pipeline and the backward
    is its transpose (XLA reverses the ppermute ring).

    loss_fn: ``(pipeline_output, target) -> scalar``.
    """
    import optax

    def total_loss(params, x, target):
        y = pipeline_apply(stage_fn, params, x, mesh, axis=axis,
                           data_axis=data_axis,
                           num_microbatches=num_microbatches)
        return loss_fn(y, target)

    def step(carry, batch):
        params, opt_state = carry
        x, target = batch
        loss, grads = jax.value_and_grad(total_loss)(params, x, target)
        updates, opt_state = tx.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        return (params, opt_state), {"loss": loss}

    return jax.jit(step, donate_argnums=(0,))
