"""R9 regression fixture: store views escaping without a pin (ISSUE 9).

The bug class the device object plane's zero-copy path makes possible:
``get_view`` / ``read_maybe_spilled`` hand out memoryviews aliasing
store memory. Local use inside one call is safe (the view dies before
the store can move the object); an ESCAPING view — returned to a
caller, parked on ``self``, or captured by a closure handed to the
event loop — outlives the frame and can alias an evicted or spilled
segment unless the object is pinned for the view's lifetime.

R9 must flag the three escape shapes below and must NOT flag the
pinned twins (the shipped ``Worker._pin_escaping_view`` discipline) or
the local-use-only reader.
"""

import asyncio


class UnpinnedEscapes:
    """The bug: views leave the function, nothing pins the object."""

    def __init__(self, store, loop):
        self.store = store
        self.loop = loop
        self._cached = None

    def read(self, oid):
        view = self.store.get_view(oid)
        return view  # expect-R9

    def cache(self, oid):
        self._cached = self.store.read_maybe_spilled(oid)  # expect-R9

    def serve_later(self, oid):
        view = self.store.get_view(oid)

        async def reply():  # expect-R9
            await asyncio.sleep(0)
            return bytes(view)

        self._task = self.loop.create_task(reply())


class PinnedEscapes:
    """The fix: a pin in scope covers the view's lifetime."""

    def __init__(self, store):
        self.store = store
        self._cached = None

    def read(self, oid):
        self.store.pin(oid.hex())
        view = self.store.get_view(oid)
        return view

    def cache(self, oid):
        self._pin_for_cache(oid)
        self._cached = self.store.get_view(oid)

    def _pin_for_cache(self, oid):
        self.store.pin(oid.hex())


class LocalUseOnly:
    """No escape: the view dies inside the call — no pin needed."""

    def __init__(self, store):
        self.store = store

    def size_of(self, oid):
        view = self.store.get_view(oid)
        if view is None:
            return 0
        return len(view)
