"""Kernel correctness: flash attention (interpret mode) and ring attention
against the XLA reference path, on the 8-device virtual CPU mesh."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from ray_tpu.ops.attention import reference_attention


def _rand_qkv(key, B=2, S=256, H=4, KVH=2, D=64, dtype=jnp.float32):
    kq, kk, kv = jax.random.split(key, 3)
    q = jax.random.normal(kq, (B, S, H, D), dtype)
    k = jax.random.normal(kk, (B, S, KVH, D), dtype)
    v = jax.random.normal(kv, (B, S, KVH, D), dtype)
    return q, k, v


class TestFlashAttention:
    @pytest.mark.parametrize("causal", [True, False])
    def test_matches_reference(self, causal):
        from ray_tpu.ops.pallas.flash_attention import flash_attention

        q, k, v = _rand_qkv(jax.random.key(0))
        out = flash_attention(q, k, v, causal)
        ref = reference_attention(q, k, v, causal=causal)
        np.testing.assert_allclose(out, ref, atol=2e-5, rtol=2e-5)

    def test_gqa_grouping(self):
        from ray_tpu.ops.pallas.flash_attention import flash_attention

        q, k, v = _rand_qkv(jax.random.key(1), H=8, KVH=2)
        out = flash_attention(q, k, v, True)
        ref = reference_attention(q, k, v, causal=True)
        np.testing.assert_allclose(out, ref, atol=2e-5, rtol=2e-5)

    def test_grad_flows(self):
        from ray_tpu.ops.pallas.flash_attention import flash_attention

        q, k, v = _rand_qkv(jax.random.key(2), S=128)

        def loss(q, k, v):
            return jnp.sum(flash_attention(q, k, v, True) ** 2)

        g = jax.grad(loss)(q, k, v)
        gref = jax.grad(
            lambda q, k, v: jnp.sum(
                reference_attention(q, k, v, causal=True) ** 2))(q, k, v)
        np.testing.assert_allclose(g, gref, atol=1e-4, rtol=1e-4)


class TestRingAttention:
    def test_matches_reference(self):
        from jax.sharding import Mesh, PartitionSpec as P
        from ray_tpu.ops.ring_attention import ring_attention

        devs = np.array(jax.devices()[:4])
        mesh = Mesh(devs.reshape(4), ("seq",))
        q, k, v = _rand_qkv(jax.random.key(3), S=64, H=4, KVH=4, D=16)
        spec = P(None, "seq", None, None)
        fn = jax.jit(jax.shard_map(
            lambda q, k, v: ring_attention(q, k, v, axis_name="seq"),
            mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec,
            check_vma=False))
        out = fn(q, k, v)
        ref = reference_attention(q, k, v, causal=True)
        np.testing.assert_allclose(out, ref, atol=2e-5, rtol=2e-5)

    def test_gqa_noncausal(self):
        from jax.sharding import Mesh, PartitionSpec as P
        from ray_tpu.ops.ring_attention import ring_attention

        devs = np.array(jax.devices()[:2])
        mesh = Mesh(devs.reshape(2), ("seq",))
        q, k, v = _rand_qkv(jax.random.key(4), S=32, H=4, KVH=2, D=8)
        spec = P(None, "seq", None, None)
        fn = jax.jit(jax.shard_map(
            lambda q, k, v: ring_attention(q, k, v, axis_name="seq",
                                           causal=False),
            mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec,
            check_vma=False))
        out = fn(q, k, v)
        ref = reference_attention(q, k, v, causal=False)
        np.testing.assert_allclose(out, ref, atol=2e-5, rtol=2e-5)


class TestBlockwiseAttention:
    def test_matches_reference_fwd_and_grad(self):
        import jax
        import jax.numpy as jnp

        from ray_tpu.ops.attention import reference_attention
        from ray_tpu.ops.blockwise_attention import blockwise_attention

        k1, k2, k3, k4 = jax.random.split(jax.random.key(0), 4)
        B, S, H, D = 2, 256, 4, 64
        q = jax.random.normal(k1, (B, S, H, D), jnp.float32)
        k = jax.random.normal(k2, (B, S, H, D), jnp.float32)
        v = jax.random.normal(k3, (B, S, H, D), jnp.float32)
        g = jax.random.normal(k4, (B, S, H, D), jnp.float32)
        ref = reference_attention(q, k, v, causal=True)
        blk = blockwise_attention(q, k, v, causal=True, block_k=64)
        assert jnp.allclose(ref, blk, atol=2e-4), \
            float(jnp.abs(ref - blk).max())
        gr = jax.grad(lambda *a: (reference_attention(
            *a, causal=True) * g).sum(), argnums=(0, 1, 2))(q, k, v)
        gb = jax.grad(lambda *a: (blockwise_attention(
            *a, causal=True, block_k=64) * g).sum(),
            argnums=(0, 1, 2))(q, k, v)
        for a, b in zip(gr, gb):
            assert float(jnp.abs(a - b).max()) < 1e-3

    def test_gqa(self):
        import jax
        import jax.numpy as jnp

        from ray_tpu.ops.attention import reference_attention
        from ray_tpu.ops.blockwise_attention import blockwise_attention

        k1, k2, k3 = jax.random.split(jax.random.key(1), 3)
        q = jax.random.normal(k1, (1, 128, 8, 32), jnp.float32)
        k = jax.random.normal(k2, (1, 128, 2, 32), jnp.float32)
        v = jax.random.normal(k3, (1, 128, 2, 32), jnp.float32)
        assert jnp.allclose(
            reference_attention(q, k, v, causal=True),
            blockwise_attention(q, k, v, causal=True, block_k=32),
            atol=2e-4)


class TestFlashBackward:
    def test_pallas_bwd_matches_reference(self):
        """The custom dq/dkv kernels (interpret mode on CPU) must produce
        reference gradients — the training-path correctness gate."""
        import jax
        import jax.numpy as jnp

        from ray_tpu.ops.attention import reference_attention
        from ray_tpu.ops.pallas.flash_attention import flash_attention

        k1, k2, k3, k4 = jax.random.split(jax.random.key(2), 4)
        B, S, H, D = 1, 256, 2, 128
        q = jax.random.normal(k1, (B, S, H, D), jnp.float32)
        k = jax.random.normal(k2, (B, S, H, D), jnp.float32)
        v = jax.random.normal(k3, (B, S, H, D), jnp.float32)
        g = jax.random.normal(k4, (B, S, H, D), jnp.float32)
        gr = jax.grad(lambda *a: (reference_attention(
            *a, causal=True) * g).sum(), argnums=(0, 1, 2))(q, k, v)
        gf = jax.grad(lambda *a: (flash_attention(*a, True) * g).sum(),
                      argnums=(0, 1, 2))(q, k, v)
        for a, b, n in zip(gr, gf, "qkv"):
            assert float(jnp.abs(a - b).max()) < 5e-5, n

    def test_gqa_backward_native(self):
        """n_rep > 1 runs the native Pallas dk/dv kernel (grid walks each
        kv head's query group; VERDICT r2 item 6) — gradients must match
        reference attention."""
        import jax
        import jax.numpy as jnp

        from ray_tpu.ops.attention import reference_attention
        from ray_tpu.ops.pallas.flash_attention import flash_attention

        k1, k2, k3 = jax.random.split(jax.random.key(3), 3)
        q = jax.random.normal(k1, (1, 256, 4, 128), jnp.float32)
        k = jax.random.normal(k2, (1, 256, 2, 128), jnp.float32)
        v = jax.random.normal(k3, (1, 256, 2, 128), jnp.float32)
        gr = jax.grad(lambda *a: reference_attention(
            *a, causal=True).sum(), argnums=(0, 1, 2))(q, k, v)
        gf = jax.grad(lambda *a: flash_attention(*a, True).sum(),
                      argnums=(0, 1, 2))(q, k, v)
        for a, b in zip(gr, gf):
            assert float(jnp.abs(a - b).max()) < 1e-3
