"""A2C — synchronous advantage actor-critic (reference:
rllib/algorithms/a2c/a2c.py, externalized to rllib_contrib in the snapshot:
one on-policy gradient step per sampled batch, no surrogate clipping, no
minibatch epochs — the degenerate PPO with num_epochs=1 and no ratio).
"""

from __future__ import annotations

from typing import Dict

import jax.numpy as jnp

from ray_tpu.rllib.algorithms.ppo.ppo import PPO, PPOConfig
from ray_tpu.rllib.core.learner import Learner


class A2CLearner(Learner):
    """Vanilla policy-gradient on GAE advantages (reference:
    a2c loss = pg + vf_coeff * vf - entropy_coeff * entropy)."""

    def loss(self, params, batch):
        cfg = self.config
        out = self.module.forward(params, batch["obs"])
        dist = self.module.dist
        logp = dist.logp(out["logits"], batch["actions"])
        adv = batch["advantages"]
        adv = (adv - adv.mean()) / (adv.std() + 1e-8)
        pi_loss = -jnp.mean(logp * adv)
        vf_loss = jnp.mean((out["vf"] - batch["value_targets"]) ** 2)
        entropy = jnp.mean(dist.entropy(out["logits"]))
        total = (pi_loss + cfg.get("vf_loss_coeff", 0.5) * vf_loss
                 - cfg.get("entropy_coeff", 0.01) * entropy)
        return total, {"policy_loss": pi_loss, "vf_loss": vf_loss,
                       "entropy": entropy}


class A2CConfig(PPOConfig):
    def __init__(self, algo_class=None):
        super().__init__(algo_class=algo_class or A2C)
        self.entropy_coeff = 0.01
        self.num_epochs = 1          # single pass: stay on-policy
        self.minibatch_size = None   # whole batch per update
        self.train_batch_size = 512


class A2C(PPO):
    """Sampling + GAE postprocessing are PPO's; only the loss differs."""

    learner_cls = A2CLearner

    @classmethod
    def get_default_config(cls):
        return A2CConfig(algo_class=cls)
