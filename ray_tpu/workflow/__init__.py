"""Durable workflows (reference: python/ray/workflow/ — workflow.run
api.py:123, run_async :177, WorkflowExecutor + step checkpointing
workflow_storage.py).

Executes a ``ray_tpu.dag`` graph with every step's result checkpointed to
storage; ``resume`` re-runs the graph, skipping steps whose checkpoints
exist — lineage-on-disk rather than lineage-in-memory.
"""

from __future__ import annotations

import json
import os
import pickle
import time
from typing import Any, Dict, List, Optional

import ray_tpu
from ray_tpu.dag import DAGNode, FunctionNode, InputNode, MultiOutputNode

_storage_root = os.path.expanduser("~/ray_tpu_workflows")


def init(storage: Optional[str] = None) -> None:
    global _storage_root
    if storage:
        _storage_root = storage
    os.makedirs(_storage_root, exist_ok=True)


def _wf_dir(workflow_id: str) -> str:
    return os.path.join(_storage_root, workflow_id)


def _node_keys(root: DAGNode) -> Dict[int, str]:
    """Deterministic step keys: postorder index + function name."""
    keys: Dict[int, str] = {}
    counter = [0]

    def visit(node: DAGNode):
        if id(node) in keys:
            return
        for a in list(node._bound_args) + list(node._bound_kwargs.values()):
            if isinstance(a, DAGNode):
                visit(a)
        name = type(node).__name__
        if isinstance(node, FunctionNode):
            name = getattr(node._remote_fn, "__name__", "fn")
        keys[id(node)] = f"step_{counter[0]:04d}_{name}"
        counter[0] += 1

    visit(root)
    return keys


class _DurableExecutor:
    def __init__(self, workflow_id: str, root: DAGNode):
        self.workflow_id = workflow_id
        self.dir = _wf_dir(workflow_id)
        os.makedirs(self.dir, exist_ok=True)
        self.keys = _node_keys(root)
        self.root = root

    def _ckpt_path(self, node) -> str:
        return os.path.join(self.dir, self.keys[id(node)] + ".pkl")

    def _set_status(self, status: str) -> None:
        with open(os.path.join(self.dir, "status.json"), "w") as f:
            json.dump({"status": status, "time": time.time()}, f)

    def run(self, *input_args, **input_kwargs) -> Any:
        self._set_status("RUNNING")
        try:
            result = self._exec(self.root, input_args, input_kwargs)
            if isinstance(result, ray_tpu.ObjectRef):
                result = ray_tpu.get(result)
            elif isinstance(result, list):
                result = [ray_tpu.get(r) if isinstance(r, ray_tpu.ObjectRef)
                          else r for r in result]
            self._set_status("SUCCESSFUL")
            return result
        except Exception:
            self._set_status("FAILED")
            raise

    def _exec(self, node: DAGNode, input_args, input_kwargs):
        if isinstance(node, InputNode):
            return node._execute_node({}, input_args, input_kwargs)
        if isinstance(node, MultiOutputNode):
            return [self._exec(a, input_args, input_kwargs)
                    for a in node._bound_args]
        path = self._ckpt_path(node)
        if os.path.exists(path):
            with open(path, "rb") as f:
                return pickle.load(f)

        def resolve(a):
            if isinstance(a, DAGNode):
                return self._exec(a, input_args, input_kwargs)
            return a

        args = [resolve(a) for a in node._bound_args]
        kwargs = {k: resolve(v) for k, v in node._bound_kwargs.items()}
        if isinstance(node, FunctionNode):
            ref = node._remote_fn.remote(*args, **kwargs)
        else:
            method = getattr(node._actor, node._method_name)
            ref = method.remote(*args, **kwargs)
        value = ray_tpu.get(ref)
        tmp = path + ".tmp"
        with open(tmp, "wb") as f:
            pickle.dump(value, f)
        os.replace(tmp, path)
        return value


def run(dag: DAGNode, *, workflow_id: Optional[str] = None,
        args: tuple = (), kwargs: Optional[Dict] = None) -> Any:
    """Execute durably; every completed step is checkpointed."""
    init()
    workflow_id = workflow_id or f"workflow_{int(time.time() * 1000)}"
    return _DurableExecutor(workflow_id, dag).run(
        *args, **(kwargs or {}))


def run_async(dag: DAGNode, *, workflow_id: Optional[str] = None,
              args: tuple = (), kwargs: Optional[Dict] = None):
    """Non-blocking run (reference: workflow/api.py:177 run_async) —
    returns a concurrent.futures.Future of the workflow result."""
    from concurrent.futures import ThreadPoolExecutor

    init()
    workflow_id = workflow_id or f"workflow_{int(time.time() * 1000)}"
    pool = ThreadPoolExecutor(max_workers=1,
                              thread_name_prefix=f"wf-{workflow_id}")
    fut = pool.submit(
        lambda: _DurableExecutor(workflow_id, dag).run(
            *args, **(kwargs or {})))
    fut.add_done_callback(lambda _: pool.shutdown(wait=False))
    fut.workflow_id = workflow_id
    return fut


# ------------------------------------------------------------------ events
class EventListener:
    """Event source ABC (reference: workflow/event_system —
    EventListener.poll_for_event; the HTTPEventProvider is an
    implementation detail of its hosted variant). ``poll_for_event``
    blocks until the event arrives and returns its payload."""

    def poll_for_event(self) -> Any:
        raise NotImplementedError


class TimerListener(EventListener):
    """Fires after ``seconds`` (reference: the timer event example)."""

    def __init__(self, seconds: float):
        self.seconds = seconds

    def poll_for_event(self) -> float:
        time.sleep(self.seconds)
        return time.time()


class FileEventListener(EventListener):
    """Fires when ``path`` exists; payload is its contents (a minimal
    external-event provider usable across processes)."""

    def __init__(self, path: str, poll_interval: float = 0.1):
        self.path = path
        self.poll_interval = poll_interval

    def poll_for_event(self) -> bytes:
        while not os.path.exists(self.path):
            time.sleep(self.poll_interval)
        with open(self.path, "rb") as f:
            return f.read()


def wait_for_event(listener_cls, *args, **kwargs) -> DAGNode:
    """A DAG step that completes when the listener's event arrives
    (reference: workflow.wait_for_event). Like any step, the received
    payload is checkpointed — a resumed workflow does NOT wait again."""
    import ray_tpu

    @ray_tpu.remote
    def __wait_for_event__():
        return listener_cls(*args, **kwargs).poll_for_event()

    return __wait_for_event__.bind()


def resume(workflow_id: str, dag: DAGNode, *, args: tuple = (),
           kwargs: Optional[Dict] = None) -> Any:
    """Re-run a workflow; completed steps are served from checkpoints.

    (The reference serializes the DAG into storage so resume needs no code;
    here the caller re-supplies the graph and storage supplies the state.)
    """
    init()
    if not os.path.isdir(_wf_dir(workflow_id)):
        raise ValueError(f"no workflow {workflow_id!r}")
    return _DurableExecutor(workflow_id, dag).run(*args, **(kwargs or {}))


def get_status(workflow_id: str) -> Optional[str]:
    path = os.path.join(_wf_dir(workflow_id), "status.json")
    if not os.path.exists(path):
        return None
    with open(path) as f:
        return json.load(f)["status"]


def list_all() -> List[Dict]:
    init()
    out = []
    for wid in sorted(os.listdir(_storage_root)):
        status = get_status(wid)
        if status:
            out.append({"workflow_id": wid, "status": status})
    return out


def delete(workflow_id: str) -> None:
    import shutil

    shutil.rmtree(_wf_dir(workflow_id), ignore_errors=True)
