// Native unit test for the shm store kernel — the ASAN/UBSAN build target
// (reference test culture: plasma's co-located unit tests,
// src/ray/object_manager/plasma/). Build + run:
//
//   g++ -std=c++17 -g -fsanitize=address,undefined -Iray_tpu/_native \
//       ray_tpu/_native/store_test.cc -o /tmp/store_test -lpthread
//   /tmp/store_test /dev/shm/store_test_seg
//
// Exercises: lifecycle, eviction, fork-based multi-writer stress, and the
// EOWNERDEAD robust-mutex recovery (a forked child dies holding the lock;
// the parent's next op must recover and repair).

#include <sys/wait.h>

#include <cassert>
#include <cstdio>
#include <cstdlib>

#include "store.cc"  // single-TU: the kernel is header-free by design


namespace {

void make_id(uint8_t* id, uint32_t n) {
  for (int i = 0; i < 20; i++) id[i] = static_cast<uint8_t>(n >> (i % 4));
  id[0] = static_cast<uint8_t>(n);
  id[1] = static_cast<uint8_t>(n >> 8);
  id[2] = static_cast<uint8_t>(n >> 16);
  id[3] = static_cast<uint8_t>(n >> 24);
}

int failures = 0;
#define CHECK(cond)                                                     \
  do {                                                                  \
    if (!(cond)) {                                                      \
      std::fprintf(stderr, "FAIL %s:%d: %s\n", __FILE__, __LINE__,      \
                   #cond);                                              \
      failures++;                                                       \
    }                                                                   \
  } while (0)

void test_lifecycle(const char* path) {
  void* h = tpu_store_create(path, 1 << 20);
  CHECK(h != nullptr);
  uint8_t id[20];
  make_id(id, 1);
  uint64_t off = tpu_store_create_object(h, id, 1000);
  CHECK(off != 0);
  uint8_t* base = tpu_store_base(h);
  for (int i = 0; i < 1000; i++) base[off + i] = static_cast<uint8_t>(i);
  CHECK(tpu_store_seal(h, id) == 0);
  uint64_t goff = 0, size = 0;
  CHECK(tpu_store_get(h, id, &goff, &size) == 0 && goff == off &&
        size == 1000);
  CHECK(tpu_store_release(h, id) == 0);
  CHECK(tpu_store_contains(h, id) == 1);
  CHECK(tpu_store_delete(h, id) == 0);
  CHECK(tpu_store_contains(h, id) == 0);
  tpu_store_detach(h);
}

void test_eviction_fill(const char* path) {
  void* h = tpu_store_create(path, 1 << 20);
  // overfill 4x: LRU eviction must keep making room
  for (uint32_t n = 0; n < 64; n++) {
    uint8_t id[20];
    make_id(id, 1000 + n);
    uint64_t off = tpu_store_create_object(h, id, 60 * 1024);
    CHECK(off != 0);
    CHECK(tpu_store_seal(h, id) == 0);
  }
  tpu_store_detach(h);
}

void test_multiprocess_stress(const char* path) {
  void* h = tpu_store_create(path, 4 << 20);
  tpu_store_detach(h);
  const int kProcs = 4, kOps = 4000;
  for (int p = 0; p < kProcs; p++) {
    pid_t pid = fork();
    if (pid == 0) {
      void* ch = tpu_store_attach(path);
      if (!ch) _exit(2);
      unsigned seed = 1234u + p;
      for (int op = 0; op < kOps; op++) {
        uint8_t id[20];
        make_id(id, (rand_r(&seed) % 512) | (p << 20));
        int what = rand_r(&seed) % 3;
        if (what == 0) {
          uint64_t off =
              tpu_store_create_object(ch, id, 1 + rand_r(&seed) % 8192);
          if (off) tpu_store_seal(ch, id);
        } else if (what == 1) {
          uint64_t goff, size;
          if (tpu_store_get(ch, id, &goff, &size) == 0)
            tpu_store_release(ch, id);
        } else {
          tpu_store_delete(ch, id);
        }
      }
      tpu_store_detach(ch);
      _exit(0);
    }
  }
  for (int p = 0; p < kProcs; p++) {
    int st = 0;
    ::wait(&st);
    CHECK(WIFEXITED(st) && WEXITSTATUS(st) == 0);
  }
  // the arena must still be fully usable
  void* h2 = tpu_store_attach(path);
  uint8_t id[20];
  make_id(id, 999999);
  uint64_t off = tpu_store_create_object(h2, id, 4096);
  CHECK(off != 0);
  CHECK(tpu_store_seal(h2, id) == 0);
  tpu_store_detach(h2);
}

void test_eownerdead_recovery(const char* path) {
  void* h = tpu_store_create(path, 1 << 20);
  pid_t pid = fork();
  if (pid == 0) {
    void* ch = tpu_store_attach(path);
    if (!ch) _exit(2);
    uint8_t id[20];
    make_id(id, 777);
    // die with a half-written (CREATED) object AND the mutex held
    tpu_store_create_object(ch, id, 2048);
    tpu_store_test_lock_and_leak(ch);
    _exit(0);  // mutex owner dies => EOWNERDEAD for the next locker
  }
  int st = 0;
  ::waitpid(pid, &st, 0);
  CHECK(WIFEXITED(st));
  // next op sees EOWNERDEAD, repairs, and proceeds
  uint8_t id2[20];
  make_id(id2, 778);
  uint64_t off = tpu_store_create_object(h, id2, 1024);
  CHECK(off != 0);
  CHECK(tpu_store_seal(h, id2) == 0);
  // the dead writer's CREATED slot was swept by the repair
  uint8_t id[20];
  make_id(id, 777);
  CHECK(tpu_store_contains(h, id) == 0);
  tpu_store_detach(h);
}

}  // namespace

int main(int argc, char** argv) {
  const char* base = argc > 1 ? argv[1] : "/dev/shm/ray_tpu_store_test";
  char path[512];
  std::snprintf(path, sizeof(path), "%s.l", base);
  ::unlink(path);
  test_lifecycle(path);
  std::snprintf(path, sizeof(path), "%s.e", base);
  ::unlink(path);
  test_eviction_fill(path);
  std::snprintf(path, sizeof(path), "%s.s", base);
  ::unlink(path);
  test_multiprocess_stress(path);
  std::snprintf(path, sizeof(path), "%s.d", base);
  ::unlink(path);
  test_eownerdead_recovery(path);
  if (failures) {
    std::fprintf(stderr, "%d failures\n", failures);
    return 1;
  }
  std::printf("store_test OK\n");
  return 0;
}
