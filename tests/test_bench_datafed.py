"""Data→Train feed proof at test scale (VERDICT r4 #6): the dense bench
step fed by Dataset.streaming_split/iter_jax_batches must train on real
blocks flowing through the streaming executor (reference:
train/_internal/data_config.py per-worker split)."""

import numpy as np

import ray_tpu


def test_datafed_dense_step_runs(monkeypatch):
    import bench
    from ray_tpu.models.llama import LlamaConfig

    cfg = LlamaConfig.tiny()
    tok_s, mfu, n = bench._run_dense_datafed(
        cfg, batch=4, seq=64, steps=3, platform="cpu")
    assert n == 3
    assert tok_s > 0 and mfu > 0
    if ray_tpu.is_initialized():
        ray_tpu.shutdown()


def test_tokenize_rows_deterministic():
    import bench

    a = bench._tokenize_rows(np.arange(4), seq=8, vocab=128)
    b = bench._tokenize_rows(np.arange(4), seq=8, vocab=128)
    assert a["inputs"].shape == (4, 8) and a["targets"].shape == (4, 8)
    np.testing.assert_array_equal(a["inputs"], b["inputs"])
    # causal pairing: targets are inputs shifted by one position
    np.testing.assert_array_equal(a["inputs"][:, 1:], a["targets"][:, :-1])
    assert a["inputs"].min() >= 0 and a["inputs"].max() < 128
