"""Autoscaler monitor loop (reference: python/ray/autoscaler/_private/
monitor.py:126 — the process polling GCS and driving StandardAutoscaler).

Runs as a daemon thread with its own event loop + GCS connection so it works
both embedded in a driver (AutoscalingCluster tests) and as a standalone
process (``python -m ray_tpu.autoscaler.monitor``).
"""

from __future__ import annotations

import asyncio
import threading
from typing import Dict, Optional

from ray_tpu.autoscaler.autoscaler import StandardAutoscaler
from ray_tpu.autoscaler.node_provider import NodeProvider


class GcsChannel:
    """Synchronous GCS RPC facade over a private event-loop thread."""

    def __init__(self, host: str, port: int):
        self._loop = asyncio.new_event_loop()
        self._thread = threading.Thread(
            target=self._loop.run_forever, name="autoscaler-gcs", daemon=True)
        self._thread.start()
        from ray_tpu._private.protocol import AsyncRpcClient

        self._client = AsyncRpcClient()
        fut = asyncio.run_coroutine_threadsafe(
            self._client.connect_tcp(host, port), self._loop)
        fut.result(30)

    def call(self, method: str, payload: Dict, timeout: float = 30.0):
        fut = asyncio.run_coroutine_threadsafe(
            self._client.call(method, payload), self._loop)
        return fut.result(timeout)

    def close(self) -> None:
        # aclose ON the private loop BEFORE stopping it, or the client's
        # cancelled read-loop task is stranded and the dying loop warns
        # "Task was destroyed but it is pending!" at teardown
        try:
            asyncio.run_coroutine_threadsafe(
                self._client.aclose(), self._loop).result(5)
        except Exception:
            pass
        self._loop.call_soon_threadsafe(self._loop.stop)
        self._thread.join(timeout=5)


class Monitor:
    def __init__(self, config: Dict, provider: NodeProvider,
                 head_host: str, head_port: int,
                 update_interval_s: float = 1.0):
        self.channel = GcsChannel(head_host, head_port)
        self.autoscaler = StandardAutoscaler(
            config, provider, self.channel.call)
        self.update_interval_s = update_interval_s
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def start(self) -> None:
        from ray_tpu._private.event import _writer, init_event_log

        if _writer is None:
            session_dir = getattr(self.autoscaler.provider,
                                  "provider_config", {}).get("session_dir")
            if session_dir:
                init_event_log(session_dir, "autoscaler")
        self._thread = threading.Thread(
            target=self._run, name="autoscaler-monitor", daemon=True)
        self._thread.start()

    def _run(self) -> None:
        while not self._stop.is_set():
            try:
                self.autoscaler.update()
            except Exception:
                pass  # transient GCS hiccups must not kill the loop
            self._stop.wait(self.update_interval_s)

    def stop(self) -> None:
        self._stop.set()
        if self._thread:
            self._thread.join(timeout=10)
        self.channel.close()
