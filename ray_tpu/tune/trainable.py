"""Trainable ABC + function trainables (reference:
python/ray/tune/trainable/trainable.py:61 — train :301, save :434,
restore :508, user step :835; function wrapping mirrors
tune/trainable/function_trainable.py's thread+queue design).
"""

from __future__ import annotations

import os
import queue
import shutil
import threading
import time
import traceback
from typing import Any, Callable, Dict, Optional

from ray_tpu.train._checkpoint import Checkpoint

# Standard result fields (reference: tune/result.py)
TRAINING_ITERATION = "training_iteration"
DONE = "done"
TRIAL_ID = "trial_id"
TIME_TOTAL_S = "time_total_s"
TIME_THIS_ITER_S = "time_this_iter_s"


class Trainable:
    """Class API: subclass and implement ``setup``/``step``/
    ``save_checkpoint``/``load_checkpoint``."""

    def __init__(self, config: Optional[Dict] = None,
                 trial_id: str = "", trial_dir: str = ""):
        self.config = config or {}
        self.trial_id = trial_id
        self.trial_dir = trial_dir or os.getcwd()
        self.iteration = 0
        self._time_total = 0.0
        self._restored = False
        self.setup(self.config)

    # ------------------------------------------------------------ user API
    def setup(self, config: Dict) -> None:
        pass

    def step(self) -> Dict:
        raise NotImplementedError

    def save_checkpoint(self, checkpoint_dir: str) -> None:
        raise NotImplementedError

    def load_checkpoint(self, checkpoint_dir: str) -> None:
        raise NotImplementedError

    def cleanup(self) -> None:
        pass

    def reset_config(self, new_config: Dict) -> bool:
        """Return True if the trainable can hot-swap configs (used to reuse
        actors across trials, reference trainable.py reset)."""
        return False

    # --------------------------------------------------------- driver API
    def train(self) -> Dict:
        start = time.monotonic()
        result = self.step() or {}
        took = time.monotonic() - start
        self.iteration += 1
        self._time_total += took
        result.setdefault(DONE, False)
        result[TRAINING_ITERATION] = self.iteration
        result[TRIAL_ID] = self.trial_id
        result[TIME_THIS_ITER_S] = took
        result[TIME_TOTAL_S] = self._time_total
        return result

    def save(self) -> str:
        d = os.path.join(self.trial_dir,
                         f"checkpoint_{self.iteration:06d}")
        os.makedirs(d, exist_ok=True)
        self.save_checkpoint(d)
        self._save_trainable_meta(d)
        return d

    def restore(self, checkpoint_dir: str) -> None:
        self._load_trainable_meta(checkpoint_dir)
        self.load_checkpoint(checkpoint_dir)
        self._restored = True

    def stop(self) -> None:
        self.cleanup()

    # ------------------------------------------------------------ internals
    def _save_trainable_meta(self, d: str) -> None:
        import json

        with open(os.path.join(d, ".tune_metadata"), "w") as f:
            json.dump({"iteration": self.iteration,
                       "time_total": self._time_total}, f)

    def _load_trainable_meta(self, d: str) -> None:
        import json

        p = os.path.join(d, ".tune_metadata")
        if os.path.exists(p):
            with open(p) as f:
                meta = json.load(f)
            self.iteration = meta.get("iteration", 0)
            self._time_total = meta.get("time_total", 0.0)


class _FunctionSession:
    """Per-process session backing ``ray_tpu.tune.report`` inside function
    trainables."""

    def __init__(self, trial_dir: str, loaded_checkpoint: Optional[Checkpoint]):
        self.trial_dir = trial_dir
        self.loaded_checkpoint = loaded_checkpoint
        self.results: "queue.Queue" = queue.Queue()
        self.resume = threading.Semaphore(0)
        self.iteration = 0

    def report(self, metrics: Dict,
               checkpoint: Optional[Checkpoint] = None) -> None:
        ckpt_dir = None
        if checkpoint is not None:
            ckpt_dir = os.path.join(
                self.trial_dir, f"checkpoint_{self.iteration:06d}")
            if os.path.abspath(checkpoint.path) != os.path.abspath(ckpt_dir):
                shutil.copytree(checkpoint.path, ckpt_dir,
                                dirs_exist_ok=True)
        self.iteration += 1
        self.results.put(("report", metrics, ckpt_dir))
        self.resume.acquire()  # block until the driver consumed it


_fn_session: Optional[_FunctionSession] = None


def _get_fn_session() -> _FunctionSession:
    if _fn_session is None:
        raise RuntimeError(
            "ray_tpu.tune.report() must be called from inside a Tune "
            "function trainable")
    return _fn_session


class FunctionTrainable(Trainable):
    """Wraps ``fn(config)`` into the iteration protocol: each
    ``tune.report`` call is one training iteration."""

    _fn: Callable = None  # set by wrap_function subclassing

    def setup(self, config: Dict) -> None:
        self._session = _FunctionSession(self.trial_dir, None)
        self._thread: Optional[threading.Thread] = None
        self._error: Optional[str] = None
        self._last_ckpt_dir: Optional[str] = None

    def _runner(self) -> None:
        global _fn_session
        _fn_session = self._session
        try:
            self._fn(self.config)
            self._session.results.put(("done", {}, None))
        except Exception:
            self._session.results.put(
                ("error", {"traceback": traceback.format_exc()}, None))

    def _ensure_started(self) -> None:
        if self._thread is None:
            self._thread = threading.Thread(target=self._runner, daemon=True)
            self._thread.start()

    def step(self) -> Dict:
        self._ensure_started()
        kind, metrics, ckpt_dir = self._session.results.get()
        if kind == "error":
            raise RuntimeError(
                f"trainable function failed:\n{metrics['traceback']}")
        if kind == "done":
            # final pseudo-step carries the last reported metrics forward
            # (reference: function trainables mark the last result done)
            return {**getattr(self, "_last_metrics", {}), DONE: True}
        self._session.resume.release()
        metrics = dict(metrics)
        self._last_metrics = dict(metrics)
        if ckpt_dir:
            self._last_ckpt_dir = ckpt_dir
            # surfaced to the controller so fault recovery / PBT can restore
            # from the last *reported* checkpoint (reference tracks this in
            # the session's TrainingResult)
            metrics["_checkpoint_dir"] = ckpt_dir
        return metrics

    def save(self) -> str:
        # function trainables checkpoint through report(); hand back the
        # latest one (reference: function_trainable saves the last reported)
        if self._last_ckpt_dir is None:
            d = os.path.join(self.trial_dir,
                             f"checkpoint_{self.iteration:06d}")
            os.makedirs(d, exist_ok=True)
            self._save_trainable_meta(d)
            return d
        self._save_trainable_meta(self._last_ckpt_dir)
        return self._last_ckpt_dir

    def restore(self, checkpoint_dir: str) -> None:
        self._load_trainable_meta(checkpoint_dir)
        self._session.loaded_checkpoint = Checkpoint(checkpoint_dir)
        self._session.iteration = self.iteration
        self._restored = True

    def stop(self) -> None:
        # the user thread is daemonic; just unblock it if waiting
        if self._thread is not None and self._thread.is_alive():
            self._session.resume.release()
        self.cleanup()


def wrap_function(fn: Callable) -> type:
    """Build a FunctionTrainable subclass bound to ``fn``."""

    class _Wrapped(FunctionTrainable):
        pass

    _Wrapped._fn = staticmethod(fn)
    _Wrapped.__name__ = getattr(fn, "__name__", "fn")
    return _Wrapped


def with_parameters(fn: Callable, **params) -> Callable:
    """Attach large constant objects to a trainable function
    (reference: tune/trainable/util.py with_parameters)."""
    import functools

    @functools.wraps(fn)
    def inner(config):
        return fn(config, **params)

    return inner
