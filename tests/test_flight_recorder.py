"""Cluster flight recorder (ISSUE 14): crash-durable span rings, trace
propagation across transports (mux TCP + shm lanes), Chrome-trace/Perfetto
timeline validity, the Prometheus scrape endpoint, read-your-writes event
flushes, and the sampling-off zero-cost contract."""

import json
import os
import subprocess
import sys
import time
import urllib.error
import urllib.request

import pytest

import ray_tpu
from ray_tpu._private import events


# ---------------------------------------------------------------------------
# ring unit tests (no cluster)
# ---------------------------------------------------------------------------
def _armed_recorder(tmp_path, role="unit", slots=None):
    rec = events.SpanRecorder()
    if slots is not None:
        os.environ["RAY_TPU_TASK_EVENT_RING_SLOTS"] = str(slots)
    try:
        assert rec.configure(str(tmp_path), role, sample_rate=1.0)
    finally:
        os.environ.pop("RAY_TPU_TASK_EVENT_RING_SLOTS", None)
    return rec


def test_ring_roundtrip_wrap_and_clip(tmp_path):
    rec = _armed_recorder(tmp_path, slots=128)
    tid, root = rec.new_trace()
    rec.open_marker("exec::f", "exec", tid, root)
    rec.record("exec::f", "exec", time.time(), 0.005, tid, root, 0,
               {"task": "abc"})
    info = events.read_ring(rec.path)
    assert info["role"] == "unit" and info["pid"] == os.getpid()
    assert info["recorded"] == 2 and len(info["spans"]) == 2
    opens = [s for s in info["spans"] if s["dur_us"] < 0]
    assert len(opens) == 1 and opens[0]["name"] == "exec::f"
    # wrap: ring keeps exactly the newest <slots> records
    for i in range(300):
        rec.record(f"s{i}", "x", time.time(), 0.0, tid, rec.next_id(), 0)
    info = events.read_ring(rec.path)
    assert info["recorded"] == 302
    assert len(info["spans"]) == 128
    assert any(s["name"] == "s299" for s in info["spans"])
    assert not any(s["name"] == "s0" for s in info["spans"])
    # oversize extra is clipped, span itself survives
    rec.record("big", "x", time.time(), 0.0, tid, rec.next_id(), 0,
               {"blob": "v" * 4096})
    assert rec.clipped == 1
    last = events.read_ring(rec.path)["spans"][-1]
    assert last["name"] == "big" and last["extra"] is None
    # drain is incremental and bounded by the ring
    drained = rec.drain()
    assert len(drained) == 128 and rec.drain() == []
    # recover_session finds the ring like a post-mortem would
    rings = events.recover_session(str(tmp_path))
    assert len(rings) == 1 and rings[0]["clipped"] == 1


def test_disabled_recorder_records_nothing(tmp_path):
    rec = events.SpanRecorder()
    assert not rec.configure(str(tmp_path), "unit", sample_rate=0.0)
    assert not rec.enabled and not rec.sample()
    rec.record("x", "x", time.time(), 0.0, 1, 2)  # no ring -> no-op
    assert rec.counter == 0
    assert not os.path.exists(os.path.join(str(tmp_path), "events"))


def test_disabled_guard_overhead_probe():
    # sanity bound only — the calibrated <2%-of-task-budget assert lives
    # in scale_bench's many_tasks gate where the task budget is measured
    ns = events.overhead_probe(100_000)
    assert ns < 1500, f"disabled guard costs {ns:.0f}ns/site"


def test_chrome_trace_export_schema_unit():
    tid = 0x123456
    spans = [
        {"trace": tid, "span": 1, "parent": 0, "name": "task::f",
         "cat": "task", "ts_us": 1000, "dur_us": 500, "extra": None,
         "role": "driver", "pid": 10, "node": "n1"},
        {"trace": tid, "span": 2, "parent": 1, "name": "exec::f",
         "cat": "exec", "ts_us": 1100, "dur_us": 300, "extra": None,
         "role": "worker", "pid": 11, "node": "n1"},
        # open marker superseded by its close must not double-render
        {"trace": tid, "span": 2, "parent": 1, "name": "exec::f",
         "cat": "exec", "ts_us": 1100, "dur_us": -1, "extra": None,
         "role": "worker", "pid": 11, "node": "n1"},
        # genuinely open marker renders as an instant
        {"trace": tid, "span": 3, "parent": 1, "name": "exec::g",
         "cat": "exec", "ts_us": 1200, "dur_us": -1, "extra": None,
         "role": "worker", "pid": 12, "node": "n1"},
    ]
    out = events.to_chrome_trace(spans)
    assert [e["ts"] for e in out] == sorted(e["ts"] for e in out)
    assert {e["ph"] for e in out} <= {"X", "i", "M"}
    xs = [e for e in out if e["ph"] == "X"]
    assert {e["name"] for e in xs} == {"task::f", "exec::f"}
    opens = [e for e in out if e["ph"] == "i"]
    assert len(opens) == 1 and opens[0]["name"] == "exec::g"
    metas = [e for e in out if e["ph"] == "M"]
    assert len(metas) == 3  # one process_name per (node, role, pid)


# ---------------------------------------------------------------------------
# cluster tests (sampling armed + scrape endpoint bound)
# ---------------------------------------------------------------------------
@pytest.fixture(scope="module")
def traced_cluster():
    import socket

    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
    os.environ["RAY_TPU_TASK_EVENT_SAMPLE_RATE"] = "1"
    os.environ["RAY_TPU_METRICS_EXPORT_PORT"] = str(port)
    assert not ray_tpu.is_initialized()
    ctx = ray_tpu.init(num_cpus=2)
    yield ctx, port
    ray_tpu.shutdown()
    os.environ.pop("RAY_TPU_TASK_EVENT_SAMPLE_RATE", None)
    os.environ.pop("RAY_TPU_METRICS_EXPORT_PORT", None)


def _spans(**filters):
    w = ray_tpu._worker_mod.global_worker
    w.flush_task_events(wait=True)
    return w._acall(w.head.call("ListSpans", {"limit": 50000, **filters}))


def _wait_for(pred, timeout=20.0, what="condition"):
    deadline = time.time() + timeout
    while time.time() < deadline:
        val = pred()
        if val:
            return val
        time.sleep(0.25)
    raise AssertionError(f"timed out waiting for {what}")



def _named(spans, kind, fn=None):
    """Match spans by phase kind and (optionally) function suffix — task
    functions defined inside tests carry qualnames like
    ``test_x.<locals>.add``, so exact-name matching is wrong."""
    out = []
    for sp in spans:
        name = sp["name"]
        if fn is None:
            if name == kind:
                out.append(sp)
        elif name.startswith(kind + "::") and name.endswith(fn):
            out.append(sp)
    return out


def test_task_phases_nest_under_one_trace(traced_cluster):
    @ray_tpu.remote
    def add(x, y):
        return x + y

    ref = add.remote(20, 22)
    assert ray_tpu.get(ref, timeout=60) == 42
    task_hex = ref.id().task_id().hex()[:16]

    def find_tree():
        spans = _spans(task=task_hex)
        roots = _named(spans, "task", "add")
        if not roots:
            return None
        all_tr = _spans(trace=roots[0]["trace"])
        # worker-side flush is paced; wait until exec phases landed
        if (_named(all_tr, "exec", "add")
                and _named(all_tr, "arg_resolve")
                and _named(all_tr, "return_put")):
            return all_tr
        return None

    spans = _wait_for(find_tree, what="full cross-process trace tree")
    root = _named(spans, "task", "add")[0]
    assert root["role"] == "driver"
    lease = _named(spans, "lease_wait")[0]
    assert lease["parent"] == root["span"]
    execs = [s for s in _named(spans, "exec", "add")
             if s["dur_us"] >= 0]
    assert execs and execs[0]["role"] == "worker"
    assert execs[0]["parent"] == root["span"]
    assert execs[0]["trace"] == root["trace"]  # ONE shared trace id
    for child in ("arg_resolve", "return_put"):
        c = _named(spans, child)[0]
        assert c["parent"] == execs[0]["span"]
    # phases nest in time: exec inside the root slice
    assert root["ts_us"] <= execs[0]["ts_us"]
    assert (execs[0]["ts_us"] + execs[0]["dur_us"]
            <= root["ts_us"] + root["dur_us"] + 50_000)


def test_actor_call_trace_rides_shm_lane(traced_cluster):
    from ray_tpu._private.shm_rpc import SHM_STATS

    @ray_tpu.remote
    class Echo:
        def hi(self, x):
            return x

    a = Echo.remote()
    ref = a.hi.remote("ping")
    assert ray_tpu.get(ref, timeout=60) == "ping"
    task_hex = ref.id().task_id().hex()[:16]
    # same-node actor calls ride the shm doorbell lane by default
    # (test_direct_call asserts the lane selection itself; here we assert
    # the trace context SURVIVES that lane)
    assert SHM_STATS["calls_out"] > 0

    def find():
        spans = _spans(task=task_hex)
        roots = _named(spans, "actor_call", "hi")
        if not roots:
            return None
        tr = _spans(trace=roots[0]["trace"])
        if any(s["role"] == "worker" and s["dur_us"] >= 0
               for s in _named(tr, "exec", "hi")):
            return tr
        return None

    spans = _wait_for(find, what="actor-call trace across the shm lane")
    root = _named(spans, "actor_call", "hi")[0]
    ex = next(s for s in _named(spans, "exec", "hi") if s["dur_us"] >= 0)
    assert ex["trace"] == root["trace"] and ex["parent"] == root["span"]
    assert _named(spans, "enqueue_wait")


def test_timeline_chrome_schema_and_read_your_writes(traced_cluster):
    @ray_tpu.remote
    def probe():
        return 1

    assert ray_tpu.get(probe.remote(), timeout=60) == 1
    # NO sleep: flush_task_events(wait=True) inside timeline() must make
    # the just-finished task visible (the old 50ms race is the bug)
    tl = ray_tpu.timeline()
    assert tl, "empty timeline"
    finished = [e for e in tl if e.get("cat") == "task_state"
                and e.get("args", {}).get("state") == "FINISHED"
                and "probe" in str(e.get("name"))]
    assert finished, "read-your-writes: FINISHED state missing"
    last_ts = None
    for e in tl:
        for key in ("name", "ph", "ts", "pid", "tid"):
            assert key in e, f"chrome-trace event missing {key}: {e}"
        assert e["ph"] in events._ALLOWED_PH
        assert isinstance(e["ts"], (int, float)) and e["ts"] >= 0
        if e["ph"] == "X":
            assert e.get("dur", -1) >= 0
        if last_ts is not None:
            assert e["ts"] >= last_ts, "timeline not ts-monotonic"
        last_ts = e["ts"]
    assert any(e["ph"] == "M" and e["name"] == "process_name" for e in tl)
    assert any(e["ph"] == "X" and e["name"].startswith("task::")
               for e in tl)
    # and it round-trips through json (what Perfetto actually loads)
    json.loads(json.dumps(tl))


def test_prometheus_scrape_endpoint(traced_cluster):
    ctx, port = traced_cluster
    session_dir = ctx.address_info["session_dir"]
    port_file = os.path.join(session_dir, "metrics_port")
    _wait_for(lambda: os.path.exists(port_file), what="metrics_port file")
    with open(port_file) as f:
        assert int(f.read()) == port

    def scrape():
        try:
            with urllib.request.urlopen(
                    f"http://127.0.0.1:{port}/metrics", timeout=10) as r:
                assert r.status == 200
                assert "text/plain" in r.headers.get("Content-Type", "")
                return r.read().decode()
        except (ConnectionError, OSError):
            return None

    text = _wait_for(scrape, what="scrape endpoint")
    assert "ray_tpu_cluster_up 1" in text
    assert "# TYPE ray_tpu_collect_time_seconds gauge" in text
    # head gauges ride the same pipeline; poll until a metrics tick ran
    text = _wait_for(
        lambda: (lambda t: t if "ray_tpu_gcs_nodes_alive" in t else None)(
            scrape() or ""),
        what="head gauges in scrape output")
    assert "ray_tpu_gcs_task_events_buffered" in text


def test_prometheus_scrape_404(traced_cluster):
    _, port = traced_cluster
    try:
        urllib.request.urlopen(f"http://127.0.0.1:{port}/nope", timeout=10)
        assert False, "expected 404"
    except urllib.error.HTTPError as e:
        assert e.code == 404


def test_event_stats_and_cli_surfaces(traced_cluster, capsys):
    @ray_tpu.remote
    def traced_fn():
        return 7

    ref = traced_fn.remote()
    assert ray_tpu.get(ref, timeout=60) == 7
    w = ray_tpu._worker_mod.global_worker
    w.flush_task_events(wait=True)
    st = w._acall(w.head.call("GetEventStats", {}))
    assert st["head"]["task_events_buffered"] > 0
    assert st["nodes"], "no per-node flight-recorder stats"
    node = next(iter(st["nodes"].values()))
    assert node["flushes"] > 0 and node["spans"] > 0
    # CLI `trace <task_id>` prints the cross-process tree
    from ray_tpu.scripts import cli

    task_hex = ref.id().task_id().hex()[:16]
    _wait_for(lambda: _named(_spans(task=task_hex), "exec", "traced_fn"),
              what="worker exec span flushed")

    class Args:
        task_id = task_hex

    assert cli.cmd_trace(Args()) == 0
    out = capsys.readouterr().out
    assert "traced_fn" in out and "exec::" in out and "task::" in out
    # CLI `status` renders the Events section off the same RPC
    cli._print_events()
    out = capsys.readouterr().out
    assert "Events" in out and "head ring:" in out


def test_kill9_worker_ring_recovered_from_disk(traced_cluster, tmp_path):
    """The chaos contract: a kill -9'd worker's flight-recorder ring is
    on disk mid-task, open exec marker included — no exit handler ran."""
    ctx, _ = traced_cluster
    session_dir = ctx.address_info["session_dir"]

    @ray_tpu.remote
    class Sleeper:
        def pid(self):
            return os.getpid()

        def nap_marker(self, seconds):
            time.sleep(seconds)
            return "done"

    a = Sleeper.remote()
    pid = ray_tpu.get(a.pid.remote(), timeout=60)
    ref = a.nap_marker.remote(60)

    def exec_started():
        try:
            info = events.read_ring(os.path.join(
                session_dir, "events", f"worker-{pid}.ring"))
        except (FileNotFoundError, ValueError):
            return None
        return any(s["name"].endswith("nap_marker")
                   for s in info["spans"])

    _wait_for(exec_started, what="open exec marker in the worker ring")
    # kill -9 through the chaos harness (no SIGTERM, no dump handler —
    # the mmap IS the dump), pinned to the worker that is mid-task
    from ray_tpu._private import lifecycle
    from ray_tpu.util import chaos

    killer = chaos.DaemonKiller(session_dir, roles=("worker",))
    target = next(r for r in lifecycle.live_registered(session_dir)
                  if r["pid"] == pid)
    assert killer.kill_target(target)
    _wait_for(lambda: not lifecycle._pid_alive(pid), what="worker death")
    rings = events.recover_session(session_dir)
    mine = [r for r in rings if r["pid"] == pid]
    assert mine, f"no ring recovered for killed worker {pid}"
    spans = mine[0]["spans"]
    naps = [s for s in spans if s["name"].startswith("exec::")
            and s["name"].endswith("nap_marker")]
    open_exec = [s for s in naps if s["dur_us"] < 0]
    closed_exec = [s for s in naps if s["dur_us"] >= 0]
    assert open_exec and not closed_exec, (
        "post-mortem must show the task OPEN at death")
    # offline timeline over the rings (ray_tpu timeline --session)
    from ray_tpu.scripts import cli

    class Args:
        session = session_dir
        output = str(tmp_path / "postmortem.json")

    assert cli.cmd_timeline(Args()) == 0
    with open(Args.output) as f:
        tl = json.load(f)
    assert any(e["ph"] == "i" and e["name"].endswith("nap_marker")
               and e.get("args", {}).get("open") for e in tl)
    # cleanup: the actor is gone; make the driver forget it
    try:
        ray_tpu.kill(a)
    except Exception:
        pass


# ---------------------------------------------------------------------------
# isolated-cluster tests (different env per cluster -> subprocess)
# ---------------------------------------------------------------------------
_SUBPROC_COMMON = """
import os, sys, time
import ray_tpu

def wait_for(pred, timeout=30, what="condition"):
    deadline = time.time() + timeout
    while time.time() < deadline:
        v = pred()
        if v:
            return v
        time.sleep(0.25)
    raise AssertionError("timed out: " + what)

def spans(**filters):
    w = ray_tpu._worker_mod.global_worker
    w.flush_task_events(wait=True)
    return w._acall(w.head.call("ListSpans", {"limit": 50000, **filters}))
"""


def _run_subproc(body, env=None):
    full_env = dict(os.environ)
    full_env["JAX_PLATFORMS"] = "cpu"
    full_env.update(env or {})
    proc = subprocess.run(
        [sys.executable, "-c", _SUBPROC_COMMON + body],
        capture_output=True, text=True, timeout=300, env=full_env)
    assert proc.returncode == 0, (
        f"subprocess failed:\nstdout:\n{proc.stdout}\n"
        f"stderr:\n{proc.stderr}")
    return proc.stdout


def test_trace_propagates_over_tcp_lane():
    """Same assertion as the shm-lane test, with the shm doorbell lane
    disabled: the trace context must ride the plain mux TCP stream
    byte-identically (the spec wire IS the propagation)."""
    _run_subproc("""
ray_tpu.init(num_cpus=2)
try:
    from ray_tpu._private.shm_rpc import SHM_STATS

    @ray_tpu.remote
    class Echo:
        def hi(self, x):
            return x

    a = Echo.remote()
    ref = a.hi.remote("tcp")
    assert ray_tpu.get(ref, timeout=60) == "tcp"
    assert SHM_STATS["calls_out"] == 0, "shm lane should be disabled"
    task_hex = ref.id().task_id().hex()[:16]

    def find():
        sp = spans(task=task_hex)
        roots = [s for s in sp if s["name"].startswith("actor_call::")
                 and s["name"].endswith("hi")]
        if not roots:
            return None
        tr = spans(trace=roots[0]["trace"])
        ex = [s for s in tr if s["name"].startswith("exec::")
              and s["name"].endswith("hi")
              and s["role"] == "worker" and s["dur_us"] >= 0]
        return (roots[0], ex[0]) if ex else None

    root, ex = wait_for(find, what="trace across TCP lane")
    assert ex["trace"] == root["trace"] and ex["parent"] == root["span"]
    print("TCP_LANE_OK")
finally:
    ray_tpu.shutdown()
""", env={"RAY_TPU_TASK_EVENT_SAMPLE_RATE": "1",
          "RAY_TPU_SHM_RPC_ENABLED": "0"})


def test_sampling_zero_records_nothing_cluster():
    """The default (sample_rate=0) leaves no trace anywhere: recorder
    disarmed in every process, no ring files, no spans at the head —
    while task state events and the timeline keep working."""
    _run_subproc("""
from ray_tpu._private import events
ctx = ray_tpu.init(num_cpus=2)
try:
    sdir = ctx.address_info["session_dir"]

    @ray_tpu.remote
    def f():
        return 1

    assert ray_tpu.get([f.remote() for _ in range(5)], timeout=60) \\
        == [1] * 5
    assert not events.REC.enabled
    assert not os.path.exists(os.path.join(sdir, "events")), \\
        os.listdir(os.path.join(sdir, "events"))
    assert spans() == []
    # legacy state-transition pairing still yields DURATION slices with
    # the recorder disarmed (the pre-recorder timeline behavior), but no
    # span-category events exist at all
    tl = ray_tpu.timeline()
    assert any(e["ph"] == "X" and e.get("cat") == "task_state"
               for e in tl)
    assert all(e.get("cat") in ("task_state", None) or e["ph"] == "M"
               for e in tl), [e for e in tl if e.get("cat")
                              not in ("task_state", None)][:3]
    print("SAMPLING_ZERO_OK")
finally:
    ray_tpu.shutdown()
""", env={"RAY_TPU_TASK_EVENT_SAMPLE_RATE": "0",
          "RAY_TPU_METRICS_EXPORT_PORT": "0"})
