"""Llama-2/3-family decoder-only transformer, TPU-first.

Design notes (why this is not a torch translation):
- Pure functional: params are a pytree of ``jnp.ndarray``; the forward pass is
  a jit-friendly function of (params, tokens). No module objects, no state.
- Every parameter carries *logical axis names* (see ``llama_logical_axes``) so
  the same model runs 1-chip or on any (data, fsdp, seq, tensor) mesh purely
  by changing the rule table — GSPMD inserts the collectives.
- Layers are stacked into single arrays (num_layers leading dim) and scanned
  with ``jax.lax.scan``: one compiled layer body regardless of depth, which
  keeps XLA compile time flat and enables per-layer remat.
- Attention dispatches to ``ray_tpu.ops`` (Pallas flash attention on TPU,
  reference einsum path elsewhere; ring attention when the seq axis > 1).
- bfloat16 activations / fp32 params+optimizer by default: MXU-native.

Reference capability being replaced: Train users bring HF torch models
(reference: python/ray/train/huggingface/, release/air_examples/gptj_deepspeed
_finetuning); here the model is framework-native.
"""

from __future__ import annotations

import dataclasses
from functools import partial
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp

from ray_tpu.ops.attention import attention
from ray_tpu.parallel.sharding import constrain


def _ring_seq_attention(q, k, v):
    """Sequence-parallel exact attention: shard_map over the ambient mesh's
    ``seq`` axis; kv chunks ride the ICI ring (ops.ring_attention)."""
    from ray_tpu.ops.ring_attention import ring_attention
    from ray_tpu.parallel.sharding import logical_to_spec

    qs = logical_to_spec(("batch", "seq", "heads", "head_dim"))
    fn = jax.shard_map(
        partial(ring_attention, axis_name="seq", causal=True),
        in_specs=(qs, qs, qs), out_specs=qs, check_vma=False)
    return fn(q, k, v)


@dataclasses.dataclass(frozen=True)
class LlamaConfig:
    vocab_size: int = 32000
    hidden: int = 4096
    mlp_hidden: int = 11008
    num_layers: int = 32
    num_heads: int = 32
    num_kv_heads: int = 32
    head_dim: int = 128
    max_seq_len: int = 4096
    rope_theta: float = 10000.0
    rms_eps: float = 1e-5
    dtype: Any = jnp.bfloat16      # activation dtype
    param_dtype: Any = jnp.float32
    remat: bool = True             # checkpoint each layer (HBM↔FLOPs trade)
    remat_policy: str = "dots"     # dots (save matmuls) | full (recompute all)
    attn_impl: str = "auto"        # auto | flash | reference | ring_seq

    @staticmethod
    def llama2_7b() -> "LlamaConfig":
        return LlamaConfig()

    @staticmethod
    def tiny(vocab_size: int = 256) -> "LlamaConfig":
        return LlamaConfig(vocab_size=vocab_size, hidden=128, mlp_hidden=352,
                           num_layers=2, num_heads=4, num_kv_heads=2,
                           head_dim=32, max_seq_len=256, remat=False)

    @staticmethod
    def debug_1l() -> "LlamaConfig":
        return LlamaConfig(vocab_size=128, hidden=64, mlp_hidden=176,
                           num_layers=1, num_heads=2, num_kv_heads=1,
                           head_dim=32, max_seq_len=128, remat=False)

    def flops_per_token(self, seq_len: Optional[int] = None) -> float:
        """Approximate fwd+bwd FLOPs/token: 6*N, plus the attention
        quadratic term 12*L*H*D*S when ``seq_len`` is given."""
        flops = 6.0 * self.num_params()
        if seq_len is not None:
            flops += (12.0 * self.num_layers * self.num_heads
                      * self.head_dim * seq_len)
        return flops

    def num_params(self) -> int:
        h, m, v = self.hidden, self.mlp_hidden, self.vocab_size
        qkv = h * (self.num_heads + 2 * self.num_kv_heads) * self.head_dim
        o = self.num_heads * self.head_dim * h
        mlp = 3 * h * m
        per_layer = qkv + o + mlp + 2 * h
        return self.num_layers * per_layer + 2 * v * h + h


def llama_logical_axes(cfg: LlamaConfig) -> Dict[str, Any]:
    """Pytree (same structure as params) of logical-axis tuples."""
    layer = {
        "wq": ("embed", "heads", "head_dim"),
        "wk": ("embed", "kv_heads", "head_dim"),
        "wv": ("embed", "kv_heads", "head_dim"),
        "wo": ("heads", "head_dim", "embed"),
        "w_gate": ("embed", "mlp"),
        "w_up": ("embed", "mlp"),
        "w_down": ("mlp", "embed"),
        "attn_norm": ("norm",),
        "mlp_norm": ("norm",),
    }
    # scanned layers carry a leading 'layers' dim — replicated (None)
    layers = {k: (None,) + v for k, v in layer.items()}
    return {
        "embed": ("vocab", "embed"),
        "layers": layers,
        "final_norm": ("norm",),
        "lm_head": ("embed", "vocab"),
    }


def init_llama(cfg: LlamaConfig, key: jax.Array) -> Dict[str, Any]:
    """Initialize params (truncated-normal fan-in scaling, fp32)."""
    h, m = cfg.hidden, cfg.mlp_hidden
    nh, nkv, hd, L = cfg.num_heads, cfg.num_kv_heads, cfg.head_dim, cfg.num_layers
    ks = jax.random.split(key, 10)
    pd = cfg.param_dtype

    def norm_init(shape, k, fan_in):
        scale = fan_in ** -0.5
        return (jax.random.truncated_normal(k, -2, 2, shape, jnp.float32)
                * scale).astype(pd)

    layers = {
        "wq": norm_init((L, h, nh, hd), ks[0], h),
        "wk": norm_init((L, h, nkv, hd), ks[1], h),
        "wv": norm_init((L, h, nkv, hd), ks[2], h),
        "wo": norm_init((L, nh, hd, h), ks[3], nh * hd),
        "w_gate": norm_init((L, h, m), ks[4], h),
        "w_up": norm_init((L, h, m), ks[5], h),
        "w_down": norm_init((L, m, h), ks[6], m),
        "attn_norm": jnp.ones((L, h), pd),
        "mlp_norm": jnp.ones((L, h), pd),
    }
    return {
        "embed": norm_init((cfg.vocab_size, h), ks[7], 1.0),
        "layers": layers,
        "final_norm": jnp.ones((h,), pd),
        "lm_head": norm_init((h, cfg.vocab_size), ks[8], h),
    }


def _rms_norm(x: jax.Array, w: jax.Array, eps: float) -> jax.Array:
    dt = x.dtype
    x = x.astype(jnp.float32)
    x = x * jax.lax.rsqrt(jnp.mean(x * x, axis=-1, keepdims=True) + eps)
    return (x * w.astype(jnp.float32)).astype(dt)


def _rope(x: jax.Array, positions: jax.Array, theta: float) -> jax.Array:
    """x: [B, S, H, D]; rotate pairs (d, d + D/2) — llama convention."""
    d = x.shape[-1]
    half = d // 2
    freq = theta ** (-jnp.arange(0, half, dtype=jnp.float32) / half)
    angles = positions[..., None].astype(jnp.float32) * freq  # [B,S,half]
    cos = jnp.cos(angles)[:, :, None, :]
    sin = jnp.sin(angles)[:, :, None, :]
    x1, x2 = x[..., :half], x[..., half:]
    out = jnp.concatenate(
        [x1 * cos - x2 * sin, x2 * cos + x1 * sin], axis=-1)
    return out.astype(x.dtype)


def _layer(cfg: LlamaConfig, x: jax.Array, lp: Dict[str, jax.Array],
           positions: jax.Array, kv_cache=None,
           cache_index: Optional[jax.Array] = None):
    """One transformer block. x: [B, S, H_model]."""
    dt = cfg.dtype
    # --- attention ---
    h = _rms_norm(x, lp["attn_norm"], cfg.rms_eps)
    q = jnp.einsum("bsh,hnd->bsnd", h, lp["wq"].astype(dt))
    k = jnp.einsum("bsh,hnd->bsnd", h, lp["wk"].astype(dt))
    v = jnp.einsum("bsh,hnd->bsnd", h, lp["wv"].astype(dt))
    q = _rope(q, positions, cfg.rope_theta)
    k = _rope(k, positions, cfg.rope_theta)
    q = constrain(q, ("batch", "seq", "heads", None))
    k = constrain(k, ("batch", "seq", "kv_heads", None))
    new_cache = None
    if kv_cache is not None:
        ck, cv = kv_cache  # [B, max_S, nkv, d]
        ck = jax.lax.dynamic_update_slice_in_dim(ck, k, cache_index, axis=1)
        cv = jax.lax.dynamic_update_slice_in_dim(cv, v, cache_index, axis=1)
        k, v = ck, cv
        new_cache = (ck, cv)
        attn_out = attention(q, k, v, impl="reference", causal=True,
                             q_offset=cache_index)
    else:
        if cfg.attn_impl == "ring_seq":
            attn_out = _ring_seq_attention(q, k, v)
        else:
            attn_out = attention(q, k, v, impl=cfg.attn_impl, causal=True)
    attn_out = constrain(attn_out, ("batch", "seq", "heads", None))
    x = x + jnp.einsum("bsnd,ndh->bsh", attn_out, lp["wo"].astype(dt))
    # --- mlp (SwiGLU) ---
    h = _rms_norm(x, lp["mlp_norm"], cfg.rms_eps)
    gate = jnp.einsum("bsh,hm->bsm", h, lp["w_gate"].astype(dt))
    up = jnp.einsum("bsh,hm->bsm", h, lp["w_up"].astype(dt))
    act = constrain(jax.nn.silu(gate) * up, ("batch", "seq", "mlp"))
    x = x + jnp.einsum("bsm,mh->bsh", act, lp["w_down"].astype(dt))
    x = constrain(x, ("batch", "seq", "embed"))
    return x, new_cache


def llama_decode(
    params: Dict[str, Any],
    tokens: jax.Array,
    cfg: LlamaConfig,
    kv_caches,
    cache_index: jax.Array,
    *,
    positions: Optional[jax.Array] = None,
) -> Tuple[jax.Array, list]:
    """Incremental decode: tokens [B, S] appended to the kv caches at
    ``cache_index`` → (logits [B, S, V] fp32, updated caches). Python loop
    over layers so each layer's cache updates functionally in place."""
    B, S = tokens.shape
    if positions is None:
        positions = jnp.broadcast_to(
            jnp.arange(S, dtype=jnp.int32) + cache_index, (B, S))
    x = jnp.take(params["embed"], tokens, axis=0).astype(cfg.dtype)
    new_caches = []
    for i in range(cfg.num_layers):
        lp = jax.tree.map(lambda a: a[i], params["layers"])
        x, c = _layer(cfg, x, lp, positions, kv_caches[i], cache_index)
        new_caches.append(c)
    x = _rms_norm(x, params["final_norm"], cfg.rms_eps)
    logits = jnp.einsum("bsh,hv->bsv", x, params["lm_head"].astype(cfg.dtype))
    return logits.astype(jnp.float32), new_caches


def llama_forward(
    params: Dict[str, Any],
    tokens: jax.Array,
    cfg: LlamaConfig,
    *,
    positions: Optional[jax.Array] = None,
) -> jax.Array:
    """tokens [B, S] int32 → logits [B, S, V] (fp32). Layers run under
    ``lax.scan`` with optional per-layer remat. For kv-cache decoding use
    ``llama_decode``."""
    B, S = tokens.shape
    if positions is None:
        positions = jnp.broadcast_to(jnp.arange(S, dtype=jnp.int32), (B, S))
    x = jnp.take(params["embed"], tokens, axis=0).astype(cfg.dtype)
    x = constrain(x, ("batch", "seq", "embed"))

    body = partial(_layer, cfg)

    def scan_fn(carry, lp):
        y, _ = body(carry, lp, positions)
        return y, None

    if cfg.remat:
        # "dots": keep matmul outputs, recompute elementwise — near-zero
        # extra MXU work for most of full remat's memory win. "full":
        # recompute everything (longest-context fallback).
        if cfg.remat_policy not in ("dots", "full"):
            raise ValueError(
                f"remat_policy {cfg.remat_policy!r}: expected 'dots'|'full'")
        policy = (jax.checkpoint_policies.nothing_saveable
                  if cfg.remat_policy == "full"
                  else jax.checkpoint_policies.dots_with_no_batch_dims_saveable)
        scan_fn = jax.checkpoint(scan_fn, policy=policy)
    x, _ = jax.lax.scan(scan_fn, x, params["layers"])
    x = _rms_norm(x, params["final_norm"], cfg.rms_eps)
    logits = jnp.einsum("bsh,hv->bsv", x, params["lm_head"].astype(cfg.dtype))
    return logits.astype(jnp.float32)


def llama_loss(params: Dict[str, Any], batch: Dict[str, jax.Array],
               cfg: LlamaConfig) -> jax.Array:
    """Next-token cross-entropy; batch = {tokens [B,S]} or {inputs, targets}."""
    if "targets" in batch:
        inputs, targets = batch["inputs"], batch["targets"]
        mask = batch.get("mask")
    else:
        inputs, targets = batch["tokens"][:, :-1], batch["tokens"][:, 1:]
        mask = None
    logits = llama_forward(params, inputs, cfg)
    logp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
    nll = -jnp.take_along_axis(logp, targets[..., None], axis=-1)[..., 0]
    if mask is not None:
        return jnp.sum(nll * mask) / jnp.maximum(jnp.sum(mask), 1.0)
    return jnp.mean(nll)
