"""Unit tests for kernel primitives: ids, resources, config, serialization.
(reference test strategy: SURVEY §4 tier 1 — pure unit tests, no cluster)"""

import os
import pickle

import numpy as np
import pytest

from ray_tpu._private import serialization as ser
from ray_tpu._private.config import CONFIG
from ray_tpu._private.ids import ActorID, JobID, ObjectID, TaskID, WorkerID
from ray_tpu._private.resources import NodeResources, ResourceSet


class TestIds:
    def test_roundtrip(self):
        t = TaskID.from_random()
        assert TaskID.from_hex(t.hex()) == t
        assert len(t.binary()) == 16

    def test_object_id_structure(self):
        t = TaskID.from_random()
        o = ObjectID.for_task_return(t, 3)
        assert o.task_id() == t
        assert o.return_index() == 3
        assert not o.is_put()

    def test_put_id(self):
        w = WorkerID.from_random()
        o = ObjectID.from_put(7, w)
        assert o.is_put()
        assert o.return_index() == 7

    def test_nil(self):
        assert JobID.nil().is_nil()
        assert not JobID.from_random().is_nil()

    def test_actor_task_id_prefix(self):
        a = ActorID.from_random()
        t1 = TaskID.for_actor_task(a, 1)
        t2 = TaskID.for_actor_task(a, 2)
        assert t1.binary()[:8] == t2.binary()[:8]
        assert t1 != t2

    def test_pickle(self):
        t = TaskID.from_random()
        assert pickle.loads(pickle.dumps(t)) == t


class TestResources:
    def test_fixed_point_exact(self):
        rs = ResourceSet({"CPU": 0.1})
        for _ in range(9):
            rs.add(ResourceSet({"CPU": 0.1}))
        assert rs.get("CPU") == 1.0

    def test_fits_and_subtract(self):
        avail = ResourceSet({"CPU": 4, "TPU": 8})
        req = ResourceSet({"CPU": 2, "TPU": 4})
        assert req.fits(avail)
        assert avail.subtract(req)
        assert avail.get("TPU") == 4
        assert not ResourceSet({"TPU": 8}).fits(avail)
        assert not avail.subtract(ResourceSet({"TPU": 8}))

    def test_node_resources_instances(self):
        nr = NodeResources(ResourceSet({"CPU": 4, "TPU": 4}),
                           accelerator_ids={"TPU": [0, 1, 2, 3]})
        got = nr.allocate(ResourceSet({"TPU": 2, "CPU": 1}), owner="w1")
        assert got["TPU"] == [0, 1]
        assert nr.available.get("TPU") == 2
        nr.release(ResourceSet({"TPU": 2, "CPU": 1}), owner="w1")
        assert sorted(nr.free_instances["TPU"]) == [0, 1, 2, 3]

    def test_utilization(self):
        nr = NodeResources(ResourceSet({"CPU": 4}))
        assert nr.utilization() == 0.0
        nr.allocate(ResourceSet({"CPU": 3}))
        assert abs(nr.utilization() - 0.75) < 1e-9

    def test_wire_roundtrip(self):
        nr = NodeResources(ResourceSet({"CPU": 4, "custom": 1.5}),
                           labels={"zone": "a"})
        nr2 = NodeResources.from_wire(nr.to_wire())
        assert nr2.total == nr.total
        assert nr2.labels == {"zone": "a"}


class TestConfig:
    def test_defaults_and_env_override(self):
        assert CONFIG.inline_object_max_size_bytes > 0
        os.environ["RAY_TPU_gossip_period_ms"] = "123"
        try:
            assert CONFIG.gossip_period_ms == 123
        finally:
            del os.environ["RAY_TPU_gossip_period_ms"]

    def test_unknown_flag(self):
        with pytest.raises(AttributeError):
            CONFIG.not_a_flag


class TestSerialization:
    def test_roundtrip_basics(self):
        ctx = ser.SerializationContext()
        for value in [1, "x", {"a": [1, 2]}, None, (1, 2), {3, 4}]:
            sobj = ctx.serialize(value)
            assert ctx.deserialize(memoryview(sobj.to_bytes())) == value

    def test_numpy_zero_copy_out_of_band(self):
        ctx = ser.SerializationContext()
        arr = np.arange(100_000, dtype=np.float64)
        sobj = ctx.serialize(arr)
        # bare contiguous arrays take the typed zero-copy path (ISSUE 9:
        # header + raw buffer, no pickle at all)
        assert isinstance(sobj, ser.ZeroCopyArray)
        out = ctx.deserialize(memoryview(sobj.to_bytes()))
        np.testing.assert_array_equal(arr, out)
        # arrays nested in containers still ride pickle-5 out-of-band
        # buffers (no inline copy into the pickle stream)
        sobj = ctx.serialize({"w": arr})
        assert len(sobj.buffers) >= 1
        out = ctx.deserialize(memoryview(sobj.to_bytes()))
        np.testing.assert_array_equal(arr, out["w"])

    def test_closure(self):
        ctx = ser.SerializationContext()
        y = 10
        sobj = ctx.serialize(lambda x: x + y)
        fn = ctx.deserialize(memoryview(sobj.to_bytes()))
        assert fn(5) == 15

    def test_jax_array_crosses_as_numpy(self):
        import jax.numpy as jnp

        ctx = ser.SerializationContext()
        arr = jnp.arange(16)
        sobj = ctx.serialize({"x": arr})
        out = ctx.deserialize(memoryview(sobj.to_bytes()))
        np.testing.assert_array_equal(np.asarray(arr), out["x"])


class TestObjectStoreLocal:
    def test_create_seal_get(self, tmp_path):
        from ray_tpu._private.object_store import StoreClient

        c = StoreClient(str(tmp_path / "store"))
        oid = ObjectID.from_put(1, WorkerID.from_random())
        data = os.urandom(4096)
        c.put_bytes(oid, data)
        view = c.get_view(oid)
        assert bytes(view[:4096]) == data

    def test_eviction_and_spill(self, tmp_path, monkeypatch):
        from ray_tpu._private.object_store import StoreDirectory

        # spilling is the tmpfs backend's mechanism; the native arena
        # evicts internally instead (covered by test_native_store.py)
        monkeypatch.setenv("RAY_TPU_STORE_BACKEND", "tmpfs")
        d = StoreDirectory(str(tmp_path / "store"), capacity=10_000)
        ids = []
        for i in range(5):
            oid = ObjectID.from_put(i + 1, WorkerID.from_random())
            d.client.put_bytes(oid, bytes(3000))
            d.on_sealed(oid.hex(), 3000)
            ids.append(oid)
        # capacity 10k, 5*3k = 15k: oldest evicted
        assert d.used <= 10_000
        assert d.num_evictions > 0
        # pin everything, next insert must spill
        for oid in ids:
            if d.contains(oid.hex()):
                d.pin(oid.hex())
        oid = ObjectID.from_put(99, WorkerID.from_random())
        d.client.put_bytes(oid, bytes(9000))
        d.on_sealed(oid.hex(), 9000)
        assert d.num_spills > 0
        # spilled objects are restorable
        spilled = [h for h in [o.hex() for o in ids] if d.is_spilled(h)]
        if spilled:
            assert d.restore(spilled[0])
            assert d.client.get_view(ObjectID.from_hex(spilled[0])) is not None
