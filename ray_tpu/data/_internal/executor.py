"""Streaming executor.

Reference: python/ray/data/_internal/execution/streaming_executor.py —
a daemon thread runs a scheduling loop (``_scheduling_loop_step``
:241) that polls operator completions, moves bundles downstream, and
dispatches new tasks on the operator chosen by
``select_operator_to_run`` (streaming_executor_state.py:501) under
backpressure. We keep the same shape: bounded in-flight work per operator,
bounded final-output buffer so a slow consumer (the training loop) throttles
upstream reads instead of buffering the dataset in RAM.
"""

from __future__ import annotations

import queue
import threading
import time
from typing import Dict, List, Optional, Tuple

from ray_tpu.data._internal.physical import (
    PhysicalOperator, RefBundle, UnionOperator, ZipOperator)


class Topology:
    """Operators in topological order plus edges (who feeds whom)."""

    def __init__(self):
        self.ops: List[PhysicalOperator] = []
        self.edges: Dict[int, List[Tuple[int, str]]] = {}  # src -> (dst, port)

    def add(self, op: PhysicalOperator) -> int:
        self.ops.append(op)
        return len(self.ops) - 1

    def connect(self, src: int, dst: int, port: str = "in") -> None:
        self.edges.setdefault(src, []).append((dst, port))


class ExecutorStats:
    """Per-operator execution accounting, rendered like the reference's
    ``ds.stats()`` report (reference: python/ray/data/_internal/stats.py —
    DatasetStats.to_summary / OpRuntimeMetrics, wired through
    streaming_executor.py)."""

    def __init__(self):
        self.start_time = time.perf_counter()
        self.wall_s = 0.0
        self.per_op: List[Dict] = []

    @staticmethod
    def _fmt_bytes(n: int) -> str:
        for unit in ("B", "KB", "MB", "GB"):
            if n < 1024 or unit == "GB":
                return f"{n:.1f}{unit}" if unit != "B" else f"{n}B"
            n /= 1024
        return f"{n}B"

    def summary(self) -> str:
        lines = []
        for i, rec in enumerate(self.per_op):
            lines.append(
                f"Operator {i} {rec['name']}: {rec['tasks']} tasks "
                f"executed, {rec['blocks_out']} blocks produced in "
                f"{rec['wall_s']:.2f}s")
            lines.append(
                f"* Rows: {rec['rows_in']} in / {rec['rows_out']} out, "
                f"bytes: {self._fmt_bytes(rec['bytes_in'])} in / "
                f"{self._fmt_bytes(rec['bytes_out'])} out")
            lines.append(
                f"* Task time: {rec['exec_s']:.3f}s total"
                + (f", {rec['exec_s'] / rec['tasks']:.3f}s mean"
                   if rec['tasks'] else ""))
        lines.append(f"Dataset: {self.wall_s:.2f}s wall, "
                     f"{sum(r['tasks'] for r in self.per_op)} tasks")
        return "\n".join(lines)

    def to_dict(self) -> Dict:
        return {"wall_s": round(self.wall_s, 4), "ops": self.per_op}


class StreamingExecutor:
    """Drives a Topology on a daemon thread; final bundles land in a bounded
    queue consumed by ``iter_bundles``."""

    POLL_INTERVAL = 0.003

    def __init__(self, topology: Topology, stats: Optional[ExecutorStats] = None):
        from ray_tpu.data.context import DataContext
        from ray_tpu.data._internal.backpressure import (
            DEFAULT_BACKPRESSURE_POLICIES, ResourceManager)

        ctx = DataContext.get_current()
        self.topology = topology
        self.out: "queue.Queue[Optional[RefBundle]]" = queue.Queue()
        self.error: Optional[BaseException] = None
        self.stats = stats or ExecutorStats()
        self._stop = threading.Event()
        self._thread = threading.Thread(
            target=self._run, daemon=True, name="raytpu-data-exec")
        self.resource_manager = ResourceManager(
            topology, ctx.execution_memory_limit)
        policy_classes = (ctx.backpressure_policies
                          if ctx.backpressure_policies is not None
                          else DEFAULT_BACKPRESSURE_POLICIES)
        self.policies = [cls(topology, self) for cls in policy_classes]

    def start(self) -> "StreamingExecutor":
        self._thread.start()
        return self

    def shutdown(self) -> None:
        self._stop.set()
        self._thread.join(timeout=10)
        for op in self.topology.ops:
            if hasattr(op, "shutdown"):
                op.shutdown()

    # ---------------------------------------------------------------- loop
    def _run(self) -> None:
        try:
            while not self._stop.is_set():
                progressed = self._step()
                if self._all_done():
                    break
                if not progressed:
                    time.sleep(self.POLL_INTERVAL)
        except BaseException as e:  # surfaced via iter_bundles
            self.error = e
        finally:
            self._record_stats()
            self.out.put(None)

    def _step(self) -> bool:
        progressed = False
        ops = self.topology.ops
        # 1. poll completions + propagate outputs downstream.
        for i, op in enumerate(ops):
            op.poll()
            while op.output_queue:
                bundle = op.output_queue.popleft()
                dsts = self.topology.edges.get(i, [])
                if not dsts:
                    self.out.put(bundle)
                for dst, port in dsts:
                    target = ops[dst]
                    target._note_input(bundle)
                    if isinstance(target, ZipOperator) and port == "right":
                        target.add_right(bundle)
                    elif isinstance(target, ZipOperator):
                        target.add_left(bundle)
                    else:
                        target.input_queue.append(bundle)
                progressed = True
            # propagate completion edges
            if op.completed():
                for dst, port in self.topology.edges.get(i, []):
                    target = ops[dst]
                    if isinstance(target, UnionOperator):
                        if not getattr(op, f"_union_done_{dst}", False):
                            setattr(op, f"_union_done_{dst}", True)
                            target.branch_done()
                    elif isinstance(target, ZipOperator):
                        if port == "right":
                            target._right_done = True
                        else:
                            target._left_done = True
                    else:
                        target.inputs_complete = True
        # 2. dispatch under the backpressure-policy chain — most-downstream
        #    runnable op first, so the pipeline drains toward the consumer
        #    (reference: select_operator_to_run prefers ops with less queued
        #    output; the policy chain replaces the old hardcoded caps).
        for i in reversed(range(len(ops))):
            op = ops[i]
            while op.can_dispatch() and \
                    all(p.can_dispatch(i) for p in self.policies):
                op.dispatch()
                progressed = True
        return progressed

    def _all_done(self) -> bool:
        return all(op.completed() for op in self.topology.ops) and not any(
            op.output_queue for op in self.topology.ops)

    def _record_stats(self):
        self.stats.wall_s = time.perf_counter() - self.stats.start_time
        self.stats.per_op = [
            {"name": op.name, "tasks": op.tasks_launched,
             "rows": op.rows_out, "rows_in": op.rows_in,
             "rows_out": op.rows_out, "bytes_in": op.bytes_in,
             "bytes_out": op.bytes_out, "blocks_out": op.blocks_out,
             "exec_s": round(op.exec_time_s, 4),
             "wall_s": round(max(0.0, op.last_activity_t
                                 - op.first_activity_t), 4)}
            for op in self.topology.ops]

    # ------------------------------------------------------------- consume
    def iter_bundles(self):
        while True:
            bundle = self.out.get()
            if bundle is None:
                if self.error is not None:
                    raise self.error
                return
            yield bundle
