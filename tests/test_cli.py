"""CLI lifecycle (reference: python/ray/scripts/scripts.py —
``ray start/stop/status``; VERDICT r1 weak #5). Drives the real daemonized
head through subprocesses. One sequential lifecycle test: the CLI's address/
pid files are machine-global, so parallel clusters would stomp each other.
"""

import os
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _cli(*args, timeout=120):
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    # the CLI talks to real clusters; tests must not inherit a test mesh
    env.pop("JAX_PLATFORMS", None)
    return subprocess.run(
        [sys.executable, "-m", "ray_tpu.scripts.cli", *args],
        capture_output=True, text=True, timeout=timeout, env=env, cwd=REPO)


def test_cli_lifecycle():
    r = _cli("start", "--head", "--num-cpus", "2")
    assert r.returncode == 0, r.stdout + r.stderr
    try:
        assert os.path.exists("/tmp/ray_tpu_current_head")
        assert ":" in open("/tmp/ray_tpu_current_head").read()

        r = _cli("status")
        assert r.returncode == 0, r.stdout + r.stderr
        assert "ALIVE" in r.stdout and "CPU" in r.stdout

        r = _cli("list", "nodes")
        assert r.returncode == 0, r.stdout + r.stderr
        assert "ALIVE" in r.stdout

        r = _cli("summary", "tasks")
        assert r.returncode == 0, r.stdout + r.stderr
    finally:
        r = _cli("stop")
    assert r.returncode == 0, r.stdout + r.stderr

    # headless status is now valid (lifecycle view): it must report the
    # stopped cluster as fully reaped — zero live sessions
    r = _cli("status")
    assert r.returncode == 0, r.stdout + r.stderr
    assert "live sessions: 0" in r.stdout
