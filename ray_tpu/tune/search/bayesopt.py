"""Native Gaussian-process Bayesian optimization searcher (reference:
python/ray/tune/search/bayesopt/bayesopt_search.py wraps the external
`bayesian-optimization` package; this is a dependency-free equivalent so
the zero-egress deployment gets a model-based searcher beyond TPE).

Model: a GP with an RBF kernel over unit-cube-normalized numeric
dimensions (log-scaled where the domain is log-uniform), fit by Cholesky
with a small jitter; acquisition is Expected Improvement maximized over
random candidates. Categorical and sample_from dimensions are sampled
randomly and passed through (the reference's BayesOpt has the same
numeric-only restriction).
"""

from __future__ import annotations

import math
import random
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from ray_tpu.tune.search.sample import Categorical, Domain, Float, Function, Integer
from ray_tpu.tune.search.searcher import Searcher
from ray_tpu.tune.search.tpe import _flatten_space, _get_path, _set_path


def _is_log(domain: Domain) -> bool:
    return bool(getattr(domain, "log", False))


def _to_unit(domain: Domain, value: float) -> float:
    lo, hi = float(domain.lower), float(domain.upper)
    if _is_log(domain):
        lo, hi, value = math.log(lo), math.log(hi), math.log(max(value, 1e-300))
    if hi <= lo:
        return 0.5
    return min(max((value - lo) / (hi - lo), 0.0), 1.0)


def _from_unit(domain: Domain, u: float) -> Any:
    lo, hi = float(domain.lower), float(domain.upper)
    if _is_log(domain):
        value = math.exp(math.log(lo) + u * (math.log(hi) - math.log(lo)))
    else:
        value = lo + u * (hi - lo)
    if isinstance(domain, Integer):
        return int(min(max(round(value), domain.lower), domain.upper - 1))
    return float(min(max(value, domain.lower), domain.upper))


class _GP:
    """RBF-kernel GP posterior on the unit cube."""

    def __init__(self, X: np.ndarray, y: np.ndarray,
                 length_scale: float = 0.25, noise: float = 1e-4):
        self.X = X
        self.ls = length_scale
        self.y_mean = float(y.mean())
        self.y_std = float(y.std()) or 1.0
        yn = (y - self.y_mean) / self.y_std
        K = self._kernel(X, X) + noise * np.eye(len(X))
        jitter = 1e-8
        while True:
            try:
                self.L = np.linalg.cholesky(K + jitter * np.eye(len(X)))
                break
            except np.linalg.LinAlgError:
                jitter *= 10
                if jitter > 1.0:
                    raise
        self.alpha = np.linalg.solve(
            self.L.T, np.linalg.solve(self.L, yn))

    def _kernel(self, A: np.ndarray, B: np.ndarray) -> np.ndarray:
        d2 = ((A[:, None, :] - B[None, :, :]) ** 2).sum(-1)
        return np.exp(-0.5 * d2 / (self.ls ** 2))

    def posterior(self, Xs: np.ndarray) -> Tuple[np.ndarray, np.ndarray]:
        Ks = self._kernel(Xs, self.X)
        mu = Ks @ self.alpha
        v = np.linalg.solve(self.L, Ks.T)
        var = np.clip(1.0 - (v ** 2).sum(0), 1e-12, None)
        return (mu * self.y_std + self.y_mean,
                np.sqrt(var) * self.y_std)


def _expected_improvement(mu: np.ndarray, sigma: np.ndarray,
                          best: float, xi: float = 0.01) -> np.ndarray:
    z = (mu - best - xi) / sigma
    # standard-normal pdf/cdf without scipy
    pdf = np.exp(-0.5 * z * z) / math.sqrt(2 * math.pi)
    cdf = 0.5 * (1.0 + np.vectorize(math.erf)(z / math.sqrt(2.0)))
    return (mu - best - xi) * cdf + sigma * pdf


class BayesOptSearcher(Searcher):
    def __init__(self, space: Optional[Dict] = None,
                 metric: Optional[str] = None, mode: Optional[str] = None,
                 n_initial_points: int = 8, n_candidates: int = 256,
                 length_scale: float = 0.25, xi: float = 0.01,
                 seed: Optional[int] = None):
        super().__init__(metric, mode)
        self.space = space
        self.n_initial = n_initial_points
        self.n_candidates = n_candidates
        self.length_scale = length_scale
        self.xi = xi
        self._rng = random.Random(seed)
        self._np_rng = np.random.default_rng(seed)
        self._live: Dict[str, Dict] = {}
        self._obs: List[Tuple[Dict[Tuple, Any], float]] = []

    def set_search_properties(self, metric, mode, config) -> bool:
        super().set_search_properties(metric, mode, config)
        if config and self.space is None:
            self.space = config
        return True

    def _numeric_dims(self, dims: Dict[Tuple, Domain]) -> Dict[Tuple, Domain]:
        return {p: d for p, d in dims.items()
                if isinstance(d, (Float, Integer))}

    def _suggest_flat(self, dims: Dict[Tuple, Domain]) -> Dict[Tuple, Any]:
        flat = {p: d.sample(self._rng) for p, d in dims.items()
                if isinstance(d, (Categorical, Function))}
        numeric = self._numeric_dims(dims)
        if not numeric:
            return flat
        obs = [(o, s) for o, s in self._obs
               if all(p in o for p in numeric)]
        if len(obs) < self.n_initial:
            flat.update({p: d.sample(self._rng)
                         for p, d in numeric.items()})
            return flat
        paths = sorted(numeric)
        X = np.array([[_to_unit(numeric[p], float(o[p])) for p in paths]
                      for o, _ in obs])
        sign = 1.0 if self.mode == "max" else -1.0
        y = sign * np.array([s for _, s in obs])
        gp = _GP(X, y, length_scale=self.length_scale)
        cand = self._np_rng.random((self.n_candidates, len(paths)))
        mu, sigma = gp.posterior(cand)
        ei = _expected_improvement(mu, sigma, float(y.max()), xi=self.xi)
        best = cand[int(ei.argmax())]
        for k, p in enumerate(paths):
            flat[p] = _from_unit(numeric[p], float(best[k]))
        return flat

    # ---------------------------------------------------------- interface
    def suggest(self, trial_id: str) -> Optional[Dict]:
        import copy

        if not self.space:
            return None
        dims = _flatten_space(self.space)
        flat = self._suggest_flat(dims)
        config = copy.deepcopy(
            {k: v for k, v in self.space.items()
             if not isinstance(v, Domain)})
        for path, value in flat.items():
            _set_path(config, path, value)
        self._live[trial_id] = config
        return config

    def on_trial_complete(self, trial_id, result=None, error=False) -> None:
        config = self._live.pop(trial_id, None)
        if error or not result or self.metric not in result or \
                config is None:
            return
        flat = {}
        for path in _flatten_space(self.space):
            try:
                flat[path] = _get_path(config, path)
            except (KeyError, TypeError):
                pass
        self._obs.append((flat, float(result[self.metric])))
