"""In-process multi-node test cluster.

Parity with the reference's test fixture (reference:
``python/ray/cluster_utils.py:108``): boots a head plus any number of
additional node agents as separate local processes sharing one session, so
multi-node scheduling, spillback, object transfer and failover are testable on
one machine (SURVEY §4 tier-2 strategy).
"""

from __future__ import annotations

import time
from typing import Dict, List, Optional

from ray_tpu._private.node import Node


class Cluster:
    def __init__(self, initialize_head: bool = True,
                 head_node_args: Optional[Dict] = None):
        self.head_node: Optional[Node] = None
        self.worker_nodes: List[Node] = []
        if initialize_head:
            self.add_node(**(head_node_args or {}))

    @property
    def address(self) -> str:
        return f"127.0.0.1:{self.head_node.head_port}"

    @property
    def session_dir(self) -> str:
        return self.head_node.session_dir

    def add_node(self, num_cpus: Optional[int] = None,
                 num_tpus: Optional[int] = None,
                 resources: Optional[Dict[str, float]] = None,
                 labels: Optional[Dict[str, str]] = None,
                 object_store_memory: Optional[int] = None,
                 env: Optional[Dict[str, str]] = None) -> Node:
        res = dict(resources or {})
        if num_cpus is not None:
            res["CPU"] = float(num_cpus)
        if num_tpus is not None:
            res["TPU"] = float(num_tpus)
        if self.head_node is None:
            node = Node(head=True, resources=res or None, labels=labels,
                        object_store_memory=object_store_memory)
            node.start()
            self.head_node = node
        else:
            node = Node(
                head=False,
                head_host="127.0.0.1",
                head_port=self.head_node.head_port,
                resources=res or None,
                labels=labels,
                object_store_memory=object_store_memory,
                session_dir=self.head_node.session_dir,
            )
            node.start()
            self.worker_nodes.append(node)
        return node

    def remove_node(self, node: Node, allow_graceful: bool = True) -> None:
        if node is self.head_node:
            raise ValueError("use shutdown() to remove the head node")
        node.stop()
        if node in self.worker_nodes:
            self.worker_nodes.remove(node)

    def wait_for_nodes(self, timeout: float = 30.0) -> None:
        """Block until every started node is registered and alive."""
        import ray_tpu

        expected = 1 + len(self.worker_nodes)
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            try:
                alive = [n for n in ray_tpu.nodes() if n["alive"]]
                if len(alive) >= expected:
                    return
            except Exception:
                pass
            time.sleep(0.1)
        raise TimeoutError(f"cluster did not reach {expected} nodes")

    def shutdown(self) -> None:
        for node in self.worker_nodes:
            node.stop()
        self.worker_nodes.clear()
        if self.head_node is not None:
            self.head_node.stop(cleanup_session=True)
            self.head_node = None
