"""Multiplexed direct-call plane + shm local RPC (ISSUE 11).

Unit layers (no cluster): ShmRing wraparound/full-ring refusal, the
cross-lane frame orderer (in-order, buffering, gap give-up), fair
round-robin interleaving across streams on a shared session (fake
client), session-scoped BatchItems demux, per-stream close semantics
(typed StreamClosedError, siblings + session survive), ring-full →
TCP fallback with the seq preserved, and the ShmAttach server-side
decline ladder (disabled / cross-node / no arena / foreign paths).

Integration: same-node actor calls measurably ride the shm lane with
byte-identical results while the worker keeps jax unimported; a tiny
max-frame knob forces constant lane alternation and execution order
still matches submission order (the reorder stage's contract); kill -9
of the peer mid-multiplexed-call surfaces a typed error promptly with
no hang; with the lane disabled everything runs on pure TCP.
"""

import asyncio
import os
import signal
import time

import pytest

import ray_tpu
from ray_tpu._private import shm_rpc
from ray_tpu._private.mux import (
    MuxSession, StreamClosedError, _FrameOrderer, handle_shm_attach,
    handle_shm_detach)
from ray_tpu._private.shm_rpc import SHM_STATS, ShmRing


# ---------------------------------------------------------------------------
# unit: ring
# ---------------------------------------------------------------------------
class TestShmRing:
    def test_wraparound_byte_identical(self, tmp_path):
        import random

        path = str(tmp_path / "ring")
        producer = ShmRing(path, capacity=512, create=True)
        consumer = ShmRing(path)  # second mapping = the peer process
        rng = random.Random(7)
        sent, recvd = [], []
        for i in range(3000):
            frame = bytes([i % 251]) * rng.randint(0, 200)
            while not producer.try_write(frame):
                recvd.extend(consumer.read_frames())
            sent.append(frame)
        recvd.extend(consumer.read_frames())
        assert recvd == sent

    def test_full_ring_refuses_not_corrupts(self, tmp_path):
        ring = ShmRing(str(tmp_path / "r"), capacity=128, create=True)
        peer = ShmRing(str(tmp_path / "r"))
        assert ring.try_write(b"a" * 100)
        assert not ring.try_write(b"b" * 100)  # no room: refused
        assert peer.read_frames() == [b"a" * 100]
        assert ring.try_write(b"b" * 100)  # space reclaimed
        assert peer.read_frames() == [b"b" * 100]

    def test_doorbell_waiting_protocol(self, tmp_path):
        ring = ShmRing(str(tmp_path / "r"), capacity=256, create=True)
        peer = ShmRing(str(tmp_path / "r"))
        # fresh ring: consumer assumed idle -> first write must bell
        assert ring.consumer_waiting()
        assert peer.read_frames() == []
        assert peer.arm_waiting() is True  # empty: safe to sleep
        ring.try_write(b"x")
        # parked consumer re-checking must refuse to sleep
        assert peer.arm_waiting() is False
        assert peer.read_frames() == [b"x"]


# ---------------------------------------------------------------------------
# unit: frame orderer
# ---------------------------------------------------------------------------
class TestFrameOrderer:
    def test_reorders_cross_lane_arrivals(self):
        async def run():
            got = []
            o = _FrameOrderer(asyncio.get_running_loop(), got.append, 5.0)
            o.feed({"q": 2, "v": "b"})   # shm lane raced ahead
            assert got == []             # held for the TCP frame
            o.feed({"q": 1, "v": "a"})
            assert [m["v"] for m in got] == ["a", "b"]
            o.feed({"q": 3, "v": "c"})
            o.feed({"v": "unstamped"})   # pre-attach frame: immediate
            assert [m.get("v") for m in got] == \
                ["a", "b", "c", "unstamped"]
            o.close()

        asyncio.run(run())

    def test_gap_gives_up_instead_of_wedging(self):
        async def run():
            got = []
            before = SHM_STATS["order_gap_flushes"]
            o = _FrameOrderer(asyncio.get_running_loop(), got.append, 0.05)
            o.feed({"q": 5, "v": "late"})  # q1-4 eaten by a fault rule
            await asyncio.sleep(0.15)
            assert [m["v"] for m in got] == ["late"]
            assert SHM_STATS["order_gap_flushes"] == before + 1
            # stream continues from past the gap
            o.feed({"q": 6, "v": "next"})
            assert [m["v"] for m in got] == ["late", "next"]
            o.close()

        asyncio.run(run())


# ---------------------------------------------------------------------------
# unit: mux session vs fake client
# ---------------------------------------------------------------------------
class _FakeClient:
    """AsyncRpcClient stand-in capturing frames in send order."""

    def __init__(self, loop):
        self._loop = loop
        self.connected = True
        self.sent = []
        self._next = 0
        self._batch_counter = 0

    def register_call(self):
        self._next += 1
        return self._next, self._loop.create_future()

    def send_msg_nowait(self, msg):
        self.sent.append(msg)
        return True

    def _send_frame(self, body, method):
        self.sent.append({"raw": body, "m": method})
        return True

    def start_idle_monitor(self, *a, **kw):
        pass


def _fake_session(loop):
    sess = MuxSession(None, "127.0.0.1", 0)
    sess.loop = loop
    sess.client = _FakeClient(loop)
    return sess


class TestMuxUnits:
    def test_chatty_stream_cannot_head_of_line_block(self, monkeypatch):
        monkeypatch.setenv("RAY_TPU_DIRECT_CALL_FAIR_FRAMES_PER_ROUND",
                           "4")

        async def run():
            sess = _fake_session(asyncio.get_running_loop())
            chatty = sess.open_stream("chatty")
            quiet = sess.open_stream("quiet")
            for i in range(40):
                chatty.push_nowait("Spam", i)
            quiet.push_nowait("OneCall", None)
            await asyncio.sleep(0)  # run the scheduled fair flush
            order = [m["s"] for m in sess.client.sent]
            assert len(order) == 41
            # quiet's single frame leaves within one quantum of the
            # chatty backlog, not behind all 40 frames
            assert order.index(quiet.sid) == 4
            # within-stream FIFO is preserved for the chatty stream
            chatty_payloads = [m["p"] for m in sess.client.sent
                               if m["s"] == chatty.sid]
            assert chatty_payloads == list(range(40))

        asyncio.run(run())

    def test_batch_router_demux_per_stream(self):
        async def run():
            sess = _fake_session(asyncio.get_running_loop())
            s1 = sess.open_stream("a1")
            s2 = sess.open_stream("a2")
            assert s1._stream_batches is s2._stream_batches
            got1, got2 = [], []
            b1, b2 = s1.next_batch_id(), s2.next_batch_id()
            assert b1 != b2  # session-scoped: no cross-stream collision
            s1._stream_batches[b1] = lambda i, r: got1.append((i, r))
            s2._stream_batches[b2] = lambda i, r: got2.append((i, r))
            sess._on_push("BatchItems", {"b": b1, "xs": [(0, "x")]})
            sess._on_push("BatchItems", {"b": b2, "xs": [(0, "y"),
                                                         (1, "z")]})
            sess._on_push("BatchItems", {"b": 999, "xs": [(0, "?")]})
            assert got1 == [(0, "x")]
            assert got2 == [(0, "y"), (1, "z")]

        asyncio.run(run())

    def test_per_stream_close_spares_siblings(self):
        async def run():
            sess = _fake_session(asyncio.get_running_loop())
            doomed = sess.open_stream("doomed")
            sibling = sess.open_stream("sibling")
            f1 = doomed.call_future("M", {})
            f2 = sibling.call_future("M", {})
            doomed.close()
            with pytest.raises(StreamClosedError):
                await f1
            assert not f2.done()  # sibling's call still in flight
            assert not sibling.closed
            assert sess.client.connected  # session survives
            # a closed stream fails fast instead of queueing silently
            with pytest.raises(StreamClosedError):
                await doomed.call("M", {})

        asyncio.run(run())

    def test_ring_full_falls_back_to_tcp_with_seq(self):
        async def run():
            sess = _fake_session(asyncio.get_running_loop())

            class _FullLane:
                closed = False

                def try_send(self, frame):
                    return False  # ring momentarily full

            sess.lane = _FullLane()
            stream = sess.open_stream("s")
            stream.push_nowait("M", {"x": 1})
            await asyncio.sleep(0)
            sent = sess.client.sent
            assert len(sent) == 1
            # fell back to the TCP lane, seq preserved for the reorder
            # stage on the receiver
            assert "raw" in sent[0]

        asyncio.run(run())


# ---------------------------------------------------------------------------
# unit: server-side attach decline ladder (arena unavailable etc.)
# ---------------------------------------------------------------------------
class _FakeConn:
    closed = False
    mux_demux = None

    def __init__(self):
        self.meta = {}


class TestAttachDeclines:
    def _attach(self, payload, node_id, store_dir):
        return asyncio.run(handle_shm_attach(
            None, _FakeConn(), payload, node_id, store_dir))

    def test_declines_cleanly(self, tmp_path, monkeypatch):
        store = str(tmp_path / "store")
        os.makedirs(store)
        # disabled
        monkeypatch.setenv("RAY_TPU_SHM_RPC_ENABLED", "0")
        assert self._attach({"node_id": "n1"}, "n1", store) == \
            {"ok": False, "reason": "disabled"}
        monkeypatch.setenv("RAY_TPU_SHM_RPC_ENABLED", "1")
        # cross-node caller
        r = self._attach({"node_id": "other"}, "n1", store)
        assert r["ok"] is False and r["reason"] == "cross-node"
        # arena unavailable
        r = self._attach({"node_id": "n1"}, "n1", None)
        assert r["ok"] is False and "arena" in r["reason"]
        # rendezvous paths outside the arena are refused
        evil = {k: "/etc/passwd" for k in
                ("ring_c2s", "ring_s2c", "bell_c2s", "bell_s2c")}
        r = self._attach({"node_id": "n1", "paths": evil}, "n1", store)
        assert r["ok"] is False and "bad path" in r["reason"]

    def test_detach_blocks_late_attach(self, tmp_path):
        """Client attach-timeout protocol: its ShmDetach must stop a
        still-queued attach from committing a lane nobody will read."""
        store = str(tmp_path / "store")
        os.makedirs(store)
        conn = _FakeConn()

        async def run():
            await handle_shm_detach(conn, {})
            r = await handle_shm_attach(None, conn, {"node_id": "n1"},
                                        "n1", store)
            assert r["ok"] is False and "detached" in r["reason"]

        asyncio.run(run())

    def test_modules_never_import_jax(self):
        import subprocess
        import sys

        proc = subprocess.run(
            [sys.executable, "-c",
             "import ray_tpu._private.mux, ray_tpu._private.shm_rpc;"
             "import sys; assert 'jax' not in sys.modules, 'jax leaked'"],
            capture_output=True, text=True, timeout=120)
        assert proc.returncode == 0, proc.stderr


# ---------------------------------------------------------------------------
# integration
# ---------------------------------------------------------------------------
@ray_tpu.remote
class Echo:
    def echo(self, x):
        return x

    def sleep(self, s):
        time.sleep(s)
        return "woke"

    def state(self):
        import sys

        return {"pid": os.getpid(), "jax": "jax" in sys.modules}


@ray_tpu.remote
class Seq:
    def __init__(self):
        self.log = []

    def add(self, i, payload):
        self.log.append(i)
        return len(payload)

    def log_so_far(self):
        return self.log


class TestShmIntegration:
    def test_same_node_calls_ride_shm_byte_identical(self, monkeypatch):
        monkeypatch.setenv("RAY_TPU_WORKER_POOL_WARM_TARGET", "2")
        ray_tpu.init(num_cpus=2)
        try:
            before_out = SHM_STATS["calls_out"]
            before_in = SHM_STATS["frames_in"]
            a = Echo.remote()
            payload = bytes(range(256)) * 37  # ~9.5 KB, rides inline
            back = ray_tpu.get(a.echo.remote(payload), timeout=120)
            assert back == payload  # byte-identical through the ring
            assert ray_tpu.get([a.echo.remote(i) for i in range(100)],
                               timeout=120) == list(range(100))
            # the driver measurably used the lane, both directions
            assert SHM_STATS["calls_out"] > before_out
            assert SHM_STATS["frames_in"] > before_in
            # ... while the worker (warm-pool contract) kept jax cold
            st = ray_tpu.get(a.state.remote(), timeout=60)
            assert st["jax"] is False
            ray_tpu.kill(a)
        finally:
            ray_tpu.shutdown()

    def test_lane_alternation_preserves_call_order(self, monkeypatch):
        """Force constant shm↔TCP alternation (tiny max-frame) and prove
        a sync actor still executes calls in submission order — the
        cross-lane seq/reorder contract, end to end."""
        monkeypatch.setenv("RAY_TPU_SHM_RPC_MAX_FRAME_BYTES", "1500")
        ray_tpu.init(num_cpus=2)
        try:
            before = SHM_STATS["fallback_oversize"]
            before_gaps = SHM_STATS["order_gap_flushes"]
            s = Seq.remote()
            refs = []
            for i in range(60):
                # alternate tiny and >1500B payloads: odd frames fall
                # back to TCP, even ones ride the ring
                payload = b"x" * (4000 if i % 2 else 8)
                refs.append(s.add.remote(i, payload))
            ray_tpu.get(refs, timeout=120)
            log = ray_tpu.get(s.log_so_far.remote(), timeout=60)
            assert log == list(range(60))
            assert SHM_STATS["fallback_oversize"] > before
            # order came from the seq/reorder stage, not from gap
            # give-ups (those would mean frames were lost or stalled)
            assert SHM_STATS["order_gap_flushes"] == before_gaps
            ray_tpu.kill(s)
        finally:
            ray_tpu.shutdown()

    def test_kill9_mid_call_typed_error_no_hang(self):
        from ray_tpu.exceptions import ActorDiedError

        ray_tpu.init(num_cpus=2)
        try:
            victim = Echo.remote()
            bystander = Echo.remote()
            pid = ray_tpu.get(victim.state.remote(), timeout=120)["pid"]
            assert ray_tpu.get(bystander.echo.remote(1), timeout=120) == 1

            @ray_tpu.remote
            def _noop():
                return None

            slow = victim.sleep.remote(30)  # guaranteed mid-call
            time.sleep(0.5)
            os.kill(pid, signal.SIGKILL)
            t0 = time.monotonic()
            with pytest.raises(ActorDiedError):
                # in-flight call on the killed peer's stream fails with
                # the typed error instead of riding a dead socket
                ray_tpu.get(slow, timeout=60)
            assert time.monotonic() - t0 < 55
            # no session/plane-wide damage: other peers answer promptly
            assert ray_tpu.get(bystander.echo.remote(2), timeout=60) == 2
            assert ray_tpu.get(_noop.remote(), timeout=120) is None
            ray_tpu.kill(bystander)
        finally:
            ray_tpu.shutdown()

    def test_disabled_lane_runs_pure_tcp(self, monkeypatch):
        monkeypatch.setenv("RAY_TPU_SHM_RPC_ENABLED", "0")
        ray_tpu.init(num_cpus=2)
        try:
            before = SHM_STATS["calls_out"]
            a = Echo.remote()
            assert ray_tpu.get([a.echo.remote(i) for i in range(20)],
                               timeout=120) == list(range(20))
            assert SHM_STATS["calls_out"] == before  # never attached
            ray_tpu.kill(a)
        finally:
            ray_tpu.shutdown()
