"""Lazy task/actor DAGs (reference: python/ray/dag/ — DAGNode
dag_node.py:25, InputNode/OutputNode, CompiledDAG
compiled_dag_node.py:141).

``fn.bind(*args)`` builds the graph lazily; ``dag.execute(input)`` walks it,
submitting each node as a task with upstream ObjectRefs as args (so the
object store pipelines the whole graph without materializing on the
driver). ``dag.experimental_compile()`` returns a :class:`CompiledDAG`:
the graph is planned ONCE, every edge becomes a pre-allocated
shared-memory :class:`~ray_tpu.experimental.channel.Channel`, and every
compute node runs a PERSISTENT executor loop in its worker/actor process
— repeat ``execute()`` calls cost channel writes/reads only, with zero
per-call task submissions (reference: compiled_dag_node.py:141 +
experimental/channel.py:171). On TPU the intended use is chaining jitted
stages whose arrays stay in shm between nodes.
"""

from __future__ import annotations

import time
from typing import Any, Dict, List, Optional, Tuple

import ray_tpu


class DAGNode:
    def __init__(self, bound_args: tuple, bound_kwargs: dict):
        self._bound_args = bound_args
        self._bound_kwargs = bound_kwargs

    # ------------------------------------------------------------ execute
    def execute(self, *input_args, **input_kwargs):
        """Run the whole DAG; returns the final ObjectRef (or value for
        InputNode-only graphs)."""
        cache: Dict[int, Any] = {}
        return self._execute_node(cache, input_args, input_kwargs)

    def _resolve_arg(self, arg, cache, input_args, input_kwargs):
        if isinstance(arg, DAGNode):
            return arg._execute_node(cache, input_args, input_kwargs)
        return arg

    def _execute_node(self, cache, input_args, input_kwargs):
        raise NotImplementedError

    def experimental_compile(self) -> "CompiledDAG":
        return CompiledDAG(self)

    def visualize(self, filename: Optional[str] = None) -> str:
        """GraphViz DOT text for the DAG (reference: dag_node.py
        visualization via graphviz — emitted here as dependency-free DOT;
        pipe to `dot -Tsvg` to render). Writes ``filename`` if given."""
        lines = ["digraph dag {", "  rankdir=LR;"]
        seen: Dict[int, str] = {}

        def label(n: "DAGNode") -> str:
            if isinstance(n, InputNode):
                raw = "INPUT"
            elif isinstance(n, InputAttributeNode):
                raw = f"INPUT[{n._key!r}]"
            elif isinstance(n, MultiOutputNode):
                raw = "OUTPUT"
            elif isinstance(n, FunctionNode):
                fn = n._remote_fn
                raw = getattr(fn, "__name__", None) or getattr(
                    getattr(fn, "_function", None), "__name__", "task")
            elif isinstance(n, ClassMethodNode):
                raw = f"{n._actor._class_name}.{n._method_name}"
            else:
                raw = type(n).__name__
            # DOT double-quoted strings: escape embedded quotes/backslashes
            return raw.replace("\\", "\\\\").replace('"', '\\"')

        def visit(n: "DAGNode") -> str:
            if id(n) in seen:
                return seen[id(n)]
            name = f"n{len(seen)}"
            seen[id(n)] = name
            shape = ("ellipse" if isinstance(
                n, (InputNode, InputAttributeNode, MultiOutputNode))
                else "box")
            lines.append(f'  {name} [label="{label(n)}", shape={shape}];')
            deps = list(n._bound_args) + list(n._bound_kwargs.values())
            if isinstance(n, InputAttributeNode):
                deps = [n._parent]
            for d in deps:
                if isinstance(d, DAGNode):
                    lines.append(f"  {visit(d)} -> {name};")
            return name

        visit(self)
        lines.append("}")
        dot = "\n".join(lines)
        if filename:
            with open(filename, "w") as f:
                f.write(dot)
        return dot


def _pack_input(input_args: tuple, input_kwargs: dict) -> Any:
    """The one input-packing rule shared by eager InputNode resolution
    and CompiledDAG.execute — the two paths must never diverge."""
    if len(input_args) == 1 and not input_kwargs:
        return input_args[0]
    if input_kwargs and not input_args:
        return input_kwargs
    return input_args


class InputNode(DAGNode):
    """Placeholder for execute()-time input (reference: dag/input_node.py).

    Supports ``with InputNode() as inp:`` for API parity, plus
    ``inp[key]`` / ``inp.attr`` projections (reference:
    dag/input_node.py InputAttributeNode) usable in both eager and
    compiled execution."""

    def __init__(self):
        super().__init__((), {})

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False

    def __getitem__(self, key) -> "InputAttributeNode":
        return InputAttributeNode(self, key, "getitem")

    def __getattr__(self, name: str) -> "InputAttributeNode":
        if name.startswith("_"):
            raise AttributeError(name)
        return InputAttributeNode(self, name, "getattr")

    def _execute_node(self, cache, input_args, input_kwargs):
        return _pack_input(input_args, input_kwargs)


class InputAttributeNode(DAGNode):
    """A projection of the runtime input — ``inp[0]``, ``inp["x"]``,
    ``inp.field`` (reference: dag/input_node.py InputAttributeNode)."""

    def __init__(self, parent: InputNode, key, kind: str):
        super().__init__((), {})
        self._parent = parent
        self._key = key
        self._kind = kind

    def _extract(self, value):
        if self._kind == "getattr":
            if isinstance(value, dict):
                return value[self._key]
            return getattr(value, self._key)
        return value[self._key]

    def _execute_node(self, cache, input_args, input_kwargs):
        return self._extract(
            self._parent._execute_node(cache, input_args, input_kwargs))


class FunctionNode(DAGNode):
    def __init__(self, remote_fn, args: tuple, kwargs: dict):
        super().__init__(args, kwargs)
        self._remote_fn = remote_fn

    def _execute_node(self, cache, input_args, input_kwargs):
        key = id(self)
        if key not in cache:
            args = [self._resolve_arg(a, cache, input_args, input_kwargs)
                    for a in self._bound_args]
            kwargs = {k: self._resolve_arg(v, cache, input_args,
                                           input_kwargs)
                      for k, v in self._bound_kwargs.items()}
            cache[key] = self._remote_fn.remote(*args, **kwargs)
        return cache[key]


class ClassMethodNode(DAGNode):
    def __init__(self, actor_handle, method_name: str, args: tuple,
                 kwargs: dict, opts: Optional[dict] = None):
        super().__init__(args, kwargs)
        self._actor = actor_handle
        self._method_name = method_name
        self._opts = opts

    def _execute_node(self, cache, input_args, input_kwargs):
        key = id(self)
        if key not in cache:
            args = [self._resolve_arg(a, cache, input_args, input_kwargs)
                    for a in self._bound_args]
            kwargs = {k: self._resolve_arg(v, cache, input_args,
                                           input_kwargs)
                      for k, v in self._bound_kwargs.items()}
            method = getattr(self._actor, self._method_name)
            if self._opts:
                method = method.options(**self._opts)
            cache[key] = method.remote(*args, **kwargs)
        return cache[key]


class MultiOutputNode(DAGNode):
    """Terminal node collecting several branches
    (reference: dag/output_node.py)."""

    def __init__(self, outputs: List[DAGNode]):
        super().__init__(tuple(outputs), {})

    def _execute_node(self, cache, input_args, input_kwargs):
        return [self._resolve_arg(o, cache, input_args, input_kwargs)
                for o in self._bound_args]


class _Sentinel:
    """Teardown marker: propagates through every channel so all stage
    loops exit at the same iteration index."""


class _StageError:
    """A stage exception travels the pipeline as a value (the loop stays
    alive — reference compiled DAGs tear down on error; keeping the
    pipeline healthy lets later executions proceed)."""

    def __init__(self, exc: BaseException):
        self.exc = exc


class _StopLoop(Exception):
    """Raised inside a stage loop when the DAG's force-stop token appears
    (teardown after a dead stage wedged the graceful sentinel path)."""


def _stop_requested(stop_id) -> bool:
    from ray_tpu._private import worker as worker_mod
    from ray_tpu._private.ids import ObjectID

    if stop_id is None:
        return False
    return worker_mod.global_worker.store.contains(ObjectID(stop_id))


def _read_with_stop(ch, stop_id):
    """Blocking channel read that stays interruptible: if an upstream
    stage died, the graceful sentinel can never arrive — the driver seals
    the stop token instead and the read resolves to a sentinel, so a
    USER actor hosting a loop is never wedged forever. The poll phase
    carries across retries so an idle loop settles into cheap sleeps."""
    phase = 0
    while True:
        try:
            return ch.read(timeout=2.0, _phase=phase)
        except TimeoutError as e:
            phase = getattr(e, "phase", phase)
            if _stop_requested(stop_id):
                return _Sentinel()


def _write_with_stop(ch, value, stop_id):
    """Blocking (backpressured) channel write, interruptible like reads.
    Channel.write only raises BEFORE writing, so retrying is safe."""
    phase = 0
    while True:
        try:
            ch.write(value, timeout=2.0, _phase=phase)
            return
        except TimeoutError as e:
            phase = getattr(e, "phase", phase)
            if _stop_requested(stop_id):
                raise _StopLoop()


def _multi_stage_body(stages, stop_id=None):
    """The persistent executor loop a compiled-DAG worker/actor runs.

    ``stages``: list of ``(call, args_desc, kwargs_desc, in_chs, out_chs)``
    in topological order (one entry for function stages; all of one
    actor's nodes share a single loop — a second blocking loop on the same
    actor would queue forever behind the first).

    Per iteration, per stage: read each distinct input channel ONCE (in
    fixed order), resolve bound args from read values + constants, run the
    call, write the result to every output channel. A sentinel read
    propagates to the stage's outputs; the loop exits after the pass so
    every channel is drained exactly once.
    """
    try:
        while True:
            stop = False
            for call, args_desc, kwargs_desc, in_chs, out_chs in stages:
                vals = [_read_with_stop(ch, stop_id) for ch in in_chs]
                if any(isinstance(v, _Sentinel) for v in vals):
                    stop = True
                    for ch in out_chs:
                        _write_with_stop(ch, _Sentinel(), stop_id)
                    continue
                err = next((v for v in vals if isinstance(v, _StageError)),
                           None)
                if err is None:
                    args = [vals[d[1]] if d[0] == "c" else d[1]
                            for d in args_desc]
                    kwargs = {k: (vals[d[1]] if d[0] == "c" else d[1])
                              for k, d in kwargs_desc.items()}
                    try:
                        result = call(*args, **kwargs)
                    except BaseException as e:  # noqa: BLE001 — crosses wire
                        result = _StageError(e)
                else:
                    result = err  # upstream failed: forward, don't call
                for ch in out_chs:
                    # a result that fails to SERIALIZE must forward as a
                    # _StageError, not kill the loop: a dead loop wedges
                    # every downstream read until the force-stop token
                    try:
                        _write_with_stop(ch, result, stop_id)
                    except _StopLoop:
                        raise
                    except BaseException as e:  # noqa: BLE001
                        try:
                            _write_with_stop(ch, _StageError(e), stop_id)
                        except _StopLoop:
                            raise
                        except BaseException:
                            # the exception itself is unserializable:
                            # forward a stringified stand-in
                            _write_with_stop(
                                ch,
                                _StageError(RuntimeError(
                                    f"{type(e).__name__}: {e}")),
                                stop_id)
            if stop:
                return "stopped"
    except _StopLoop:
        return "force-stopped"


def _actor_stage_apply(instance, specs, stop_id=None):
    """specs: list of (method_name, args_desc, kwargs_desc, in, out)."""
    return _multi_stage_body(
        [(getattr(instance, m), a, k, i, o) for m, a, k, i, o in specs],
        stop_id)


class _StageActor:
    """Dedicated executor process for a compiled function stage. A stage
    loop blocks its process for the DAG's lifetime, so it must NOT share a
    pooled task worker (the submitter pipelines tasks onto busy workers —
    two loops on one worker deadlock the pipeline). Hidden actors give
    each loop its own process, torn down with the DAG (the reference's
    compiled DAGs likewise run their loops inside dedicated actor
    processes, compiled_dag_node.py)."""

    def run(self, fn, args_desc, kwargs_desc, in_chs, out_chs,
            stop_id=None):
        return _multi_stage_body(
            [(fn, args_desc, kwargs_desc, in_chs, out_chs)], stop_id)


def _actor_node_id(handle) -> Optional[str]:
    """Node an actor currently lives on (from the head's actor table),
    or None when unknown (actor still PENDING / head unreachable)."""
    from ray_tpu._private import worker as worker_mod

    w = worker_mod.global_worker
    if w is None or not w.connected:
        return None
    try:
        view = w._acall(
            w.head.call("GetActor", {"actor_id": handle._actor_id.hex()}),
            timeout=5)
    except Exception:
        return None
    return (view or {}).get("node_id") or None


_STAGE_ACTOR_CLS = None


def _stage_actor_cls():
    global _STAGE_ACTOR_CLS
    if _STAGE_ACTOR_CLS is None:
        # zero-CPU so an N-stage pipeline fits any node
        _STAGE_ACTOR_CLS = ray_tpu.remote(num_cpus=0)(_StageActor)
    return _STAGE_ACTOR_CLS


class CompiledDAGRef:
    """Result handle for one ``CompiledDAG.execute`` call; ``ray_tpu.get``
    unwraps it (reference: compiled_dag_node.py CompiledDAGRef)."""

    def __init__(self, dag: "CompiledDAG", seq: int):
        self._dag = dag
        self._seq = seq

    def get(self, timeout: Optional[float] = None) -> Any:
        return self._dag._result_for(self._seq, timeout)

    async def get_async(self, timeout: Optional[float] = None) -> Any:
        """Await the result without blocking the event loop (reference:
        CompiledDAGRef await support for async serving callers).

        Polls in short chunks so asyncio cancellation (wait_for) takes
        effect between chunks — a cancelled get must not leave a zombie
        thread camped on the DAG's consumer lock, and any worker thread
        outliving the cancellation is bounded by one chunk."""
        import asyncio

        deadline = None if timeout is None else \
            time.monotonic() + timeout
        while True:
            chunk = 2.0 if deadline is None else min(
                2.0, max(0.05, deadline - time.monotonic()))
            try:
                return await asyncio.to_thread(self.get, chunk)
            except TimeoutError:
                if deadline is not None and \
                        time.monotonic() >= deadline:
                    raise

    def __await__(self):
        return self.get_async().__await__()

    # duck-typed hook for ray_tpu.get
    def _compiled_get(self, timeout: Optional[float] = None) -> Any:
        return self.get(timeout)

    def __repr__(self):
        return f"CompiledDAGRef(seq={self._seq})"


class CompiledDAG:
    """Channel-based precompiled execution (reference:
    compiled_dag_node.py:141).

    ``__init__`` plans the graph once: topological node order, one
    shared-memory channel per edge (plus driver input / output channels),
    then launches one persistent executor loop per compute node — function
    nodes on dedicated leased workers, actor-method nodes INSIDE their
    actor via the reserved ``__ray_apply__`` dispatch so state semantics
    match the eager path. ``execute()`` writes the input into the driver-
    fed channels and returns a :class:`CompiledDAGRef`; no tasks are
    submitted per call. Channel capacity bounds in-flight executions
    (backpressure = write blocks). Single-node scope, like the reference
    prototype.
    """

    def __init__(self, root: DAGNode, max_inflight: int = 8):
        from ray_tpu.experimental.channel import Channel

        self._root = root
        self._capacity = max_inflight
        self._torn_down = False
        self._seq = 0          # executions issued
        self._next_read = 0    # next seq to read from output channels
        self._buffered: Dict[int, Any] = {}
        self._partial_input = None    # (value, next channel idx) on timeout
        self._partial_read: list = []  # output values read so far this seq
        self._discard_seqs: set = set()  # voided executions to drop
        # SPSC bookkeeping is single-writer/single-reader state. Two
        # INDEPENDENT locks so a backpressured producer (execute holding
        # the input lock across a blocking channel write) can never starve
        # the consumer that would drain the outputs and unblock it:
        #   _in_mu:  _seq, _partial_input, input-channel writes
        #   _out_mu: _next_read, _partial_read, _buffered, output reads
        # (_discard_seqs crosses the two; set add/discard are GIL-atomic)
        import threading

        self._in_mu = threading.RLock()
        self._out_mu = threading.RLock()

        # ---- plan: collect nodes reachable from root (post-order = topo)
        order: List[DAGNode] = []
        seen: Dict[int, DAGNode] = {}

        def visit(n: DAGNode) -> None:
            if id(n) in seen:
                return
            seen[id(n)] = n
            for dep in n._bound_args:
                if isinstance(dep, DAGNode):
                    visit(dep)
            for dep in n._bound_kwargs.values():
                if isinstance(dep, DAGNode):
                    visit(dep)
            order.append(n)

        visit(root)
        if isinstance(root, InputNode):
            raise ValueError("InputNode cannot be the DAG root")
        compute = [n for n in order
                   if isinstance(n, (FunctionNode, ClassMethodNode))]
        if not compute:
            raise ValueError("compiled DAG needs at least one task/actor node")
        for n in order:
            if isinstance(n, MultiOutputNode) and n is not root:
                raise ValueError("MultiOutputNode must be the DAG root")
        # force-stop token: sealed by teardown when the graceful sentinel
        # path can't complete (a dead stage wedges downstream reads)
        import os as _os

        from ray_tpu._private.ids import ObjectID as _OID

        self._stop_id = _os.urandom(_OID.SIZE)

        # ---- channels: one per (producer, consumer-node) edge
        def mkch() -> Channel:
            return Channel(capacity=self._capacity)

        edge_ch: Dict[Tuple[int, int], Channel] = {}
        # driver-written channels: (channel, extractor) — the extractor
        # projects the execute() input for InputAttributeNode edges
        self._input_channels: List[Tuple[Channel, Any]] = []
        node_in: Dict[int, List[Channel]] = {}
        node_in_idx: Dict[int, Dict[int, int]] = {}  # node -> dep id -> pos
        for n in compute:
            ins: List[Channel] = []
            idx: Dict[int, int] = {}
            deps = [d for d in list(n._bound_args)
                    + list(n._bound_kwargs.values())
                    if isinstance(d, DAGNode)]
            for d in deps:
                if id(d) in idx:
                    continue
                ch = mkch()
                edge_ch[(id(d), id(n))] = ch
                idx[id(d)] = len(ins)
                ins.append(ch)
                if isinstance(d, InputAttributeNode):
                    self._input_channels.append((ch, d._extract))
                elif isinstance(d, InputNode):
                    self._input_channels.append((ch, None))
            if not ins:
                # constant-only stage: a driver-fed tick channel triggers
                # one iteration per execute (and carries the sentinel)
                ch = mkch()
                ins.append(ch)
                self._input_channels.append((ch, None))
            node_in[id(n)] = ins
            node_in_idx[id(n)] = idx

        # driver-read output channels (root, or each MultiOutput branch)
        self._output_channels: List[Channel] = []
        node_out: Dict[int, List[Channel]] = {id(n): [] for n in compute}
        if isinstance(root, MultiOutputNode):
            for branch in root._bound_args:
                if not isinstance(branch, (FunctionNode, ClassMethodNode)):
                    raise ValueError(
                        "MultiOutputNode branches must be task/actor nodes")
                ch = mkch()
                node_out[id(branch)].append(ch)
                self._output_channels.append(ch)
        else:
            ch = mkch()
            node_out[id(root)].append(ch)
            self._output_channels.append(ch)
        for (prod, cons), ch in edge_ch.items():
            if prod in node_out:  # InputNode edges are driver-written
                node_out[prod].append(ch)

        # ---- launch persistent loops (one dedicated stage actor per
        # function node; all of a user actor's nodes share ONE loop, in
        # topo order). Channels are node-local shm: every participant
        # MUST live on the driver's node — stage actors are pinned there
        # via node affinity, and a user actor on a different node is a
        # compile-time error instead of a read that hangs forever.
        from ray_tpu._private import worker as worker_mod

        driver_node = getattr(worker_mod.global_worker, "node_id", "")
        self._loop_refs = []
        self._stage_actors: List[Any] = []
        actor_specs: Dict[Any, List] = {}
        actor_handles: Dict[Any, Any] = {}
        checked_actors: set = set()
        for n in compute:
            if isinstance(n, ClassMethodNode):
                if n._actor._actor_id in checked_actors:
                    continue  # one GetActor RPC per actor, not per method
                checked_actors.add(n._actor._actor_id)
                actor_node = _actor_node_id(n._actor)
                if driver_node and actor_node and actor_node != driver_node:
                    raise ValueError(
                        f"compiled DAG actor {n._actor._class_name} "
                        f"(method {n._method_name!r}) lives on node "
                        f"{actor_node[:12]} but the driver is on "
                        f"{driver_node[:12]}: compiled-DAG channels are "
                        "node-local shared memory, so every participating "
                        "actor must be created on the driver's node (e.g. "
                        "with NodeAffinitySchedulingStrategy)")
        for n in compute:
            idx = node_in_idx[id(n)]

            def desc(v, idx=idx):
                return ("c", idx[id(v)]) if isinstance(v, DAGNode) \
                    else ("k", v)

            args_desc = [desc(a) for a in n._bound_args]
            kwargs_desc = {k: desc(v) for k, v in n._bound_kwargs.items()}
            if isinstance(n, FunctionNode):
                fn = n._remote_fn
                raw = getattr(fn, "_function", None) or fn
                stage_cls = _stage_actor_cls()
                if driver_node:
                    from ray_tpu.util.scheduling_strategies import (
                        NodeAffinitySchedulingStrategy)

                    stage_cls = stage_cls.options(
                        scheduling_strategy=NodeAffinitySchedulingStrategy(
                            driver_node))
                stage = stage_cls.remote()
                self._stage_actors.append(stage)
                ref = stage.run.remote(
                    raw, args_desc, kwargs_desc,
                    node_in[id(n)], node_out[id(n)], self._stop_id)
                self._loop_refs.append(ref)
            else:
                key = n._actor._actor_id
                actor_handles[key] = n._actor
                actor_specs.setdefault(key, []).append(
                    (n._method_name, args_desc, kwargs_desc,
                     node_in[id(n)], node_out[id(n)]))
        for key, specs in actor_specs.items():
            from ray_tpu.actor import ActorMethod

            apply_m = ActorMethod(actor_handles[key], "__ray_apply__")
            self._loop_refs.append(
                apply_m.remote(_actor_stage_apply, specs, self._stop_id))

    # -------------------------------------------------------------- execute
    def execute(self, *input_args, **input_kwargs) -> CompiledDAGRef:
        with self._in_mu:
            return self._execute_locked(input_args, input_kwargs)

    def _execute_locked(self, input_args, input_kwargs) -> CompiledDAGRef:
        if self._torn_down:
            raise RuntimeError("compiled DAG was torn down")
        input_val = _pack_input(input_args, input_kwargs)
        if self._partial_input is not None:
            # a previous execute timed out mid-write: finish delivering its
            # input FIRST so branches stay in lockstep. That voided call
            # never issued a ref, so its completed execution is discarded
            # transparently on the read side.
            val, idx = self._partial_input
            self._write_inputs(val, idx)  # progress saved if this raises
            self._partial_input = None
            self._discard_seqs.add(self._seq)
            self._seq += 1
        self._write_inputs(input_val, 0)
        ref = CompiledDAGRef(self, self._seq)
        self._seq += 1
        return ref

    async def execute_async(self, *input_args,
                            **input_kwargs) -> CompiledDAGRef:
        """execute() for asyncio callers: the (possibly backpressured)
        input-channel writes run off-loop (reference:
        compiled_dag_node.py execute_async)."""
        import asyncio
        from functools import partial

        return await asyncio.to_thread(
            partial(self.execute, *input_args, **input_kwargs))

    def _write_inputs(self, input_val: Any, start_idx: int) -> None:
        """Write one execution's input to every driver-fed channel,
        recording progress so a backpressure TimeoutError stays retry-safe
        (a partial write must never silently skew branch iterations)."""
        # project FIRST: a bad input (e.g. KeyError in an inp["x"]
        # extractor) must fail before ANY channel write, not mid-vector
        projected = [
            (extract(input_val) if extract is not None else input_val)
            for _ch, extract in self._input_channels[start_idx:]]
        for i, value in zip(range(start_idx, len(self._input_channels)),
                            projected):
            try:
                self._input_channels[i][0].write(value)
            except TimeoutError:
                if i > 0 or start_idx > 0:
                    # genuinely partial: must resume with THIS value
                    self._partial_input = (input_val, i)
                # else nothing was written — plain retry-safe backpressure
                raise

    def _result_for(self, seq: int, timeout: Optional[float]) -> Any:
        # honor a finite timeout on the LOCK acquisition too — a 0.5s get
        # must not wait forever behind another getter holding the lock in
        # an unbounded read
        deadline = None if timeout is None else time.monotonic() + timeout
        if not self._out_mu.acquire(
                timeout=-1 if timeout is None else timeout):
            raise TimeoutError(
                f"result for execution #{seq} blocked behind another "
                "consumer past the timeout")
        try:
            remaining = None if deadline is None else max(
                0.005, deadline - time.monotonic())
            return self._result_for_locked(seq, remaining)
        finally:
            self._out_mu.release()

    def _result_for_locked(self, seq: int, timeout: Optional[float]) -> Any:
        # one absolute deadline for the WHOLE call: each channel read gets
        # the time remaining, not a fresh copy of the user's timeout (a
        # get(timeout=t) over N channels × M buffered seqs must not be
        # able to block ~N*M*t)
        deadline = None if timeout is None else time.monotonic() + timeout
        if seq in self._buffered:
            out = self._buffered.pop(seq)
        else:
            if seq < self._next_read:
                raise ValueError(
                    f"result for execution #{seq} was already consumed")
            if self._torn_down:
                raise RuntimeError("compiled DAG was torn down")
            while self._next_read <= seq:
                out = self._read_output_vector(deadline)
                if self._next_read in self._discard_seqs:
                    # a voided (timed-out) execution's result: drop it
                    self._discard_seqs.discard(self._next_read)
                    self._next_read += 1
                    continue
                if self._next_read == seq:
                    self._next_read += 1
                    break
                self._buffered[self._next_read] = out
                self._next_read += 1
        errs = out if isinstance(out, list) else [out]
        for v in errs:
            if isinstance(v, _StageError):
                raise v.exc
        return out

    # between blocking-read chunks, check the stage loops for EARLY death
    # so a killed stage surfaces as an error instead of a hang
    _LIVENESS_POLL_S = 1.0

    def _raise_if_stage_dead(self) -> None:
        """A stage loop that completed while the DAG is live means a dead
        stage (loops only return at teardown): surface its error — a
        SIGKILL'd stage process otherwise leaves every downstream channel
        empty and ``CompiledDAGRef.get()`` blocked forever."""
        if self._torn_down or not self._loop_refs:
            return
        try:
            done, _ = ray_tpu.wait(list(self._loop_refs), num_returns=1,
                                   timeout=0)
        except Exception:
            return
        if not done or self._torn_down:
            return
        try:
            ray_tpu.get(done[0], timeout=5.0)
        except Exception as e:
            raise RuntimeError(
                "compiled DAG stage died mid-pipeline: "
                f"{type(e).__name__}: {e}") from e
        raise RuntimeError(
            "compiled DAG stage loop exited unexpectedly (worker killed "
            "or loop crashed); tear the DAG down and recompile")

    def _read_output_vector(self, deadline: Optional[float]) -> Any:
        """Read one value from every output channel. Partial progress is
        buffered across calls (``_partial_read``) so a user timeout on a
        slow branch stays retry-safe instead of skewing branch pairs.
        deadline=None blocks indefinitely, matching eager ray_tpu.get —
        but reads are chunked so dead stages are detected either way."""
        vals = self._partial_read
        while len(vals) < len(self._output_channels):
            ch = self._output_channels[len(vals)]
            phase = 0
            while True:
                remaining = None if deadline is None \
                    else deadline - time.monotonic()
                # an expired deadline still attempts one timeout=0 read:
                # get(timeout=0) is the documented nonblocking poll and
                # must return a READY result, not raise unconditionally
                chunk = self._LIVENESS_POLL_S if remaining is None \
                    else min(self._LIVENESS_POLL_S, max(0.0, remaining))
                try:
                    vals.append(ch.read(timeout=chunk, _phase=phase))
                    break
                except TimeoutError as e:
                    phase = getattr(e, "phase", phase)
                    if remaining is not None and remaining <= 0:
                        raise TimeoutError(
                            "compiled DAG result not ready within timeout")
                    self._raise_if_stage_dead()
        self._partial_read = []
        return vals if len(self._output_channels) > 1 else vals[0]

    # ------------------------------------------------------------- teardown
    def teardown(self, timeout: float = 30.0) -> None:
        """Stop every stage loop and release the channels."""
        if self._torn_down:
            return
        self._torn_down = True
        # the input channels are SPSC — the sentinel writes must not race
        # a concurrent execute's writes. If a wedged execute holds the
        # lock (blocked on backpressure), seal the stop token so the
        # pipeline unwedges; the writer's own timeout then releases it.
        if not self._in_mu.acquire(timeout=min(timeout, 5.0)):
            self._seal_stop_token()
            self._in_mu.acquire()
        try:
            for ch, _extract in self._input_channels:
                try:
                    ch.write(_Sentinel(), timeout=timeout)
                except Exception:
                    pass
        finally:
            self._in_mu.release()
        # drain pending results + the sentinel so every slot is consumed;
        # skip if a getter camps on the consumer lock (force-stop covers)
        deadline = time.monotonic() + timeout
        if self._out_mu.acquire(timeout=min(timeout, 5.0)):
            try:
                for ch in self._output_channels:
                    while time.monotonic() < deadline:
                        try:
                            v = ch.read(timeout=max(
                                0.1, deadline - time.monotonic()))
                        except Exception:
                            break
                        if isinstance(v, _Sentinel):
                            break
            finally:
                self._out_mu.release()
        try:
            ray_tpu.get(self._loop_refs, timeout=timeout)
        except Exception:
            # graceful sentinel drain failed (a stage died mid-pipeline and
            # can't forward its sentinel): seal the force-stop token so
            # every surviving loop — including loops INSIDE user actors —
            # unwedges within its 2 s read poll instead of blocking forever
            self._seal_stop_token()
            try:
                # loops poll the stop token every ~2s; don't exceed the
                # caller's budget (__del__ tears down with timeout=2)
                ray_tpu.get(self._loop_refs, timeout=min(timeout, 15.0))
            except Exception:
                pass
        for stage in self._stage_actors:
            try:
                ray_tpu.kill(stage)
            except Exception:
                pass
        self._stage_actors = []

    def _seal_stop_token(self) -> None:
        from ray_tpu._private import worker as worker_mod
        from ray_tpu._private.ids import ObjectID

        try:
            w = worker_mod.global_worker
            oid = ObjectID(self._stop_id)
            if not w.store.contains(oid):
                view, handle = w.store.create(oid, 1)
                view[0:1] = b"\x01"
                w.store.seal(oid, handle)
        except Exception:
            pass

    def __del__(self):
        try:
            if not self._torn_down:
                self.teardown(timeout=2.0)
        except Exception:
            pass
