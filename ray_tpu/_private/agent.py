"""Per-node agent (raylet analog).

Parity with the reference raylet (reference: ``src/ray/raylet/node_manager.h``,
``worker_pool.h``, ``local_task_manager.h``): one agent per node owning the
worker pool (spawn/lease/kill), the local resource accounting + lease-based
scheduler with spillback (reference: ``cluster_task_manager.cc:44``,
``hybrid_scheduling_policy.h:50``), the shared-memory store accounting
(reference: plasma + ``local_object_manager.h``), placement-group bundle
reservations (reference: ``placement_group_resource_manager.h``), and the
node-to-node object transfer plane (reference: ``object_manager.h:117``
Push/Pull chunking).

One asyncio process. Local clients (driver, workers) connect over a unix
socket; remote agents and spilled-back submitters connect over TCP.
"""

from __future__ import annotations

import asyncio
import logging
import os
import subprocess
import sys
import time
from collections import OrderedDict, deque
from typing import Any, Dict, List, Optional, Tuple

from ray_tpu._private import events as _events
from ray_tpu._private import lifecycle
from ray_tpu._private.async_util import (
    DecorrelatedJitterBackoff, spawn_tracked)
from ray_tpu._private.config import CONFIG
from ray_tpu._private.ids import ObjectID
from ray_tpu._private.object_store import StoreDirectory
from ray_tpu._private.protocol import (
    AsyncRpcClient, Connection, ConnectionPool, RawData, RpcServer,
    retry_call, set_fault_self_id)
from ray_tpu._private.pull_manager import PullManager
from ray_tpu._private.resources import (
    NodeResources, ResourceSet, label_constraints_match)


def _note_hist(hist: Dict[str, int], n: int) -> None:
    """Power-of-two batch-size histogram bucket (`1`,`2`,`4`,...,`128+`)."""
    bucket = 1
    while bucket < n and bucket < 128:
        bucket *= 2
    label = f"{bucket}+" if bucket == 128 and n > 128 else str(bucket)
    hist[label] = hist.get(label, 0) + 1


def _env_key_language(env_key):
    """Top-level "language" of a canonical runtime_env key, or None — a
    nested env_vars value spelled 'language' must not be mistaken for a
    cross-language lease (env keys are json with sorted keys,
    task_spec.runtime_env_key)."""
    if not env_key:
        return None
    try:
        import json as _json

        env = _json.loads(env_key)
    except Exception:
        return None
    lang = env.get("language") if isinstance(env, dict) else None
    return lang if isinstance(lang, str) else None


class NodeFencedError(Exception):
    """The head rejected this agent's registration: the node's incarnation
    was fenced after a death verdict (we were partitioned away and the
    cluster moved on). The only safe move is to stop existing — any lease
    we still hold or object we would still serve is a zombie."""


class _NeverLaunched:
    """Sentinel proc for spawns that failed before producing a process."""

    pid = None

    def poll(self):
        return 1

    def terminate(self):
        pass


class WorkerHandle:
    def __init__(self, worker_id: str, proc: Optional[subprocess.Popen]):
        self.worker_id = worker_id
        # None while the spawn sits in the admission queue (the agent caps
        # concurrent process startups like the reference raylet's
        # maximum_startup_concurrency, worker_pool.h)
        self.proc = proc
        self.launched_at: Optional[float] = None
        self.conn: Optional[Connection] = None  # registration connection
        self.direct_addr: Optional[Dict] = None  # {"host","port","unix"} for PushTask
        self.registered = asyncio.Event()
        # set when the agent observes the worker gone (exit handler or
        # watchdog eviction): liveness watchers await this instead of
        # polling — 1,000 live actors at a 0.5s poll each cost the agent
        # loop ~2,000 timer wakeups + proc.poll syscalls per second
        self.exited = asyncio.Event()
        self.leased_to: Optional[str] = None  # lease id
        self.assigned_resources: Optional[ResourceSet] = None
        self.is_actor = False
        self.actor_id: Optional[str] = None
        self.spawn_time = time.monotonic()
        self.idle_since = time.monotonic()
        # runtime_env this process has applied (None = pristine). A worker
        # that applied one env can never serve a different one (reference:
        # worker_pool keys processes by runtime-env hash, worker_pool.h).
        self.env_key: Optional[str] = None

    # set when the forkserver's death ledger reported this pid reaped —
    # authoritative even if the OS has recycled the pid (poll can't tell)
    force_dead = False

    @property
    def alive(self) -> bool:
        if self.force_dead:
            return False
        return self.proc is None or self.proc.poll() is None

    def terminate(self) -> None:
        """None-safe terminate (proc is None while spawn-queued)."""
        if self.proc is not None:
            try:
                self.proc.terminate()
            except Exception:
                pass

    def hard_kill(self) -> None:
        """SIGKILL — for workers that ignore SIGTERM (e.g. wedged in a
        native collective holding the GIL, where the Python-level signal
        handler never gets to run)."""
        if self.proc is not None:
            try:
                kill = getattr(self.proc, "kill", None)
                if kill is not None:
                    kill()
                elif getattr(self.proc, "pid", None):
                    os.kill(self.proc.pid, 9)
            except Exception:
                pass

    def mark_failed(self) -> None:
        """A launch that will never produce a process: flips `alive` to
        False so liveness watchers (actor resource release) resolve."""
        if self.proc is None:
            self.proc = _NeverLaunched()


class NodeAgent:
    def __init__(
        self,
        node_id: str,
        session_dir: str,
        store_dir: str,
        head_host: str,
        head_port: int,
        resources: Dict[str, float],
        labels: Optional[Dict[str, str]] = None,
        object_store_memory: Optional[int] = None,
    ):
        self.node_id = node_id
        # per-boot incarnation: strictly increases across restarts of an
        # agent under the same node_id, so the head can fence a dead
        # incarnation while letting a fresh boot rejoin (ns resolution —
        # two boots within one tick would defeat the fence)
        self.incarnation = time.time_ns()
        self.session_dir = session_dir
        self.head_host = head_host
        self.head_port = head_port
        self.unix_path = os.path.join(session_dir, "sockets", f"agent-{node_id[:12]}.sock")
        os.makedirs(os.path.dirname(self.unix_path), exist_ok=True)
        self.store = StoreDirectory(store_dir, capacity=object_store_memory)
        self.store_dir = store_dir
        accel_ids: Dict[str, list] = {}
        for name in ("TPU", "GPU"):
            if resources.get(name):
                accel_ids[name] = list(range(int(resources[name])))
        self.resources = NodeResources(ResourceSet(resources), labels, accel_ids)
        self.server = RpcServer("agent")
        self.tcp_port = 0
        self.head = AsyncRpcClient()
        self.pool = ConnectionPool()
        self.cluster_view: Dict[str, Dict] = {}

        # worker pool state
        self.workers: Dict[str, WorkerHandle] = {}
        self.idle_workers: List[WorkerHandle] = []
        self.leases: Dict[str, WorkerHandle] = {}
        # actor_id -> hosting worker: kill/lookup without scanning the
        # whole worker table (O(1) at 1000+ live actors)
        self.workers_by_actor: Dict[str, WorkerHandle] = {}
        self.max_workers = int(resources.get("CPU", 1)) or 1
        if CONFIG.num_workers_soft_limit:
            self.max_workers = CONFIG.num_workers_soft_limit
        self._starting_workers = 0
        # warm pool bookkeeping (ISSUE 10): pristine spawns in flight (so
        # the refill loop needn't scan self.workers), hit/miss counters,
        # and the forkserver death-ledger read offset (pids reaped by the
        # forkserver's SIGCHLD handler — the agent's kill(pid, 0) probe
        # cannot see those deaths once the pid is recycled)
        self._spawning_plain = 0
        # set on teardown: the warm-pool refill loop must stop forking (a
        # refill racing shutdown can respawn the forkserver AFTER the
        # terminate sweep captured its pid — a leaked daemon)
        self._closing = False
        self._pool_hits = 0
        self._pool_misses = 0
        self._pool_refills = 0
        self._pool_reaped = 0
        # predictive demand-paged refill (ISSUE 11): actor starts that
        # miss the warm pool park here for the next pool registration
        # (instead of each cold-forking), and the refill burst is sized
        # from the StartActor(Batch) demand seen inside the window —
        # not one fork per tick
        self._pool_waiters: deque = deque()
        self._demand_hits = 0
        self._demand_events: deque = deque()  # (monotonic, n)
        self._pid_handles: Dict[int, WorkerHandle] = {}
        self._death_ledger_pos = 0
        # batched control-RPC state: queued worker ActorReady reports
        # (flushed as ONE head RPC per window) + batch-size histograms
        self._ready_queue: List[Tuple[Dict, asyncio.Future]] = []
        self._ready_flush_armed = False
        self._ready_batch_hist: Dict[str, int] = {}
        self._lease_batch_hist: Dict[str, int] = {}
        # spawn admission (reference: maximum_startup_concurrency):
        # requests queue here; at most STARTUP_CONCURRENCY are between
        # fork and registration at once
        self._spawn_queue: deque = deque()
        self._launching_workers = 0
        # warm-template forkserver (worker_forkserver.py): plain workers
        # fork from a pre-imported template (~20ms) instead of a cold
        # interpreter launch (~350ms); container/conda workers still use
        # Popen (they need a different command line)
        self._forkserver_proc: Optional[subprocess.Popen] = None
        self._forkserver_sock = os.path.join(
            session_dir, "sockets", f"fs-{node_id[:12]}.sock")
        self._lease_counter = 0
        self._pending_leases: List[Dict] = []  # queued lease requests

        # transient spill ledger: demands redirected to a remote node in
        # the last ~2s, counted against its advertised availability so a
        # burst of simultaneous lease requests doesn't all pick the same
        # least-utilized node off the same stale gossip view
        self._recent_spills: Dict[str, List[Tuple[float, ResourceSet]]] = {}

        # object plane
        self._object_waits: Dict[str, List[asyncio.Future]] = {}
        self._pulls_inflight: Dict[str, asyncio.Task] = {}
        # cancelled pulls whose cleanup (stripe teardown + store abort) is
        # still running; a NEW pull of the same object must wait for ALL
        # of them or an old abort unlinks the new transfer's unsealed
        # allocation (list: rapid waiter churn can park several)
        self._pulls_draining: Dict[str, List[asyncio.Task]] = {}
        # hex -> monotonic stamp of the LAST waiter departure; only the
        # reap timer matching the current stamp may cancel, so the grace
        # window always runs full length from the latest detach
        self._pull_orphan_stamp: Dict[str, float] = {}
        # serve-side view cache: see _fetch_object_chunk
        self._serve_view_cache: "OrderedDict[str, list]" = OrderedDict()
        self.pulls = PullManager(self)
        # zero-copy array puts sealed on this node (device object plane)
        self._zero_copy_puts = 0

        # object ownership ledger (ISSUE 15): hex -> {owner addr, creating
        # task, sealed_at} recorded from ObjectSealed/WaitObjects; pruned
        # on free and whenever a scan observes the object gone from the
        # store. Feeds GetObjectRefs and the leak watchdog.
        self._object_owners: Dict[str, Dict] = {}
        # driver processes registered on this node (worker_id -> {addr,
        # pid}); workers are in self.workers, but the DRIVER owns most
        # objects and must be introspectable too. Pruned on disconnect.
        self._driver_clients: Dict[str, Dict] = {}
        # leak watchdog state: first-seen stamps of leak candidates and
        # the last scan's confirmed suspects (CLI/metrics read these)
        self._leak_candidates: Dict[str, float] = {}
        self._leak_suspects: List[Dict] = []
        self._leak_scans = 0
        # repair hook (ISSUE 17): store copies freed after a graduated
        # owner_unreachable / zero_refs verdict
        self._leak_repairs = 0

        # placement groups: (pg_id, bundle_index) -> reserved ResourceSet
        self._pg_bundles: Dict[Tuple[str, int], ResourceSet] = {}
        self._pg_available: Dict[Tuple[str, int], ResourceSet] = {}

        self._resources_dirty = True
        self._register_routes()

    # ------------------------------------------------------------------ boot
    async def start(self) -> None:
        from ray_tpu._private.event import init_event_log, report_event

        init_event_log(self.session_dir, f"agent_{self.node_id[:8]}")
        report_event("INFO", "NODE_STARTED",
                     f"node agent {self.node_id[:12]} starting",
                     node_id=self.node_id)
        # flight recorder (ISSUE 14): pull / broadcast / spill / actor-start
        # spans ride the same crash-durable ring workers use
        _events.configure(self.session_dir, "agent")
        await self.server.start_unix(self.unix_path)
        self.tcp_port = await self.server.start_tcp("0.0.0.0", 0)
        self.server.set_disconnect_handler(self._on_disconnect)
        await self._connect_head()
        spawn_tracked(self._resource_report_loop(), "agent-resource-report")
        spawn_tracked(self._worker_reaper_loop(), "agent-worker-reaper")
        spawn_tracked(self._node_stats_loop(), "agent-node-stats")
        spawn_tracked(self._head_watchdog_loop(), "agent-head-watchdog")
        if float(CONFIG.object_leak_scan_interval_s) > 0:
            # default-off: the watchdog only exists when the knob arms it
            spawn_tracked(self._leak_watchdog_loop(), "agent-leak-watchdog")
        if _events.REC.enabled:
            spawn_tracked(self._events_flush_loop(), "agent-events-flush")
        if os.environ.get("RAY_TPU_LOG_TO_DRIVER", "1") != "0":
            from ray_tpu._private.log_monitor import LogMonitor

            async def publish(channel, message):
                await self.head.call("Publish",
                                     {"channel": channel, "message": message},
                                     timeout=CONFIG.control_rpc_timeout_s)

            monitor = LogMonitor(os.path.join(self.session_dir, "logs"),
                                 self.node_id, publish)
            spawn_tracked(monitor.run(), "agent-log-monitor")
        if os.environ.get("RAY_TPU_MEMORY_MONITOR", "1") != "0":
            from ray_tpu._private.memory_monitor import (
                MemoryMonitor,
                OomKiller,
            )

            def list_leases():
                return [
                    {"lease": lid, "worker": w,
                     "retriable": getattr(w, "lease_retriable", True),
                     "owner": getattr(w, "lease_owner", ""),
                     "start": getattr(w, "lease_start", 0.0)}
                    for lid, w in self.leases.items()
                    if w.alive and not w.is_actor
                ]

            def kill(victim):
                from ray_tpu._private.event import report_event

                w = victim["worker"]
                report_event("WARNING", "OOM_KILL",
                             f"killing worker {w.worker_id[:12]} under "
                             "memory pressure",
                             worker_id=w.worker_id, node_id=self.node_id)
                try:
                    w.terminate()  # owner sees the failure and retries
                except Exception:
                    pass

            threshold = float(
                os.environ.get("RAY_TPU_MEMORY_USAGE_THRESHOLD", "0.95"))
            self.oom_killer = OomKiller(
                MemoryMonitor(usage_threshold=threshold), list_leases, kill)
            spawn_tracked(self.oom_killer.run(), "agent-oom-killer")
        if CONFIG.prestart_workers:
            spawn_tracked(self._prestart(), "agent-prestart")
            spawn_tracked(self._warm_pool_loop(), "agent-warm-pool")

    async def _events_flush_loop(self) -> None:
        """Batch-flush this agent's flight-recorder ring to the head
        (extending the ReportTaskEvents path the way driver/worker
        processes do). The ring itself stays the crash-durable copy."""
        rec = _events.REC
        while not self._closing:
            await asyncio.sleep(max(0.5, CONFIG.task_event_flush_interval_s))
            if rec.counter == rec.flushed:
                continue
            spans = rec.drain()
            try:
                await self.head.call(
                    "ReportTaskEvents",
                    {"node_id": self.node_id, "spans": spans,
                     "role": "agent", "pid": os.getpid(),
                     "ring": rec.stats()},
                    timeout=CONFIG.control_rpc_timeout_s)
            except Exception:
                pass  # head mid-bounce: spans stay readable in the ring

    async def aclose_clients(self) -> None:
        """Await every outbound client's read loop (head + the per-peer
        control/data connection pool) so shutdown leaves no pending task."""
        self._closing = True
        await self.pool.aclose_all()
        try:
            await self.head.aclose()
        except Exception:
            pass
        try:
            await self.server.close()
        except Exception:
            pass

    def teardown_processes(self) -> None:
        """Reap everything this agent spawned (workers, forkserver, and —
        via the session registry — grandchildren in foreign pgids). The
        agent is the fate-share supervisor for its node: this runs on
        SIGTERM, on head-gone give-up, and when the spawning driver dies,
        so no daemon outlives the session (VERDICT r5: 22 leaked daemons
        starved the next benchmark run)."""
        self._closing = True
        procs = [w.proc for w in self.workers.values()]
        if self._forkserver_proc is not None:
            procs.append(self._forkserver_proc)
        try:
            lifecycle.terminate_tree(procs)
        except Exception:
            pass
        try:
            lifecycle.reap_session(self.session_dir, node_id=self.node_id,
                                   sigterm_timeout_s=1.0)
        except Exception:
            pass

    def _register_routes(self) -> None:
        r = self.server.add_handler
        # local clients
        r("RegisterClient", self._register_client)
        r("RequestWorkerLease", self._request_worker_lease)
        r("RequestWorkerLeaseBatch", self._request_worker_lease_batch)
        r("ReturnWorker", self._return_worker)
        r("ReportActorReady", self._report_actor_ready)
        r("GetWorkerPoolStats", self._get_worker_pool_stats)
        r("ObjectSealed", self._object_sealed)
        r("WaitObjects", self._wait_objects)
        r("FreeObjects", self._free_objects)
        r("PinObject", self._pin_object)
        r("UnpinObject", self._unpin_object)
        r("GetStoreStats", self._get_store_stats)
        r("GetPullStats", self._get_pull_stats)
        r("GetNodeInfo", self._get_node_info)
        r("ListWorkers", self._list_workers)
        r("ListEvents", self._list_events)
        r("GetNodeStats", self._get_node_stats)
        r("ListStoreObjects", self._list_store_objects)
        r("GetObjectRefs", self._get_object_refs)
        r("SetResource", self._set_resource)
        r("RestoreSpilled", self._restore_spilled)
        # remote agents
        r("FetchObjectMeta", self._fetch_object_meta)
        r("FetchObjectChunk", self._fetch_object_chunk)
        r("Ping", self._ping)

    async def _ping(self, conn: Connection, p) -> Dict:
        """Liveness probe target (idle-deadline monitors, chaos tooling)."""
        return {"ok": True, "node_id": self.node_id,
                "incarnation": self.incarnation}

    async def _prestart(self) -> None:
        """Initial warm-pool fill: burst-fork up to the warm target (the
        spawn admission queue still caps concurrent boots); the warm-pool
        loop maintains the level afterwards with rate-limited refills.
        With warm leasing disabled, keep the historical prestart of
        min(max_workers, num_cpus) plain workers."""
        if self.warm_lease_enabled:
            target = self.WARM_TARGET
        else:
            target = min(self.max_workers,
                         int(self.resources.total.get("CPU")) or 1)
        for _ in range(target):
            self._spawn_worker(pool_fill=True)

    # ------------------------------------------------------ warm worker pool
    @property
    def WARM_TARGET(self) -> int:
        """Pre-warmed pool size the refill loop maintains (ISSUE 10).
        0 = auto (max(2, num_cpus)); negative config disables warm
        leasing entirely (cold fork per actor, the pre-pool behavior)."""
        t = int(CONFIG.worker_pool_warm_target)
        if t < 0:
            return 0
        if t == 0:
            return max(2, int(self.resources.total.get("CPU") or 1))
        return t

    @property
    def warm_lease_enabled(self) -> bool:
        return int(CONFIG.worker_pool_warm_target) >= 0

    def _warm_idle_count(self) -> int:
        return sum(1 for w in self.idle_workers
                   if w.env_key is None and w.alive and not w.is_actor)

    async def _warm_pool_loop(self) -> None:
        """Background refill: keep ``WARM_TARGET`` pristine workers parked
        (booted through registration, before any actor-class unpickle),
        at most one fork per ``worker_pool_refill_interval_ms`` so a
        drained pool refills without starving the burst that drained it
        (reference: worker_pool.h prestart + maximum_startup_concurrency)."""
        while True:
            await asyncio.sleep(
                max(CONFIG.worker_pool_refill_interval_ms, 5) / 1000.0)
            if not self.warm_lease_enabled or self._closing:
                continue
            try:
                self._consume_death_ledger()
            except Exception:
                pass
            if CONFIG.worker_pool_demand_paging:
                # predictive refill (ISSUE 11): deficit = live waiters +
                # warm floor − parked − mid-boot; the burst events from
                # _note_actor_demand already pre-forked toward the batch
                # window, so this tick only covers the floor and
                # stragglers (a fork that died, an expired waiter)
                self._refill_to_demand(include_floor=True)
                continue
            deficit = self.WARM_TARGET - self._warm_idle_count() \
                - self._spawning_plain
            if deficit <= 0:
                continue
            # legacy pacing (demand paging disabled): while a burst is
            # actively draining the pool refill one fork per tick; once
            # the burst passes, a whole admission window per tick.
            now = time.monotonic()
            busy = (now - getattr(self, "_last_warm_lease", 0.0) < 1.0
                    or now - getattr(self, "_last_ready_report", 0.0) < 1.0
                    or bool(self._ready_queue))
            for _ in range(1 if busy
                           else min(deficit, self.STARTUP_CONCURRENCY)):
                self._pool_refills += 1
                self._spawn_worker(pool_fill=True)

    def _consume_death_ledger(self) -> None:
        """Apply the forkserver's SIGCHLD death ledger: a warm worker that
        died between fork and first lease has no agent connection to drop
        and its pid may already be recycled — without the ledger a dead
        (or foreign) pid could be leased. Cheap when nothing died (one
        stat per call)."""
        path = self._forkserver_sock + ".deaths"
        try:
            if os.path.getsize(path) <= self._death_ledger_pos:
                return
            with open(path, "r") as f:
                f.seek(self._death_ledger_pos)
                data = f.read()
                self._death_ledger_pos = f.tell()
        except OSError:
            return
        for line in data.splitlines():
            try:
                pid = int(line)
            except ValueError:
                continue
            handle = self._pid_handles.get(pid)
            if handle is None or handle.worker_id not in self.workers:
                continue
            handle.force_dead = True
            spawn_tracked(
                self._handle_worker_exit(
                    handle, "reaped by forkserver (death ledger)"),
                "agent-ledger-exit")

    def _note_actor_demand(self, n: int) -> None:
        """A StartActor(Batch) frame just landed: record the demand and
        pre-fork toward it NOW — by the time the entries clear resource
        admission, their workers are already booting through the
        admission queue (the 1-fork/tick pacing this replaces left
        hit_ratio at 0.17 under a burst of 200, ACTORS_latest r10)."""
        if n > 0:
            self._demand_events.append((time.monotonic(), n))
            # prune HERE, not just in the stats read (the only other
            # caller): a long-lived agent serving millions of creates
            # with nobody polling stats must not grow this unbounded
            self._recent_demand()
        self._refill_to_demand(extra_demand=n)

    def _recent_demand(self) -> int:
        window = float(CONFIG.worker_pool_demand_window_s)
        now = time.monotonic()
        while self._demand_events and \
                now - self._demand_events[0][0] > window:
            self._demand_events.popleft()
        return sum(n for _t, n in self._demand_events)

    def _refill_to_demand(self, extra_demand: int = 0,
                          include_floor: bool = False) -> None:
        """Fork pool-fill workers up to the observed shortfall: live
        waiters + fresh batch demand, minus what is already parked or
        mid-boot. The warm FLOOR is included only from the periodic
        loop tick — adding it per StartActorBatch would re-fork the
        floor once per frame of a burst (measured: 546 forks for 400
        actors, every extra fork stealing boot CPU from the burst on a
        2-core box). The spawn admission queue still bounds concurrent
        boots; this only sizes the pipeline."""
        if not self.warm_lease_enabled or self._closing or \
                not CONFIG.worker_pool_demand_paging:
            return
        # shed settled waiters (timed-out futures from re-arm windows):
        # without this the deque only drains when a registration pops
        # through it, which is exactly what ISN'T happening when
        # waiters time out
        while self._pool_waiters and self._pool_waiters[0].done():
            self._pool_waiters.popleft()
        deficit = (extra_demand
                   + sum(1 for f in self._pool_waiters if not f.done())
                   + (self.WARM_TARGET if include_floor else 0)
                   - self._warm_idle_count()
                   - self._spawning_plain)
        cap = int(CONFIG.worker_pool_refill_burst_max)
        if cap > 0:
            deficit = min(deficit, cap)
        for _ in range(max(0, deficit)):
            self._pool_refills += 1
            self._spawn_worker(pool_fill=True)

    def _offer_pool_worker(self, handle: WorkerHandle) -> bool:
        """Hand a just-available pristine worker to the oldest live
        pool waiter (a missed actor start parked by demand paging).
        True = consumed; False = caller parks it idle as before."""
        if handle.is_actor or handle.leased_to is not None or \
                handle.env_key is not None:
            return False
        while self._pool_waiters:
            fut = self._pool_waiters.popleft()
            if fut.done():
                continue  # waiter timed out and cold-forked meanwhile
            fut.set_result(handle)
            return True
        return False

    async def _wait_pool_worker(self) -> Optional[WorkerHandle]:
        """Demand-paged miss path: park for the next pool registration
        instead of cold-forking a dedicated process. The wait window
        EXTENDS while pool-fill spawns are still in flight — under a
        saturated burst the pre-forked worker for the queue tail
        legitimately arrives after a flat window, and a timeout there
        cold-forks a DUPLICATE that steals boot CPU from the very
        pipeline the waiter depends on (measured: a 20 s cliff turned
        92/400 starts into duplicate forks and halved the burst rate).
        Hard-capped regardless, so a wedged forkserver still degrades
        to the cold fork (never a failure mode)."""
        fut = asyncio.get_running_loop().create_future()
        self._pool_waiters.append(fut)
        self._refill_to_demand()
        window = float(CONFIG.worker_pool_wait_s)
        deadline = time.monotonic() + 10 * window
        remaining = window
        while True:
            try:
                handle = await asyncio.wait_for(
                    fut, timeout=max(0.05, remaining))
                break
            except asyncio.TimeoutError:
                if self._spawning_plain <= 0 or \
                        time.monotonic() > deadline or self._closing:
                    return None
                # workers are still owed to the pool: re-arm a fresh
                # future (the timed-out one is poisoned for set_result
                # — and removed so it cannot accumulate as deque junk)
                try:
                    self._pool_waiters.remove(fut)
                except ValueError:
                    pass
                fut = asyncio.get_running_loop().create_future()
                self._pool_waiters.append(fut)
                remaining = window
        # paranoia at handout: the ledger may have caught its death
        # between registration and this wakeup
        if not handle.alive or (handle.conn is not None
                                and handle.conn.closed):
            return None
        return handle

    def _lease_warm_worker(self) -> Optional[WorkerHandle]:
        """Pop a live pristine warm worker for an actor start, with a
        liveness check on handout (alive pid, registered, connection not
        mid-close, not in the death ledger)."""
        if not self.warm_lease_enabled:
            return None
        try:
            self._consume_death_ledger()
        except Exception:
            pass
        handle = self._pop_idle_worker(None)
        if handle is not None:
            self._last_warm_lease = time.monotonic()
        return handle

    # ------------------------------------------------------------ head link
    async def _connect_head(self) -> None:
        await self.head.connect_tcp(self.head_host, self.head_port)
        self.head.set_push_handler(self._on_head_push)
        # bounded: a one-way partition eats the request without an RST, and
        # an unbounded call would wedge the watchdog's reconnect loop on
        # its very first attempt (it could then never deliver a fence
        # verdict after the partition heals)
        reply = await self.head.call(
            "RegisterNode",
            {
                "node_id": self.node_id,
                "incarnation": self.incarnation,
                "addr": {"host": "127.0.0.1", "port": self.tcp_port},
                "resources": self.resources.to_wire(),
                # the actors this node ACTUALLY still hosts: a restarted
                # head reconciles its restored (RECOVERING) actor table
                # against this list — present means claimed-alive, absent
                # means the worker died during the outage
                "actors": [w.actor_id for w in self.workers.values()
                           if w.is_actor and w.actor_id and w.alive],
            },
            timeout=max(CONFIG.head_ping_timeout_s * 2, 5.0),
        )
        if reply.get("fenced"):
            raise NodeFencedError(
                f"node {self.node_id[:12]} incarnation {self.incarnation} "
                "was fenced by the head")
        CONFIG.apply_cluster_config(reply.get("cluster_config", {}))
        self.cluster_view = reply.get("cluster_view", {})
        self._resources_dirty = True

    def _fenced_suicide(self) -> None:
        """The head fenced us: tear down every process this node spawned
        (workers holding zombie leases, the forkserver) and exit. After a
        healed partition this is what converges the lifecycle pid
        registry to zero instead of leaving a shadow cluster."""
        from ray_tpu._private.event import report_event

        try:
            report_event("ERROR", "NODE_FENCED_EXIT",
                         f"node {self.node_id[:12]} fenced by head; "
                         "terminating",
                         node_id=self.node_id,
                         incarnation=self.incarnation)
        except Exception:
            pass
        self.teardown_processes()
        try:
            lifecycle.unregister_process(self.session_dir, os.getpid())
        except Exception:
            pass
        os._exit(1)

    async def _head_watchdog_loop(self) -> None:
        """Survive a head restart (reference: GCS fault tolerance —
        NotifyGCSRestart + raylet resubscribe, node_manager.proto:364):
        ping the head; on failure reconnect with backoff and re-register
        under the same node_id so leases/actors on this node carry over.

        If the head stays gone past ``agent_head_gone_exit_s``, the agent
        shuts itself (and its workers) down: an unreachable head means the
        cluster is dead, and immortal orphaned agents accumulate into a
        box-wide CPU leak (observed: a killed test run left 40+ agents
        idling at ~1%% CPU each — reference parity: raylets exit when the
        GCS declares them dead, node_manager.cc HandleUnexpectedDisconnect)."""
        give_up_s = float(CONFIG.agent_head_gone_exit_s)
        while True:
            await asyncio.sleep(CONFIG.head_watchdog_period_s)
            try:
                await asyncio.wait_for(
                    self.head.call("Ping", {}),
                    timeout=CONFIG.head_ping_timeout_s)
                continue
            except Exception:
                pass
            # decorrelated jitter: after a head bounce every agent's
            # retries spread across the interval instead of arriving in
            # synchronized waves at the recovering head
            backoff = DecorrelatedJitterBackoff(base_s=0.2, cap_s=2.0)
            down_since = time.monotonic()
            while True:
                try:
                    await self.head.aclose()
                except Exception:
                    pass
                try:
                    # reconnect in place: connect_tcp replaces the broken
                    # stream and restarts the read loop on self.head
                    await self._connect_head()
                    break
                except NodeFencedError:
                    # the cluster declared this incarnation dead while we
                    # were partitioned; self-terminate (no zombie leases)
                    self._fenced_suicide()
                except Exception:
                    if time.monotonic() - down_since > give_up_s:
                        _events.REC.dump_local("head_gone_exit")
                        self.teardown_processes()
                        os._exit(1)
                    await asyncio.sleep(backoff.next_delay())

    async def _on_head_push(self, method: str, payload: Any) -> None:
        if method == "ClusterView":
            self.cluster_view = payload
            await self._drain_pending_leases()
        elif method == "StartActor":
            self._note_actor_demand(1)
            await self._start_actor(payload)
        elif method == "StartActorBatch":
            # one frame per node per CreateActorBatch: each entry gets its
            # own task — _start_actor can legitimately await resource
            # capacity, and one starved entry must not wedge its siblings.
            # The batch size IS the demand window: pre-fork toward it now
            # so workers boot while entries clear admission (ISSUE 11).
            self._note_actor_demand(len(payload["items"]))
            for item in payload["items"]:
                spawn_tracked(self._start_actor(item), "agent-start-actor")
        elif method == "KillActorWorker":
            self._kill_actor_worker(payload["actor_id"])
        elif method == "PreparePGBundle":
            ok = self._prepare_pg_bundle(payload)
            await self.head.call(
                "Publish",
                {"channel": payload["reply_channel"], "message": {"ok": ok}},
                timeout=CONFIG.control_rpc_timeout_s,
            )
        elif method == "ReturnPGBundle":
            self._return_pg_bundle(payload)
        elif method == "NodeRemoved":
            self._on_peer_node_removed(payload)
        elif method == "Pub":
            pass
        elif method == "Drain":
            pass

    def _on_peer_node_removed(self, payload: Dict) -> None:
        """Fail-fast on a peer's death verdict: purge it from the gossip
        view immediately (spillback must stop targeting it) and drop the
        cached control/data channels so every in-flight RPC to it — chunk
        fetches mid-pull, spilled lease requests — fails NOW instead of
        waiting out a 60 s chunk deadline on a socket a partition will
        never reset."""
        node_id = payload.get("node_id")
        if node_id:
            self.cluster_view.pop(node_id, None)
        addr = payload.get("addr") or {}
        if addr.get("host") is not None and addr.get("port") is not None:
            self.pulls.on_peer_removed(addr)  # drops ctrl+data channels
            # a dead peer is no longer a remote-tier restore source
            self.store.forget_remote_source(addr)

    async def _resource_report_loop(self) -> None:
        """Versioned delta gossip (reference: ray_syncer.h:88 — versioned
        per-node RESOURCE_VIEW snapshots over bidi streams). A full snapshot
        goes out only when the node's view changed; unchanged ticks send a
        tiny heartbeat frame, so head ingress per tick is O(changed nodes)
        plus O(n) constant-size liveness probes."""
        period = max(CONFIG.gossip_period_ms, 50) / 1000
        last_sent: Optional[Dict] = None
        version = 0
        while True:
            await asyncio.sleep(period)
            dirty = self._resources_dirty
            self._resources_dirty = False
            snapshot = {
                "resources": self.resources.to_wire(),
                "pending": [r["resources"].to_wire()
                            for r in self._pending_leases],
            }
            try:
                if dirty or snapshot != last_sent:
                    version += 1
                    await self.head.call(
                        "UpdateResources",
                        {"node_id": self.node_id, "v": version, **snapshot},
                        timeout=CONFIG.control_rpc_timeout_s)
                    last_sent = snapshot
                else:
                    reply = await self.head.call(
                        "UpdateResources",
                        {"node_id": self.node_id, "hb": True, "v": version},
                        timeout=CONFIG.control_rpc_timeout_s)
                    if reply and reply.get("resync"):
                        # the head's applied version disagrees with ours
                        # (restart / lost report): next tick sends full
                        last_sent = None
            except Exception:
                # head unreachable or restarted: resend full on recovery
                last_sent = None

    # ---------------------------------------------------------- worker pool
    @property
    def STARTUP_CONCURRENCY(self) -> int:
        cap = CONFIG.worker_startup_concurrency
        if cap > 0:
            return cap
        return max(2, int(self.resources.total.get("CPU") or 1))

    def _spawn_worker(self, actor_spec: Optional[Dict] = None,
                      container: Optional[Dict] = None,
                      conda_prefix: Optional[str] = None,
                      env_key: Optional[str] = None,
                      pool_fill: bool = False) -> WorkerHandle:
        """Admission-queued spawn: a burst of requests (1000 actors at
        once) must not fork 1000 interpreters simultaneously — that starves
        the node's cores until the head's health checks declare it dead.
        At most STARTUP_CONCURRENCY processes are between fork and
        registration at any moment (reference: worker_pool.h
        maximum_startup_concurrency = num_cpus)."""
        worker_id = os.urandom(16).hex()
        handle = WorkerHandle(worker_id, proc=None)
        handle.env_key = env_key
        self.workers[worker_id] = handle
        self._starting_workers += 1
        if pool_fill:
            # pool-fill spawn (prestart / warm refill): counts toward the
            # warm level until it registers (or dies trying). Cold actor
            # forks and demand task spawns do NOT count — they never park
            # in the pool, and counting them would zero the refill
            # deficit for exactly as long as a miss burst lasts.
            handle.pending_plain = True
            self._spawning_plain += 1
        self._spawn_queue.append(
            (handle, actor_spec, container, conda_prefix, env_key))
        self._workers_spawned = getattr(self, "_workers_spawned", 0) + 1
        self._kick_spawner()
        return handle

    def _plain_spawn_done(self, handle: WorkerHandle) -> None:
        """A pristine spawn registered or died: it no longer counts as a
        warm-pool fill in flight (exactly-once via the flag reset)."""
        if getattr(handle, "pending_plain", False):
            handle.pending_plain = False
            self._spawning_plain = max(0, self._spawning_plain - 1)

    def _kick_spawner(self) -> None:
        while (self._spawn_queue
               and self._launching_workers < self.STARTUP_CONCURRENCY):
            (handle, actor_spec, container, conda_prefix,
             env_key) = self._spawn_queue.popleft()
            if handle.worker_id not in self.workers:  # cancelled meanwhile
                self._starting_workers = max(0, self._starting_workers - 1)
                continue
            self._launching_workers += 1
            handle.launching = True
            if container or conda_prefix or not CONFIG.worker_forkserver:
                try:
                    self._launch_worker(handle, container, conda_prefix,
                                        env_key)
                except Exception:
                    self._launching_workers -= 1
                    handle.launching = False
                    self._starting_workers = max(0,
                                                 self._starting_workers - 1)
                    handle.mark_failed()
                    self.workers.pop(handle.worker_id, None)
            else:
                spawn_tracked(self._launch_via_forkserver(handle, env_key),
                              "agent-forkserver-launch")

    async def _launch_via_forkserver(self, handle: WorkerHandle,
                                     env_key: Optional[str]) -> None:
        try:
            pid = await self._forkserver_spawn(handle)
        except Exception:
            pid = None
        if pid:
            handle.proc = _ForeignProc(pid)
            handle.launched_at = time.monotonic()
            handle.spawn_time = time.monotonic()
            self._pid_handles[pid] = handle
            lifecycle.register_process(self.session_dir, "worker", pid,
                                       self.node_id)
            return
        # template unavailable/broken: cold-launch fallback (never during
        # teardown — a shutdown-raced spawn would leak past the sweep)
        try:
            if self._closing:
                raise RuntimeError("agent closing")
            self._launch_worker(handle, None, None, env_key)
        except Exception:
            self._launching_workers = max(0, self._launching_workers - 1)
            handle.launching = False
            self._starting_workers = max(0, self._starting_workers - 1)
            handle.mark_failed()
            self.workers.pop(handle.worker_id, None)
            # the freed slot must pull the next queued spawn or a burst
            # whose launches all fail would strand the queue forever
            self._kick_spawner()

    def _worker_ray_env(self, worker_id: str) -> Dict[str, str]:
        """The one authoritative worker-bootstrap variable set (every
        launch path — forkserver, Popen, container, conda — builds on
        this; divergence here means divergent worker environments).
        RAY_TPU_PARENT_PID designates this agent as the worker's
        fate-share supervisor (lifecycle.fate_share_with_parent)."""
        return {
            "RAY_TPU_WORKER_ID": worker_id,
            "RAY_TPU_AGENT_SOCK": self.unix_path,
            "RAY_TPU_NODE_ID": self.node_id,
            "RAY_TPU_SESSION_DIR": self.session_dir,
            "RAY_TPU_STORE_DIR": self.store_dir,
            "RAY_TPU_HEAD_ADDR": f"{self.head_host}:{self.head_port}",
            "RAY_TPU_PARENT_PID": str(os.getpid()),
        }

    def _worker_env(self, worker_id: str) -> Dict[str, str]:
        from ray_tpu._private.config import scrub_axon_bootstrap_env

        env = dict(os.environ)
        env.update(self._worker_ray_env(worker_id))
        scrub_axon_bootstrap_env(env)
        return env

    async def _forkserver_spawn(self, handle: WorkerHandle) -> Optional[int]:
        """Ask the warm template to fork a worker; returns the child pid
        or None when the template can't serve (caller cold-launches)."""
        import json as _json

        if self._closing:
            return None
        if self._forkserver_proc is None or \
                self._forkserver_proc.poll() is not None:
            from ray_tpu._private.config import scrub_axon_bootstrap_env

            env = dict(os.environ)
            scrub_axon_bootstrap_env(env)
            try:
                os.unlink(self._forkserver_sock + ".ready")
            except FileNotFoundError:
                pass
            log_dir = os.path.join(self.session_dir, "logs")
            os.makedirs(log_dir, exist_ok=True)
            with open(os.path.join(log_dir, "forkserver.log"), "ab") as lg:
                env["RAY_TPU_SESSION_DIR"] = self.session_dir
                env["RAY_TPU_NODE_ID"] = self.node_id
                env["RAY_TPU_PARENT_PID"] = str(os.getpid())
                self._forkserver_proc = subprocess.Popen(
                    [sys.executable, "-m",
                     "ray_tpu._private.worker_forkserver",
                     self._forkserver_sock],
                    env=env, stdout=lg, stderr=lg, start_new_session=True)
            lifecycle.register_process(self.session_dir, "forkserver",
                                       self._forkserver_proc.pid,
                                       self.node_id)
            # the fresh forkserver unlinks + recreates its death ledger:
            # a stale offset would silently skip (or mid-line misparse)
            # every death it reports from now on
            self._death_ledger_pos = 0
        for _ in range(200):  # template warms up once (~0.5s)
            if os.path.exists(self._forkserver_sock + ".ready"):
                break
            if self._forkserver_proc.poll() is not None:
                return None
            await asyncio.sleep(0.05)
        else:
            return None
        log_dir = os.path.join(self.session_dir, "logs")
        wid = handle.worker_id
        req = {
            "env": self._worker_env(wid),
            "log_out": os.path.join(log_dir, f"worker-{wid[:12]}.out"),
            "log_err": os.path.join(log_dir, f"worker-{wid[:12]}.err"),
        }
        writer = None
        try:
            reader, writer = await asyncio.open_unix_connection(
                self._forkserver_sock)
            writer.write((_json.dumps(req) + "\n").encode())
            await writer.drain()
            line = await asyncio.wait_for(reader.readline(), 30)
            rep = _json.loads(line)
            return rep.get("pid")
        except Exception:
            return None
        finally:
            # the forkserver serves connections serially — a leaked open
            # connection (timeout/exception path) would stall every
            # subsequent warm-fork request behind its recv loop
            if writer is not None:
                try:
                    writer.close()
                except Exception:
                    pass

    def _spawn_slot_freed(self, handle: WorkerHandle) -> None:
        """A launching worker registered or died: free its startup slot."""
        if getattr(handle, "launching", False):
            handle.launching = False
            self._launching_workers = max(0, self._launching_workers - 1)
            self._kick_spawner()

    def _launch_worker(self, handle: WorkerHandle,
                       container: Optional[Dict] = None,
                       conda_prefix: Optional[str] = None,
                       env_key: Optional[str] = None) -> None:
        worker_id = handle.worker_id
        log_dir = os.path.join(self.session_dir, "logs")
        os.makedirs(log_dir, exist_ok=True)
        out = open(os.path.join(log_dir, f"worker-{worker_id[:12]}.out"), "ab")
        err = open(os.path.join(log_dir, f"worker-{worker_id[:12]}.err"), "ab")
        ray_env = self._worker_ray_env(worker_id)
        if container:
            # container runtime_env: the worker process starts INSIDE
            # podman/docker with the session dir (unix socket), object
            # store, and the ray_tpu package bind-mounted (reference:
            # _private/runtime_env/container.py prepending `podman run`)
            from ray_tpu.runtime_env.container import (
                worker_container_command)

            # same scrub as the host path: the axon bootstrap does not
            # exist inside the image, so an inherited axon platform would
            # break jax there
            from ray_tpu._private.config import scrub_axon_bootstrap_env

            container_env = scrub_axon_bootstrap_env(
                {"JAX_PLATFORMS": os.environ.get("JAX_PLATFORMS", "cpu")})
            ray_env["JAX_PLATFORMS"] = container_env["JAX_PLATFORMS"]
            cmd = worker_container_command(
                container, self.session_dir, self.store_dir, ray_env)
            env = dict(os.environ)
        elif conda_prefix:
            # conda runtime_env: the worker runs under the env's
            # interpreter (reference conda.py sets the context's
            # py_executable the same way); ray_tpu rides PYTHONPATH
            from ray_tpu.runtime_env.conda import worker_conda_command

            cmd, ray_env = worker_conda_command(conda_prefix, ray_env)
            env = dict(os.environ)
            env.update(ray_env)
            from ray_tpu._private.config import scrub_axon_bootstrap_env

            scrub_axon_bootstrap_env(env)
        else:
            cmd = [sys.executable, "-m", "ray_tpu._private.worker_process"]
            env = dict(os.environ)
            env.update(ray_env)
            # Workers must not grab the TPU runtime by default (tasks that
            # request TPU resources get chip visibility through their
            # lease's instance ids), and the axon dev-tunnel bootstrap
            # must not run in them (config.scrub_axon_bootstrap_env).
            from ray_tpu._private.config import scrub_axon_bootstrap_env

            scrub_axon_bootstrap_env(env)
        proc = subprocess.Popen(
            cmd,
            env=env,
            stdout=out,
            stderr=err,
            start_new_session=True,
        )
        out.close()
        err.close()
        handle.proc = proc
        handle.launched_at = time.monotonic()
        handle.spawn_time = time.monotonic()
        self._pid_handles[proc.pid] = handle
        lifecycle.register_process(self.session_dir, "worker", proc.pid,
                                   self.node_id)

    def _spawn_conda_worker(self, conda_spec, env_key: Optional[str],
                            req: Dict) -> None:
        """Resolve/materialize the conda env off-loop, then spawn a worker
        under its interpreter. Env creation can take minutes (solver +
        offline package cache), so it must not block the agent's event
        loop; failures land on the lease future as a terminal
        ``runtime_env`` error (retrying would fail identically).

        One in-flight resolution per env_key: every drain pass while the
        solver runs would otherwise re-trigger a redundant create for the
        same pending lease."""
        spawning = getattr(self, "_conda_spawning", None)
        if spawning is None:
            spawning = self._conda_spawning = set()
        failed = getattr(self, "_conda_failed", None)
        if failed is None:
            failed = self._conda_failed = {}
        cached = failed.get(env_key)
        if cached is not None and \
                time.monotonic() - cached[0] < CONFIG.conda_failure_cache_s:
            # recently failed: the same spec very likely fails the same
            # way — don't re-run a minutes-long doomed solver for every
            # queued lease. The cache expires (transient solver/disk
            # failures must not poison the env for the agent's lifetime).
            fut: asyncio.Future = req["fut"]
            if not fut.done():
                fut.set_result({"error": "runtime_env",
                                "message": cached[1]})
                if req in self._pending_leases:
                    self._pending_leases.remove(req)
            return
        if env_key in spawning:
            return
        spawning.add(env_key)
        self._starting_workers += 1

        async def run() -> None:
            try:
                from ray_tpu.runtime_env.conda import ensure_conda_env

                cache_root = os.path.join(self.session_dir,
                                          "runtime_env_cache")
                os.makedirs(cache_root, exist_ok=True)
                prefix = await asyncio.get_running_loop().run_in_executor(
                    None, ensure_conda_env, conda_spec, cache_root)
            except Exception as e:
                spawning.discard(env_key)
                failed[env_key] = (time.monotonic(), str(e))
                self._starting_workers = max(0, self._starting_workers - 1)
                fut: asyncio.Future = req["fut"]
                if not fut.done():
                    fut.set_result({"error": "runtime_env",
                                    "message": str(e)})
                    if req in self._pending_leases:
                        self._pending_leases.remove(req)
                await self._drain_pending_leases()
                return
            spawning.discard(env_key)
            self._starting_workers = max(0, self._starting_workers - 1)
            self._spawn_worker(conda_prefix=prefix, env_key=env_key)
            await self._drain_pending_leases()

        spawn_tracked(run(), "agent-conda-spawn")

    async def _register_client(self, conn: Connection, p: Dict) -> Dict:
        role = p.get("role")
        conn.meta["role"] = role
        if role == "driver" and p.get("direct_addr"):
            # drivers own most objects: keep their direct addr so the
            # introspection plane (GetObjectRefs fan-out, leak watchdog)
            # can read their ref tables like any worker's
            client_id = p.get("worker_id") or f"driver-{p.get('pid', 0)}"
            conn.meta["driver_id"] = client_id
            self._driver_clients[client_id] = {
                "direct_addr": dict(p["direct_addr"]),
                "pid": p.get("pid", 0)}
        if role == "worker":
            worker_id = p["worker_id"]
            handle = self.workers.get(worker_id)
            if handle is None:
                # Worker we didn't spawn (e.g. driver-embedded, or an
                # externally-started C++ worker); track anyway.
                handle = WorkerHandle(worker_id, proc=_ForeignProc(p.get("pid", 0)))
                self.workers[worker_id] = handle
            else:
                self._starting_workers = max(0, self._starting_workers - 1)
                self._spawn_slot_freed(handle)
                self._plain_spawn_done(handle)
            # raylint: disable=R14 -- the sender is cross-language: C++
            # workers (cpp/include/ray_tpu/worker.hpp RegisterClient)
            # self-tag language:cpp via env_key; no Python send site
            # ships the key, so the linter can't see the producer
            if p.get("env_key"):
                # self-tagged env affinity (C++ workers tag themselves
                # language:cpp so only matching leases land on them)
                handle.env_key = p["env_key"]
            handle.conn = conn
            # stamp the node onto the advertised addr: lease grants carry
            # it so a same-node owner can pick the shm lane (ISSUE 11) —
            # the worker registers before it learns its own node_id
            handle.direct_addr = dict(p["direct_addr"])
            handle.direct_addr.setdefault("node_id", self.node_id)
            handle.registered.set()
            conn.meta["worker_id"] = worker_id
            if not handle.is_actor and handle.leased_to is None:
                # demand paging: a parked waiter (missed actor start)
                # beats the idle pool — the worker goes straight to work
                if not self._offer_pool_worker(handle):
                    handle.idle_since = time.monotonic()
                    self.idle_workers.append(handle)
                    await self._drain_pending_leases()
        return {
            "node_id": self.node_id,
            "head_addr": {"host": self.head_host, "port": self.head_port},
            "store_dir": self.store_dir,
            # folded-in GetNodeInfo: one fewer boot round trip per worker
            "tcp_port": self.tcp_port,
            # flight-recorder ring files live under <session>/events/
            "session_dir": self.session_dir,
            "cluster_config": CONFIG.snapshot(),
        }

    async def _on_disconnect(self, conn: Connection) -> None:
        driver_id = conn.meta.get("driver_id")
        if driver_id:
            self._driver_clients.pop(driver_id, None)
        worker_id = conn.meta.get("worker_id")
        if worker_id:
            handle = self.workers.get(worker_id)
            if handle:
                await self._handle_worker_exit(handle, "connection closed")

    async def _handle_worker_exit(self, handle: WorkerHandle, reason: str) -> None:
        pid = getattr(handle.proc, "pid", None) if handle.proc is not None \
            else None
        if pid and not handle.alive:
            lifecycle.unregister_process(self.session_dir, pid)
        if pid:
            self._pid_handles.pop(pid, None)
        popped = self.workers.pop(handle.worker_id, None)
        if popped is not None and not handle.registered.is_set():
            # died between launch and registration: the register path that
            # normally decrements the starting count never ran. Pop-guarded
            # so a handle processed by both the reaper and the actor
            # watchdog is decremented exactly once.
            self._starting_workers = max(0, self._starting_workers - 1)
        handle.exited.set()
        self._spawn_slot_freed(handle)
        self._plain_spawn_done(handle)
        if handle.actor_id and \
                self.workers_by_actor.get(handle.actor_id) is handle:
            self.workers_by_actor.pop(handle.actor_id, None)
        if handle in self.idle_workers:
            self.idle_workers.remove(handle)
        if handle.leased_to:
            self._release_lease(handle.leased_to, handle)
        if handle.is_actor and handle.actor_id:
            # bounded retry with jitter: ActorDied is idempotent, and
            # dropping it during a head blip would leave the actor ALIVE
            # in the registry forever (callers keep dispatching into a
            # dead worker)
            try:
                await retry_call(lambda: self.head.call(
                    "ActorDied",
                    {"actor_id": handle.actor_id, "reason": reason},
                    timeout=CONFIG.head_ping_timeout_s))
            except Exception:
                pass
        if handle.alive:
            try:
                handle.terminate()
            except Exception:
                pass

    async def _worker_reaper_loop(self) -> None:
        tick = 0
        while True:
            await asyncio.sleep(CONFIG.worker_spawn_retry_s)
            tick += 1
            # Registered workers announce death through their dropped
            # agent connection (_on_disconnect) or the forkserver death
            # ledger — polling every pid each tick cost 2 syscalls per
            # live worker per 0.5s at 1,000 actors. Fast ticks scan only
            # not-yet-registered launches; a slow full sweep (every 10th
            # tick) stays as the belt-and-braces for missed events.
            full = tick % 10 == 0
            try:
                self._consume_death_ledger()
            except Exception:
                pass
            for handle in list(self.workers.values()):
                if not full and handle.registered.is_set():
                    continue
                if not handle.alive:
                    await self._handle_worker_exit(
                        handle, f"worker process exited (code {handle.proc.poll()})"
                    )
                elif (not handle.registered.is_set()
                      and handle.launched_at is not None
                      and time.monotonic() - handle.launched_at
                      > CONFIG.worker_register_timeout_s):
                    # Launched but never registered (hung before the unix
                    # socket handshake): the actor path has its own
                    # watchdog, but plain-task launches would otherwise pin
                    # their startup slot forever — after
                    # STARTUP_CONCURRENCY such hangs the admission queue is
                    # wedged node-wide. Terminate + evict + free the slot
                    # so queued spawns drain.
                    handle.terminate()
                    handle.mark_failed()
                    await self._handle_worker_exit(
                        handle, "worker failed to register before timeout")
            # Reap idle workers beyond the warm floor. The floor keeps the
            # warm pool alive; extras (burst leftovers returned from
            # leases) go after the pool idle TTL, or the long-standing
            # idle-killing cutoff, whichever expires first.
            now = time.monotonic()
            floor = max(self.max_workers, self.WARM_TARGET)
            cutoff = max(now - CONFIG.idle_worker_killing_time_ms / 1000,
                         now - float(CONFIG.worker_pool_idle_ttl_s))
            while len(self.idle_workers) > floor:
                victim = self.idle_workers[0]
                if victim.idle_since < cutoff:
                    self.idle_workers.pop(0)
                    victim.terminate()
                    self._pool_reaped += 1
                else:
                    break

    # ------------------------------------------------------------- leasing
    async def _request_worker_lease(self, conn: Connection, p: Dict) -> Dict:
        """Grant a worker lease, queue it, or reply with a spillback target.

        The hybrid policy (reference: hybrid_scheduling_policy.h:50): run
        locally while local utilization is below the spread threshold or no
        remote node is better; otherwise spill to the least-utilized feasible
        remote node.
        """
        request = ResourceSet.from_wire(p.get("resources", {}))
        pg = p.get("pg")  # [pg_id, bundle_index] or None
        if not p.get("spilled_once"):
            target = self._maybe_spillback(request, p)
            if target is not None:
                return {"spillback": target}
        fut = asyncio.get_running_loop().create_future()
        req = {"resources": request, "p": p, "fut": fut, "pg": pg}
        self._pending_leases.append(req)
        await self._drain_pending_leases()
        return await fut

    async def _request_worker_lease_batch(self, conn: Connection,
                                          p: Dict) -> Dict:
        """One frame opens N identical lease requests (ISSUE 10 batched
        RPCs). Entries resolve INDEPENDENTLY — each grant/spillback/error
        streams back as a ``LeaseItem`` push the moment it lands, so a
        fast grant is never gated on a sibling queued behind capacity;
        the frame's reply just closes the batch (same shape as the worker
        PushTaskBatchStream protocol)."""
        n = max(1, int(p.get("n", 1)))
        bid = p.get("b")
        _note_hist(self._lease_batch_hist, n)

        async def one(i: int) -> None:
            try:
                reply = await self._request_worker_lease(conn, p)
            except Exception as e:  # noqa: BLE001 — per-entry blast radius
                reply = {"error": "lease", "message": repr(e)}
            try:
                conn.push_nowait("LeaseItem", {"b": bid, "i": i, "r": reply})
            except Exception:
                pass  # requester gone; the closing reply fails too

        await asyncio.gather(*[one(i) for i in range(n)])
        return {"n": n}

    # ----------------------------------------- batched readiness relay
    async def _report_actor_ready(self, conn: Connection, p: Dict) -> bool:
        """Worker→head ActorReady relay (ISSUE 10): workers report over
        their (unix) agent connection; the agent coalesces a creation
        burst into ONE ActorReadyBatch head RPC (+ one WAL group commit
        head-side) per flush window. The worker is acked only after the
        head acked — its retry/exit-on-persistent-failure contract (the
        PROFILE_ACTORS zombie fix) is preserved end to end."""
        fut = asyncio.get_running_loop().create_future()
        self._last_ready_report = time.monotonic()
        self._ready_queue.append((p, fut))
        if not self._ready_flush_armed:
            self._ready_flush_armed = True
            asyncio.get_running_loop().call_later(
                max(CONFIG.actor_ready_batch_window_ms, 0) / 1000.0,
                lambda: spawn_tracked(self._flush_ready_batch(),
                                      "agent-ready-flush"))
        return await fut

    async def _flush_ready_batch(self) -> None:
        self._ready_flush_armed = False
        batch, self._ready_queue = self._ready_queue, []
        if not batch:
            return
        _note_hist(self._ready_batch_hist, len(batch))
        items = [p for p, _f in batch]
        try:
            await retry_call(lambda: self.head.call(
                "ActorReadyBatch",
                {"items": items, "node_id": self.node_id},
                timeout=CONFIG.control_rpc_timeout_s))
        except Exception as e:
            for _p, fut in batch:
                if not fut.done():
                    fut.set_exception(
                        ConnectionError(f"ActorReadyBatch failed: {e!r}"))
            return
        for _p, fut in batch:
            if not fut.done():
                fut.set_result(True)

    async def _get_worker_pool_stats(self, conn: Connection, p) -> Dict:
        return {
            "warm_target": self.WARM_TARGET,
            "warm": self._warm_idle_count(),
            "idle": len(self.idle_workers),
            "workers": len(self.workers),
            "starting": self._starting_workers,
            "spawning_plain": self._spawning_plain,
            "hits": self._pool_hits,
            "misses": self._pool_misses,
            # demand-paged handouts (ISSUE 11): missed-then-served by a
            # pre-forked pool worker instead of a dedicated cold fork
            "demand_hits": self._demand_hits,
            "waiters": sum(1 for f in self._pool_waiters
                           if not f.done()),
            "recent_demand": self._recent_demand(),
            "refills": self._pool_refills,
            "reaped": self._pool_reaped,
            "spawned_total": getattr(self, "_workers_spawned", 0),
            "lease_batch_hist": dict(self._lease_batch_hist),
            "ready_batch_hist": dict(self._ready_batch_hist),
        }

    def _maybe_spillback(self, request: ResourceSet, p: Dict) -> Optional[Dict]:
        target = self._maybe_spillback_inner(request, p)
        if target is not None:
            # feeds ray_tpu_scheduler_spillbacks_total
            self._spillback_count = getattr(self, "_spillback_count", 0) + 1
        return target

    def _maybe_spillback_inner(self, request: ResourceSet,
                               p: Dict) -> Optional[Dict]:
        strategy = p.get("scheduling_strategy") or {}
        if isinstance(strategy, dict) and strategy.get("type") == "node_label":
            hard = strategy.get("hard") or {}
            soft = strategy.get("soft") or {}
            local_ok = (label_constraints_match(self.resources.labels, hard)
                        and request.feasible_on(self.resources.total))
            # Candidate remotes that satisfy hard + feasibility; prefer
            # soft-matching ones (best-effort, reference: node-label soft).
            candidates = []
            for node_id, view in self.cluster_view.items():
                if node_id == self.node_id or not view.get("alive", True):
                    continue
                nr = NodeResources.from_wire(view["resources"])
                if (label_constraints_match(nr.labels, hard)
                        and request.feasible_on(nr.total)):
                    candidates.append(
                        (label_constraints_match(nr.labels, soft),
                         node_id, view["addr"]))
            if local_ok and (label_constraints_match(self.resources.labels, soft)
                             or not any(c[0] for c in candidates)):
                return None
            for prefer_soft in (True, False):
                for soft_ok, node_id, addr in candidates:
                    if soft_ok == prefer_soft:
                        return {"node_id": node_id, "addr": addr}
            return None
        if isinstance(strategy, dict) and strategy.get("type") == "node_affinity":
            target_node = strategy.get("node_id")
            if target_node and target_node != self.node_id:
                view = self.cluster_view.get(target_node)
                if view:
                    return {"node_id": target_node, "addr": view["addr"]}
            return None
        if p.get("pg"):
            return None  # PG leases run where the bundle lives; caller targeted us
        spread = isinstance(strategy, dict) and strategy.get("type") == "spread"
        local_feasible = request.feasible_on(self.resources.total)
        local_fits = request.fits(self.resources.available)
        local_util = self.resources.utilization()
        if (
            local_feasible
            and local_fits
            and not spread
            and local_util < CONFIG.scheduler_spread_threshold
        ):
            return None
        # Consider remote nodes from the gossip view.
        best = None
        best_util = None
        for node_id, view in self.cluster_view.items():
            if node_id == self.node_id or not view.get("alive", True):
                continue
            nr = NodeResources.from_wire(view["resources"])
            self._apply_recent_spills(node_id, nr)
            if not request.feasible_on(nr.total):
                continue
            if not request.fits(nr.available):
                continue
            util = nr.utilization()
            if best is None or util < best_util:
                best, best_util = (node_id, view["addr"]), util
        if best is None:
            return None
        if not local_feasible or not local_fits:
            self._record_spill(best[0], request)
            return {"node_id": best[0], "addr": best[1]}
        if spread or local_util >= CONFIG.scheduler_spread_threshold:
            if best_util < local_util:
                self._record_spill(best[0], request)
                return {"node_id": best[0], "addr": best[1]}
        return None

    @property
    def SPILL_LEDGER_TTL_S(self) -> float:
        return CONFIG.spill_ledger_ttl_ms / 1000.0

    def _apply_recent_spills(self, node_id: str, nr: NodeResources) -> None:
        ledger = self._recent_spills.get(node_id)
        if not ledger:
            return
        now = time.monotonic()
        live = [(t, rs) for t, rs in ledger if t > now]
        if live:
            self._recent_spills[node_id] = live
        else:
            self._recent_spills.pop(node_id, None)
        for _t, rs in live:
            nr.available.subtract(rs, allow_negative=True)

    def _record_spill(self, node_id: str, request: ResourceSet) -> None:
        if os.environ.get("RAY_TPU_DEBUG"):
            print(f"SPILL {self.node_id[:8]} -> {node_id[:8]} "
                  f"{request.to_dict()}", file=sys.stderr, flush=True)
        self._recent_spills.setdefault(node_id, []).append(
            (time.monotonic() + self.SPILL_LEDGER_TTL_S, request))

    async def _drain_pending_leases(self) -> None:
        made_progress = True
        while made_progress and self._pending_leases:
            made_progress = False
            for req in list(self._pending_leases):
                if await self._try_grant(req):
                    self._pending_leases.remove(req)
                    made_progress = True
                    continue
                # A queued request that this node can never (or not soon)
                # satisfy gets re-evaluated for spillback as the gossip view
                # evolves — otherwise a request that arrived before the view
                # caught up would wedge here forever.
                p = req["p"]
                if not p.get("spilled_once"):
                    target = self._maybe_spillback(req["resources"], p)
                    if target is not None and not req["fut"].done():
                        req["fut"].set_result({"spillback": target})
                        self._pending_leases.remove(req)
                        made_progress = True

    async def _try_grant(self, req: Dict) -> bool:
        request: ResourceSet = req["resources"]
        strategy = req["p"].get("scheduling_strategy") or {}
        if isinstance(strategy, dict) and strategy.get("type") == "node_label":
            if not label_constraints_match(self.resources.labels,
                                           strategy.get("hard") or {}):
                return False
        pg = req.get("pg")
        pg_key = None
        if pg:
            pg_key = self._match_pg_bundle(pg, request)
            if pg_key is None:
                if any(k[0] == pg[0] for k in self._pg_bundles):
                    return False  # bundles exist but are full: stay queued
                # Every bundle of this group is gone from this node — the
                # group was removed; fail the lease instead of wedging it.
                fut: asyncio.Future = req["fut"]
                if not fut.done():
                    fut.set_result({"error": "pg_removed"})
                return True
        elif not request.fits(self.resources.available):
            return False
        env_key = req["p"].get("env_key")
        container = req["p"].get("container")
        conda = req["p"].get("conda")
        # container/conda envs apply at SPAWN (the process must start
        # inside the image / under the env's interpreter), so a pristine
        # host worker can never serve them: match only workers already
        # tagged with this env_key
        spawn_env = bool(container or conda)
        # language-tagged leases ({"language": "cpp"}) can only run on a
        # worker of that language; those register EXTERNALLY (reference:
        # C++ worker processes joining the cluster) — never spawn a
        # Python worker for them, just wait for one to appear
        lang_env = _env_key_language(env_key) is not None
        worker = self._pop_idle_worker(
            env_key, tagged_only=spawn_env or lang_env)
        if worker is None:
            if lang_env:
                return False
            if len(self.workers) + self._starting_workers < self.max_workers + 8 \
                    or self._evict_mismatched_idle():
                if conda and not container:
                    self._spawn_conda_worker(conda, env_key, req)
                else:
                    self._spawn_worker(container=container,
                                       env_key=env_key if spawn_env else None)
            return False
        # allocate resources
        assigned_instances: Dict[str, list] = {}
        if pg:
            self._pg_available[pg_key].subtract(request)
        else:
            assigned_instances = self.resources.allocate(request, owner=worker.worker_id) or {}
            self._resources_dirty = True
        self._lease_counter += 1
        lease_id = f"{self.node_id[:8]}-{self._lease_counter}"
        worker.leased_to = lease_id
        worker.assigned_resources = request
        worker.lease_owner = req["p"].get("owner", "")
        worker.lease_start = time.monotonic()
        worker.lease_retriable = bool(req["p"].get("retriable", True))
        self.leases[lease_id] = worker
        worker.meta_pg = list(pg_key) if pg_key else None
        fut: asyncio.Future = req["fut"]
        if not fut.done():
            if env_key is not None:
                # tag only on a delivered grant: the worker will apply this
                # runtime_env on its first task and can never serve another
                worker.env_key = env_key
            fut.set_result(
                {
                    "grant": {
                        "lease_id": lease_id,
                        "worker_id": worker.worker_id,
                        "addr": worker.direct_addr,
                        "node_id": self.node_id,
                        "assigned_instances": assigned_instances,
                    }
                }
            )
        else:
            self._release_lease(lease_id, worker)
            self.idle_workers.append(worker)
        return True

    def _pop_idle_worker(self, env_key: Optional[str] = None,
                         tagged_only: bool = False
                         ) -> Optional[WorkerHandle]:
        # prune dead workers (incl. pid-ledger deaths and connections
        # already mid-close — the disconnect callback may not have run
        # yet), then prefer an env-matching worker, falling back to a
        # pristine one (tagged by the caller on grant).
        # tagged_only: spawn-time envs (container) can never ride a
        # pristine host worker — exact tag match or nothing.
        self.idle_workers = [w for w in self.idle_workers
                             if w.alive and w.registered.is_set()
                             and (w.conn is None or not w.conn.closed)]
        tiers = (env_key,) if tagged_only else (env_key, None)
        for tier in tiers:
            for i in range(len(self.idle_workers) - 1, -1, -1):
                if self.idle_workers[i].env_key == tier:
                    return self.idle_workers.pop(i)
        return None

    def _evict_mismatched_idle(self) -> bool:
        """Kill one idle worker with a foreign runtime_env to make room for
        a fresh process (its env cannot be un-applied)."""
        for i, w in enumerate(self.idle_workers):
            # externally-managed language workers (C++) are not ours to
            # recycle for Python leases
            if w.env_key is not None and \
                    _env_key_language(w.env_key) is None:
                self.idle_workers.pop(i)
                w.terminate()
                self.workers.pop(w.worker_id, None)
                self._env_evictions = getattr(self, "_env_evictions", 0) + 1
                return True
        return False

    async def _return_worker(self, conn: Connection, p: Dict) -> bool:
        lease_id = p["lease_id"]
        worker = self.leases.get(lease_id)
        if worker is None:
            return False
        self._release_lease(lease_id, worker)
        if p.get("worker_exiting") or not worker.alive:
            return True
        if self._offer_pool_worker(worker):
            return True  # returned lease feeds a parked actor start
        worker.idle_since = time.monotonic()
        self.idle_workers.append(worker)
        await self._drain_pending_leases()
        return True

    def _release_lease(self, lease_id: str, worker: WorkerHandle) -> None:
        self.leases.pop(lease_id, None)
        if worker.assigned_resources is not None:
            pg = getattr(worker, "meta_pg", None)
            if pg:
                pool = self._pg_available.get((pg[0], pg[1]))
                if pool is not None:
                    pool.add(worker.assigned_resources)
            else:
                self.resources.release(worker.assigned_resources, owner=worker.worker_id)
                self._resources_dirty = True
        worker.assigned_resources = None
        worker.leased_to = None
        worker.meta_pg = None

    # ---------------------------------------------------------------- actors
    async def _start_actor(self, p: Dict) -> None:
        rec_ev = _events.REC
        ev_trace = rec_ev.new_trace() if rec_ev.enabled and rec_ev.sample() \
            else None
        ev_t0 = time.time() if ev_trace is not None else 0.0
        spec = p["spec"]
        request = ResourceSet.from_wire(spec.get("resources", {}))
        pg = spec.get("pg")
        if pg:
            # Wait for bundle capacity like the non-PG path waits for node
            # resources: a just-returned lease may still hold the bundle.
            deadline = time.monotonic() + CONFIG.actor_creation_timeout_ms / 1000
            while True:
                key = self._match_pg_bundle(pg, request)
                if key is not None:
                    break
                if not any(k[0] == pg[0] for k in self._pg_bundles) or \
                        time.monotonic() > deadline:
                    await self.head.call(
                        "ActorDied",
                        {"actor_id": p["actor_id"],
                         "reason": "pg bundle unavailable"},
                        timeout=CONFIG.control_rpc_timeout_s,
                    )
                    return
                await asyncio.sleep(CONFIG.actor_resource_wait_poll_s)
            pg = list(key)
            self._pg_available[key].subtract(request)
            assigned = {}
        else:
            deadline = time.monotonic() + CONFIG.actor_creation_timeout_ms / 1000
            while not request.fits(self.resources.available):
                if time.monotonic() > deadline:
                    await self.head.call(
                        "ActorDied",
                        {"actor_id": p["actor_id"],
                         "reason": "timed out waiting for actor resources"},
                        timeout=CONFIG.control_rpc_timeout_s,
                    )
                    return
                await asyncio.sleep(CONFIG.actor_resource_wait_poll_s)
            assigned = self.resources.allocate(request, owner=p["actor_id"]) or {}
            self._resources_dirty = True
        # Warm-pool lease (ISSUE 10): a pre-booted pristine worker skips
        # the whole fork + loop setup + handshake + store-attach boot
        # (~0.1 core-s measured, PROFILE_ACTORS step 4) — actor creation
        # pays only class unpickle + __init__. Cold fork is the fallback,
        # never a failure mode.
        handle = self._lease_warm_worker()
        ev_source = "warm_hit"
        if handle is not None:
            self._pool_hits += 1
        else:
            if self.warm_lease_enabled and CONFIG.worker_pool_demand_paging:
                # demand paging (ISSUE 11): park for the next pool
                # registration — the pre-forked pipeline from
                # _note_actor_demand is already booting toward us
                handle = await self._wait_pool_worker()
            if handle is not None:
                self._demand_hits += 1
                self._last_warm_lease = time.monotonic()
                ev_source = "demand_hit"
            else:
                self._pool_misses += 1
                handle = self._spawn_worker()
                ev_source = "fork"
        if ev_trace is not None:
            # resource wait + pool decision, tagged with how the start was
            # served — the per-hop answer to "warm hit or cold fork?"
            rec_ev.record("actor_start::" + ev_source, "actor", ev_t0,
                          time.time() - ev_t0, ev_trace[0], ev_trace[1], 0,
                          {"actor": str(p.get("actor_id", ""))[:16]})
        handle.is_actor = True
        handle.actor_id = p["actor_id"]
        handle.assigned_resources = None  # released via actor-death path below
        self.workers_by_actor[p["actor_id"]] = handle

        async def finish():
            # the register timeout counts from the actual LAUNCH (fork),
            # not from enqueue: under spawn admission a 1000-actor burst
            # legitimately queues for minutes
            while True:
                try:
                    await asyncio.wait_for(handle.registered.wait(), 5.0)
                    break
                except asyncio.TimeoutError:
                    if handle.worker_id not in self.workers or (
                            handle.launched_at is not None
                            and time.monotonic() - handle.launched_at
                            > CONFIG.worker_register_timeout_s):
                        # a hung launch must not pin its startup slot or
                        # linger in the pool — terminate + evict, or the
                        # admission queue wedges node-wide after
                        # STARTUP_CONCURRENCY such hangs
                        handle.terminate()
                        handle.mark_failed()
                        if self.workers.pop(handle.worker_id, None) \
                                is not None and \
                                not handle.registered.is_set():
                            # same accounting as _handle_worker_exit: the
                            # register path that decrements never ran
                            self._starting_workers = max(
                                0, self._starting_workers - 1)
                        handle.exited.set()
                        self._spawn_slot_freed(handle)
                        await self.head.call(
                            "ActorDied",
                            {"actor_id": p["actor_id"],
                             "reason": "worker failed to start"},
                            timeout=CONFIG.control_rpc_timeout_s,
                        )
                        return
            await handle.conn.push(
                "BecomeActor",
                {"spec": spec, "actor_id": p["actor_id"],
                 "assigned_instances": assigned},
            )

        spawn_tracked(finish(), "agent-actor-finish")

        # Hold the resources until the actor dies. An evicted/never-
        # launched handle (no longer in the pool) counts as dead — its
        # resources must flow back (the spawn may have failed with
        # proc=None, which `alive` alone reads as still-starting).
        async def watch_release():
            # event-driven with a slow fallback poll: N live actors must
            # not cost the loop N wakeups per poll period
            while handle.alive and handle.worker_id in self.workers:
                try:
                    await asyncio.wait_for(
                        handle.exited.wait(),
                        timeout=CONFIG.actor_liveness_poll_s)
                except asyncio.TimeoutError:
                    pass
            if pg:
                pool = self._pg_available.get((pg[0], pg[1]))
                if pool is not None:
                    pool.add(request)
            else:
                self.resources.release(request, owner=p["actor_id"])
                self._resources_dirty = True

        spawn_tracked(watch_release(), "agent-actor-release")

    def _kill_actor_worker(self, actor_id: str) -> None:
        handle = self.workers_by_actor.get(actor_id)
        if handle is None:
            return
        try:
            handle.terminate()
        except Exception:
            pass

        # SIGTERM is advisory: a worker wedged inside a native collective
        # (dead-peer jax/gloo rendezvous holds the GIL in C++) never runs
        # the Python signal handler and only dies at the collective's own
        # timeout (~100s) — which stalls the killed actor's PG bundle and
        # wedges the elastic restart behind it. Escalate to SIGKILL after
        # a bounded grace.
        async def escalate():
            try:
                await asyncio.wait_for(
                    handle.exited.wait(),
                    timeout=float(CONFIG.worker_kill_escalation_s))
            except asyncio.TimeoutError:
                if handle.alive:
                    handle.hard_kill()

        spawn_tracked(escalate(), "agent-kill-escalate")

    # ------------------------------------------------------ placement groups
    def _match_pg_bundle(self, pg, request: ResourceSet):
        """Map a lease/actor pg target onto a concrete local bundle.

        bundle_index -1 means "any bundle of the group" (reference semantics:
        placement_group.py bundle_index default); scan this node's bundles of
        the group for one the request fits.
        """
        pg_id, idx = pg[0], pg[1]
        if idx is not None and idx >= 0:
            pool = self._pg_available.get((pg_id, idx))
            if pool is not None and request.fits(pool):
                return (pg_id, idx)
            if (pg_id, idx) in self._pg_bundles:
                return None  # exists but full — caller decides to queue
            return None
        for key, pool in sorted(self._pg_available.items()):
            if key[0] == pg_id and request.fits(pool):
                return key
        return None

    def _prepare_pg_bundle(self, p: Dict) -> bool:
        key = (p["pg_id"], p["bundle_index"])
        if key in self._pg_bundles:
            return True
        request = ResourceSet.from_wire(p["resources"])
        if self.resources.allocate(request) is None:
            return False
        self._pg_bundles[key] = request
        self._pg_available[key] = request.copy()
        self._resources_dirty = True
        return True

    def _return_pg_bundle(self, p: Dict) -> None:
        key = (p["pg_id"], p["bundle_index"])
        request = self._pg_bundles.pop(key, None)
        self._pg_available.pop(key, None)
        if request is not None:
            self.resources.release(request)
            self._resources_dirty = True
        # Queued leases targeting this group must fail now, not hang: the
        # drain's _try_grant sees the bundles are gone and replies pg_removed.
        spawn_tracked(self._drain_pending_leases(), "agent-pg-drain")

    # --------------------------------------------------------- object plane
    async def _object_sealed(self, conn: Connection, p: Dict) -> None:
        hex_id = p["object_id"]
        self.store.on_sealed(hex_id, p["size"])
        if "replayable" in p:
            # lineage hints (ISSUE 17): drive the store's lineage-aware
            # eviction (prefer dropping cheap-to-replay copies)
            self.store.note_lineage(hex_id, bool(p.get("replayable")),
                                    float(p.get("exec_ms") or 0.0))
        if p.get("zero_copy"):
            self._zero_copy_puts += 1
        owner = p.get("owner")
        if owner:
            # object ledger (ISSUE 15): remember who OWNS each sealed
            # object (+ its creating task/callsite) so the leak watchdog
            # can interrogate the owner later and attribution survives
            # the owner row dropping (free in flight). Pruned on free
            # and by the watchdog/stats scan when the object leaves the
            # store.
            self._object_owners[hex_id] = {
                "owner": owner, "task": p.get("task") or "",
                "callsite": p.get("callsite") or "",
                "sealed_at": time.time()}
        for fut in self._object_waits.pop(hex_id, []):
            if not fut.done():
                fut.set_result(True)

    async def _wait_objects(self, conn: Connection, p: Dict) -> Dict:
        """Wait until num_returns of the ids are local, pulling remotes.

        p: {ids: [hex], owners: {hex: owner_addr}, locations: {hex:
        [addr]}, num_returns, timeout_ms}. ``locations`` are the
        caller's last-known holders (owner directory / borrow reply) —
        used as a routed-fetch fallback when the owner is unreachable.
        """
        ids: List[str] = p["ids"]
        owners: Dict[str, Dict] = p.get("owners", {})
        hints: Dict[str, List[Dict]] = p.get("locations", {}) or {}
        num_returns = p.get("num_returns", len(ids))
        timeout_ms = p.get("timeout_ms")
        tc = p.get("tc")  # caller's trace context (sampled get)
        futs = {}
        for hex_id in ids:
            waited_owner = owners.get(hex_id)
            if waited_owner and hex_id not in self._object_owners:
                # pulls announce owners too: a pulled copy on this node is
                # leak-scannable even though it was sealed elsewhere
                self._object_owners[hex_id] = {
                    "owner": waited_owner, "task": "",
                    "sealed_at": time.time()}
            if self.store.contains(hex_id):
                continue
            fut = asyncio.get_running_loop().create_future()
            self._object_waits.setdefault(hex_id, []).append(fut)
            # re-attaching invalidates any pending orphan-reap timer
            self._pull_orphan_stamp.pop(hex_id, None)
            futs[hex_id] = fut
            owner = owners.get(hex_id)
            if owner and hex_id not in self._pulls_inflight:
                self._pulls_inflight[hex_id] = asyncio.get_running_loop().create_task(
                    self._pull_object(hex_id, owner, tc=tc,
                                      hint_locs=hints.get(hex_id))
                )

        def ready_count() -> int:
            return sum(1 for h in ids if self.store.contains(h))

        deadline = None if timeout_ms is None else time.monotonic() + timeout_ms / 1000
        try:
            while ready_count() < num_returns:
                pending = [f for f in futs.values() if not f.done()]
                if not pending:
                    break
                wait_timeout = None
                if deadline is not None:
                    wait_timeout = deadline - time.monotonic()
                    if wait_timeout <= 0:
                        break
                # Cap each wait to re-poll the (filesystem-authoritative) store:
                # seal notifications are fire-and-forget and can be lost if the
                # sealing worker dies right after store.seal — the object is
                # still on disk, so the poll keeps waiters from hanging forever.
                poll_s = CONFIG.object_wait_poll_ms / 1000.0
                poll = poll_s if wait_timeout is None \
                    else min(wait_timeout, poll_s)
                done, _ = await asyncio.wait(
                    pending, timeout=poll, return_when=asyncio.FIRST_COMPLETED
                )
                if not done and deadline is not None \
                        and time.monotonic() >= deadline:
                    break
        finally:
            # Deregister this call's waiters; when an object's LAST waiter
            # leaves (get timed out, caller gone), cancel its in-flight
            # pull instead of letting it burn the full pull deadline
            # re-locating an object nobody wants.
            for hex_id, fut in futs.items():
                waiters = self._object_waits.get(hex_id)
                if waiters is None:
                    continue
                try:
                    waiters.remove(fut)
                except ValueError:
                    pass
                if not waiters:
                    del self._object_waits[hex_id]
                    self._cancel_orphan_pull(hex_id)
        ready = [h for h in ids if self.store.contains(h)]
        not_ready = [h for h in ids if h not in set(ready)]
        return {"ready": ready, "not_ready": not_ready}

    def _cancel_orphan_pull(self, hex_id: str) -> None:
        """Schedule cancellation of the pull task for an object with no
        waiters left — after a grace window, so a get() retried on a short
        timeout re-attaches to the running transfer instead of restarting
        it from byte 0. If the grace expires with still no waiter, the
        task is popped + cancelled (eagerly popped so a later waiter
        starts fresh instead of parking behind a zombie) and parks in
        ``_pulls_draining`` so that fresh pull defers to its cleanup (the
        old abort would unlink the new transfer's unsealed allocation)."""
        task = self._pulls_inflight.get(hex_id)
        if task is None or task.done():
            return
        stamp = time.monotonic()
        self._pull_orphan_stamp[hex_id] = stamp

        async def reap():
            await asyncio.sleep(CONFIG.object_pull_orphan_grace_s)
            if self._pull_orphan_stamp.get(hex_id) != stamp:
                # a waiter re-attached (stamp popped) or a LATER detach
                # re-stamped — only the newest timer may cancel, so the
                # grace always runs full length from the last departure
                return
            self._pull_orphan_stamp.pop(hex_id, None)
            if self._object_waits.get(hex_id):
                return  # a new waiter re-attached; keep the pull
            if self._pulls_inflight.get(hex_id) is not task or task.done():
                return  # finished, or a different pull took the slot
            self._pulls_inflight.pop(hex_id, None)
            task.cancel()
            self._pulls_draining.setdefault(hex_id, []).append(task)

            def _drained(t, h=hex_id):
                lst = self._pulls_draining.get(h)
                if lst is not None:
                    try:
                        lst.remove(t)
                    except ValueError:
                        pass
                    if not lst:
                        self._pulls_draining.pop(h, None)

            task.add_done_callback(_drained)

        spawn_tracked(reap(), "agent-orphan-pull-reap")

    async def _pull_object(self, hex_id: str, owner: Dict,
                           tc=None, hint_locs=None) -> None:
        """Flight-recorder shell around the pull: one ``pull`` span per
        admission, stitched under the caller's get() trace when the
        WaitObjects frame carried one, else its own sampled root."""
        rec = _events.REC
        if rec.enabled and (tc is not None or rec.sample()):
            if tc is None:
                trace, parent = rec.new_trace()[0], 0
            else:
                trace, parent = tc[0], tc[1]
            span = rec.next_id()
            t0 = time.time()
            rec.open_marker("pull", "object", trace, span, parent,
                            {"obj": hex_id[:16]})
            try:
                await self._pull_object_inner(hex_id, owner,
                                              tc=(trace, span),
                                              hint_locs=hint_locs)
            finally:
                rec.record("pull", "object", t0, time.time() - t0,
                           trace, span, parent,
                           {"obj": hex_id[:16],
                            "sealed": bool(self.store.contains(hex_id))})
        else:
            await self._pull_object_inner(hex_id, owner,
                                          hint_locs=hint_locs)

    async def _pull_object_inner(self, hex_id: str, owner: Dict,
                                 tc=None, hint_locs=None) -> None:
        """Owner-directed pull (reference: pull_manager.h + ownership-based
        object directory): ask the owner where the object lives, then hand
        the holder set to the pull manager — windowed pipeline, multi-
        holder striping, budgeted admission (pull_manager.py) — or take
        the inline value from the owner."""
        task = asyncio.current_task()
        try:
            while True:
                # cancelled predecessors may still be tearing down their
                # transfers (aborting the unsealed store allocation we
                # would otherwise collide with). asyncio.wait — NOT gather
                # — so cancelling THIS pull mid-wait never re-cancels a
                # predecessor out of its cleanup.
                draining = [t for t in self._pulls_draining.get(hex_id, [])
                            if not t.done()]
                if not draining:
                    break
                await asyncio.wait(draining)
            deadline = time.monotonic() + CONFIG.object_pull_deadline_s
            dead_rounds = 0
            while time.monotonic() < deadline:
                if self.store.contains(hex_id):
                    return
                try:
                    client = await self.pool.get(owner["host"], owner["port"])
                    loc = await client.call(
                        "LocateObject", {"object_id": hex_id},
                        timeout=CONFIG.object_locate_timeout_s
                    )
                except asyncio.CancelledError:
                    raise
                except Exception:
                    # Owner unreachable: fall back to the caller's hinted
                    # holders (borrow-reply locations survive the owner)
                    # before the blind sleep-retry — a borrower can often
                    # restore from a live replica while the owner's node
                    # is mid-recovery.
                    hinted = [
                        a for a in (hint_locs or [])
                        if not (a.get("host") == "127.0.0.1"
                                and a.get("port") == self.tcp_port)]
                    if hinted:
                        st = await self._fetch_routed(hex_id, hinted,
                                                      tc=tc)
                        if st == "ok":
                            self._notify_sealed(hex_id)
                            return
                    await asyncio.sleep(CONFIG.object_pull_retry_s)
                    continue
                if loc is None:
                    await asyncio.sleep(CONFIG.object_unlocated_retry_s)
                    continue
                if loc.get("inline") is not None:
                    data = loc["inline"]
                    self.store.client.put_bytes(ObjectID.from_hex(hex_id), data)
                    self.store.on_sealed(hex_id, len(data))
                    self._notify_sealed(hex_id)
                    return
                remote_locs = [
                    a for a in loc.get("locations", [])
                    if not (a.get("host") == "127.0.0.1"
                            and a.get("port") == self.tcp_port)]
                if not remote_locs:
                    # remote-tier spill: this node dropped its local copy
                    # against recorded remote holders — those are a valid
                    # restore source even when the owner only lists us
                    remote_locs = self.store.remote_sources_for(hex_id)
                st = "absent"
                if remote_locs:
                    st = await self._fetch_routed(hex_id, remote_locs,
                                                  tc=tc)
                if st == "ok":
                    self._notify_sealed(hex_id)
                    # Tell the owner we now hold a copy.
                    try:
                        await client.push(
                            "ObjectLocationAdded",
                            {"object_id": hex_id,
                             "addr": {"host": "127.0.0.1", "port": self.tcp_port}},
                        )
                    except Exception:
                        pass
                    return
                if remote_locs and st == "conn":
                    # Every advertised holder is connection-dead (not merely
                    # missing the object or a local hiccup). After a few
                    # rounds, fail the wait so the owner's lineage recovery
                    # can resubmit the creating task instead of burning the
                    # caller's whole get deadline (reference: pull_manager
                    # hands off to reconstruction on location death).
                    dead_rounds += 1
                    if dead_rounds >= CONFIG.pull_dead_holder_rounds:
                        for fut in self._object_waits.pop(hex_id, []):
                            if not fut.done():
                                fut.set_result(False)
                        return
                else:
                    dead_rounds = 0
                await asyncio.sleep(CONFIG.object_pull_round_s)
            # deadline exhausted: fail the waiters so a timeout-less
            # WaitObjects (and the get() blocked on it) sees a lost
            # verdict instead of polling forever on futures nobody will
            # ever resolve
            for fut in self._object_waits.pop(hex_id, []):
                if not fut.done():
                    fut.set_result(False)
        finally:
            # identity-guarded: an orphan-cancel may have popped this task
            # already and a NEW pull registered under the same object
            if self._pulls_inflight.get(hex_id) is task:
                self._pulls_inflight.pop(hex_id, None)

    def _notify_sealed(self, hex_id: str) -> None:
        for fut in self._object_waits.pop(hex_id, []):
            if not fut.done():
                fut.set_result(True)

    async def _fetch_routed(self, hex_id: str, holders: List[Dict],
                            tc=None) -> str:
        """Route one pull: the spanning broadcast tree for large objects
        (K consumers of the same object share O(log N) distribution via
        chunk-level relay) with transparent degradation to the plain
        multi-holder striped pull — the tree is an optimization layer,
        never a new failure mode."""
        from ray_tpu._private import broadcast

        rec = _events.REC

        async def spanned(name, coro, n_holders):
            if tc is None or not rec.enabled:
                return await coro
            t0 = time.time()
            st = await coro
            rec.record(name, "object", t0, time.time() - t0, tc[0],
                       rec.next_id(), tc[1],
                       {"obj": hex_id[:16], "st": st,
                        "holders": n_holders})
            return st

        size, alive, any_absent = await self.pulls._probe_meta(
            hex_id, holders)
        if size is None:
            return "absent" if any_absent else "conn"
        meta = (size, alive, any_absent)
        if not (CONFIG.bcast_enabled and size >= CONFIG.bcast_min_bytes):
            return await spanned(
                "stripe_pull", self.pulls.fetch(hex_id, alive, meta=meta),
                len(alive))
        progress = self.pulls.register_progress(hex_id, size)
        try:
            st = await spanned(
                "bcast_pull",
                broadcast.bcast_fetch(self, hex_id, size, alive, progress),
                len(alive))
            if st == "fallback":
                # keep the SAME progress registered: children this node
                # was assigned relay off the striped pull just the same
                st = await spanned(
                    "stripe_pull",
                    self.pulls.fetch(hex_id, alive, meta=meta,
                                     progress=progress),
                    len(alive))
            return st
        finally:
            self.pulls.unregister_progress(hex_id, progress)

    async def _fetch_object_meta(self, conn: Connection, p: Dict) -> Dict:
        hex_id = p["object_id"]
        view = self.store.read_maybe_spilled(hex_id)
        if view is not None:
            return {"exists": True, "size": len(view)}
        # mid-pull relay source: a broadcast child probing its assigned
        # parent must see the advertised size, not an absent verdict
        prog = self.pulls.active.get(hex_id)
        if prog is not None and not prog.failed:
            return {"exists": True, "size": prog.size, "partial": True}
        return {"exists": False}

    async def _fetch_object_chunk(self, conn: Connection, p: Dict):
        hex_id = p["object_id"]
        # Per-transfer view cache: a windowed pull asks for the SAME object
        # dozens of times in a burst; re-resolving the store view per chunk
        # (native store: lock + pin + finalizer each) was measurable on the
        # serve hot path. Tiny LRU, and TIME-BOUNDED: a cached view pins
        # its object (native arena LRU cannot evict it), so entries idle
        # past the TTL are purged by the node-stats loop — the cache only
        # ever holds objects mid-transfer, never cold ones.
        cache = self._serve_view_cache
        entry = cache.get(hex_id)
        if entry is None:
            view = self.store.read_maybe_spilled(hex_id)
            if view is None:
                # broadcast relay: the object may be mid-pull on this
                # node — serve ranges that have already arrived
                return await self._serve_relay_chunk(
                    hex_id, p["offset"], p["length"])
            cache[hex_id] = [view, time.monotonic()]
            # cap must exceed the batched-get fan-in (8 concurrent
            # transfers from one holder is the common burst) or the LRU
            # thrashes mid-transfer entries on every insert
            while len(cache) > 16:
                cache.popitem(last=False)
        else:
            view = entry[0]
            entry[1] = time.monotonic()
            cache.move_to_end(hex_id)
        off, length = p["offset"], p["length"]
        self._chunks_served = getattr(self, "_chunks_served", 0) + 1
        await self._serve_throttle(length)
        # RawData: header + raw writer.write of the store view slice — no
        # bytes() materialization, no msgpack re-pack of the payload.
        # raylint: disable=R9 -- the serve-view cache entry above IS the
        # pin: it holds the (natively pinned) view until the TTL purge,
        # which outlives the reply write by construction
        return RawData(view[off : off + length])

    async def _serve_throttle(self, length: int) -> None:
        """Per-node upload-bandwidth cap for bulk chunk serving
        (``object_serve_bandwidth_bytes_ps``): a virtual-clock token
        bucket — each served byte advances the node's serve clock, and a
        request sleeps until its slot. Serialized per node (not per
        connection), so a broadcast root's fanout shares one simulated
        uplink the way a real NIC would."""
        bw = CONFIG.object_serve_bandwidth_bytes_ps
        if not bw or length <= 0:
            return
        loop = asyncio.get_running_loop()
        now = loop.time()
        clock = max(getattr(self, "_serve_clock", now), now)
        self._serve_clock = clock + length / bw
        if clock > now:
            await asyncio.sleep(clock - now)

    async def _serve_relay_chunk(self, hex_id: str, off: int, length: int):
        """Serve a chunk out of an in-flight pull's unsealed view — the
        broadcast-tree relay: interior nodes forward ranges while still
        receiving the rest. Waits (bounded) for the range to arrive,
        which also carries a child across this node's own admission
        delay. The bytes are copied out of the unsealed view (chunk-
        sized, one memcpy): its mmap's lifetime belongs to the transfer,
        and an abort must never invalidate a reply mid-write."""
        prog = self.pulls.active.get(hex_id)
        if prog is not None:
            ok = await prog.wait_covered(
                off, length, CONFIG.bcast_chunk_wait_s)
            if ok and prog.view is not None:
                self.pulls.bcast_relay_chunks += 1
                self.pulls.bcast_relay_bytes += length
                # copy BEFORE the bandwidth throttle sleeps: an abort
                # during the sleep nulls prog.view
                payload = bytes(prog.view[off : off + length])
                await self._serve_throttle(length)
                return RawData(payload)
        # the transfer may have sealed-and-unregistered while we waited:
        # the store is now the source of truth. The cache entry is the
        # escaping view's pin (same contract as the main serve path,
        # including its size cap and the bandwidth throttle).
        view = self.store.read_maybe_spilled(hex_id)
        if view is not None:
            cache = self._serve_view_cache
            cache[hex_id] = [view, time.monotonic()]
            while len(cache) > 16:
                cache.popitem(last=False)
            await self._serve_throttle(length)
            # raylint: disable=R9 -- pinned by the cache entry just
            # inserted (same contract as _fetch_object_chunk)
            return RawData(view[off : off + length])
        return None

    async def _get_pull_stats(self, conn: Connection, p) -> Dict:
        stats = self.pulls.stats()
        stats["chunks_served"] = getattr(self, "_chunks_served", 0)
        stats["zero_copy_puts"] = self._zero_copy_puts
        stats["spill"] = self.store.tier_stats()
        return stats

    async def _free_objects(self, conn: Connection, p: Dict) -> None:
        for hex_id in p["ids"]:
            # release the serve view (and its pin) before the store delete
            self._serve_view_cache.pop(hex_id, None)
            self.store.delete(hex_id)
            self._object_owners.pop(hex_id, None)
            self._leak_candidates.pop(hex_id, None)

    async def _pin_object(self, conn: Connection, p: Dict) -> None:
        self.store.pin(p["object_id"])

    async def _unpin_object(self, conn: Connection, p: Dict) -> None:
        self.store.unpin(p["object_id"])

    async def _restore_spilled(self, conn: Connection, p: Dict) -> bool:
        rec = _events.REC
        if rec.enabled and rec.sample():
            t0 = time.time()
            ok = self.store.restore(p["object_id"])
            trace, span = rec.new_trace()
            rec.record("spill_restore", "object", t0, time.time() - t0,
                       trace, span, 0,
                       {"obj": str(p["object_id"])[:16], "ok": bool(ok)})
        else:
            ok = self.store.restore(p["object_id"])
        if ok:
            self._restored_count = getattr(self, "_restored_count", 0) + 1
        return ok

    async def _get_store_stats(self, conn: Connection, p) -> Dict:
        return self.store.stats()

    async def _get_node_info(self, conn: Connection, p) -> Dict:
        return {
            "node_id": self.node_id,
            "tcp_port": self.tcp_port,
            "resources_total": self.resources.total.to_wire(),
            "resources_available": self.resources.available.to_wire(),
            "num_workers": len(self.workers),
            "num_idle": len(self.idle_workers),
            "cluster_view": self.cluster_view,
        }

    # ----------------------------------------------------- node reporter
    def _sample_node_stats(self) -> Dict:
        """One psutil sample + TPU duty (reference:
        dashboard/modules/reporter/reporter_agent.py:277 — per-node
        cpu/mem/disk/net stats; TPU utilization is the SURVEY §5 ask)."""
        import psutil

        vm = psutil.virtual_memory()
        try:
            disk = psutil.disk_usage(self.session_dir)
            disk_stats = {"total": disk.total, "used": disk.used,
                          "percent": disk.percent}
        except Exception:
            disk_stats = {}
        try:
            la1, la5, la15 = os.getloadavg()
        except OSError:
            la1 = la5 = la15 = 0.0
        return {
            "node_id": self.node_id,
            "time": time.time(),
            "cpu_percent": psutil.cpu_percent(interval=None),
            "cpu_count": psutil.cpu_count(),
            "load_avg": [la1, la5, la15],
            "mem_total_bytes": vm.total,
            "mem_used_bytes": vm.total - vm.available,
            "mem_percent": vm.percent,
            "disk": disk_stats,
            "num_workers": len(self.workers),
            "num_idle_workers": len(self.idle_workers),
            "object_store": self.store.stats(),
            "tpu": self._tpu_stats(),
        }

    def _tpu_stats(self) -> Dict:
        """TPU duty: a fake-topology override for tests, else allocation
        fraction from the resource ledger (chips leased / chips total —
        scheduling-level utilization; device-trace-level duty comes from
        the per-worker jax.profiler capture endpoint)."""
        fake = os.environ.get("RAY_TPU_FAKE_TPU_DUTY")
        total = self.resources.total.get("TPU")
        if not total and fake is None:
            return {}
        avail = self.resources.available.get("TPU") or 0.0
        out = {"chips_total": total or 0.0,
               "chips_in_use": (total or 0.0) - avail,
               "utilization": ((total - avail) / total) if total else 0.0}
        if fake is not None:
            out["duty_cycle_percent"] = float(fake)
        return out

    async def _node_stats_loop(self) -> None:
        import json as _json

        from ray_tpu._private.protocol import STATS as _rpc_stats

        period = max(CONFIG.metrics_report_interval_ms, 1000) / 1000
        self.node_stats: Dict = {}
        while True:
            # purge serve-view cache entries idle past ~2 ticks: a held
            # view pins its object against store eviction, so the cache
            # must never outlive the transfer burst it accelerates
            cache = self._serve_view_cache
            cutoff = time.monotonic() - 2 * period
            for hex_id in [h for h, e in cache.items() if e[1] < cutoff]:
                cache.pop(hex_id, None)
            # object-owner ledger prune (ISSUE 15): evictions bypass the
            # FreeObjects handler, so without this tick the ledger would
            # grow with cumulative traffic when the leak watchdog (whose
            # scan also prunes) is disarmed — the default. Entries get a
            # 30s settle window (a just-waited object may not be sealed
            # yet); remote-tier objects are live and keep their entry.
            if self._object_owners:
                now_wall = time.time()
                for hex_id, info in list(self._object_owners.items()):
                    if now_wall - info.get("sealed_at", 0) < 30:
                        continue
                    if self.store.spill_tier(hex_id) == "remote" or \
                            self.store.contains(hex_id):
                        continue
                    self._object_owners.pop(hex_id, None)
            try:
                self.node_stats = await asyncio.to_thread(
                    self._sample_node_stats)
                # publish as Prometheus-schema gauges through the same KV
                # pipeline user metrics ride (util/metrics.py flush_now)
                from ray_tpu.util.metrics import (
                    make_counter_snapshot, make_gauge_snapshot)

                st = self.node_stats
                tags = {"node_id": self.node_id}

                def gauge(name, desc, value):
                    return make_gauge_snapshot(name, desc, value, tags)

                def counter(name, desc, value):
                    return make_counter_snapshot(name, desc, value, tags)

                store_stats = st["object_store"]
                disk = st.get("disk") or {}
                snaps = [
                    gauge("ray_tpu_node_cpu_percent",
                          "Node CPU utilization percent.",
                          st["cpu_percent"]),
                    gauge("ray_tpu_node_cpu_count",
                          "Logical CPUs on the node.",
                          st.get("cpu_count") or 0),
                    gauge("ray_tpu_node_load_avg_1m",
                          "1-minute load average.",
                          (st.get("load_avg") or [0])[0]),
                    gauge("ray_tpu_node_mem_used_bytes",
                          "Node memory in use.", st["mem_used_bytes"]),
                    gauge("ray_tpu_node_mem_total_bytes",
                          "Node memory total.", st["mem_total_bytes"]),
                    gauge("ray_tpu_node_disk_used_bytes",
                          "Session-disk bytes used.",
                          disk.get("used", 0)),
                    gauge("ray_tpu_node_disk_total_bytes",
                          "Session-disk bytes total.",
                          disk.get("total", 0)),
                    gauge("ray_tpu_node_workers",
                          "Worker processes on the node.",
                          st["num_workers"]),
                    gauge("ray_tpu_node_idle_workers",
                          "Idle (leasable) worker processes.",
                          st["num_idle_workers"]),
                    # scheduler (reference: metric_defs.cc scheduler_*)
                    gauge("ray_tpu_scheduler_active_leases",
                          "Worker leases currently granted on the node.",
                          len(self.leases)),
                    gauge("ray_tpu_scheduler_pending_lease_requests",
                          "Lease requests queued on the node.",
                          len(self._pending_leases)),
                    gauge("ray_tpu_scheduler_leases_granted_total",
                          "Cumulative leases granted (counter semantics).",
                          self._lease_counter),
                    gauge("ray_tpu_scheduler_spillbacks_total",
                          "Lease requests redirected to other nodes.",
                          getattr(self, "_spillback_count", 0)),
                    gauge("ray_tpu_pg_bundles_reserved",
                          "Placement-group bundles reserved on the node.",
                          len(self._pg_bundles)),
                    # object plane (reference: metric_defs.cc object_store_*
                    # + object_manager_*)
                    gauge("ray_tpu_object_store_used_bytes",
                          "Object store bytes in use.",
                          store_stats.get("used", 0)),
                    gauge("ray_tpu_object_store_capacity_bytes",
                          "Object store arena capacity.",
                          store_stats.get("capacity", 0)),
                    gauge("ray_tpu_object_store_num_objects",
                          "Sealed objects resident in the store.",
                          store_stats.get("num_objects", 0)),
                    gauge("ray_tpu_object_store_evictions_total",
                          "Cumulative LRU evictions.",
                          store_stats.get("num_evictions", 0)),
                    gauge("ray_tpu_object_store_created_total",
                          "Cumulative objects created.",
                          store_stats.get("num_created", 0)),
                    gauge("ray_tpu_object_spilled_total",
                          "Objects spilled to disk.",
                          getattr(self.store, "num_spills", 0)),
                    gauge("ray_tpu_object_restored_total",
                          "Spilled objects restored.",
                          getattr(self, "_restored_count", 0)),
                    counter("ray_tpu_object_chunks_served_total",
                            "Object chunks served to remote nodes.",
                            getattr(self, "_chunks_served", 0)),
                    counter("ray_tpu_object_chunks_fetched_total",
                            "Object chunks fetched from remote nodes.",
                            self.pulls.chunks_fetched),
                    gauge("ray_tpu_object_pulls_inflight",
                          "Cross-node object pulls in progress.",
                          len(self._pulls_inflight)),
                    # pull pipeline (reference: object_manager chunk/window
                    # stats + pull_manager admission counters)
                    gauge("ray_tpu_object_pull_window_occupancy",
                          "Chunk RPCs in flight across all transfers.",
                          self.pulls.window_occupancy),
                    gauge("ray_tpu_object_pull_inflight_bytes",
                          "Unsealed pull bytes admitted on the node.",
                          self.pulls.budget.inflight),
                    gauge("ray_tpu_object_pull_queued",
                          "Transfers waiting on the pull byte budget.",
                          self.pulls.budget.queued),
                    counter("ray_tpu_object_pull_queued_total",
                            "Transfers that ever queued on the budget.",
                            self.pulls.budget.queued_total),
                    counter("ray_tpu_object_pull_bytes_total",
                            "Bytes fetched from remote nodes.",
                            self.pulls.bytes_fetched),
                    counter("ray_tpu_object_pull_stripe_failovers_total",
                            "Chunk stripes failed over to another holder.",
                            self.pulls.stripe_failovers),
                    # device object plane (ISSUE 9): zero-copy puts,
                    # broadcast-tree shape + relay volume, spill tiers
                    counter("ray_tpu_store_zero_copy_puts",
                            "Typed array objects sealed without a "
                            "pickle pass.",
                            self._zero_copy_puts),
                    gauge("ray_tpu_bcast_tree_depth",
                          "Depth of this node's latest broadcast-tree "
                          "slot.",
                          self.pulls.bcast_last_depth),
                    counter("ray_tpu_bcast_relay_bytes",
                            "Bytes relayed to children from unsealed "
                            "in-flight views.",
                            self.pulls.bcast_relay_bytes),
                    counter("ray_tpu_bcast_reparents_total",
                            "Dead broadcast parents this node reported.",
                            self.pulls.bcast_reparents_client),
                    counter("ray_tpu_object_spill_remote_total",
                            "Objects demoted to the remote-holder spill "
                            "tier.",
                            getattr(self.store, "num_remote_demotions",
                                    0)),
                    gauge("ray_tpu_object_waits_pending",
                          "Local seal-wait futures outstanding.",
                          sum(len(v) for v in self._object_waits.values())),
                    # worker pool lifecycle (reference: metric_defs.cc
                    # worker_register/worker_process series)
                    gauge("ray_tpu_worker_processes_started_total",
                          "Cumulative worker processes spawned.",
                          getattr(self, "_workers_spawned", 0)),
                    gauge("ray_tpu_worker_env_evictions_total",
                          "Idle workers killed for runtime-env mismatch.",
                          getattr(self, "_env_evictions", 0)),
                    gauge("ray_tpu_worker_starting",
                          "Worker processes spawning (pre-registration).",
                          self._starting_workers),
                    # warm worker pool (ISSUE 10)
                    gauge("ray_tpu_worker_pool_warm",
                          "Pristine pre-warmed workers parked leasable.",
                          self._warm_idle_count()),
                    counter("ray_tpu_worker_pool_hits_total",
                            "Actor starts served from the warm pool.",
                            self._pool_hits),
                    counter("ray_tpu_worker_pool_misses_total",
                            "Actor starts that fell back to a cold fork.",
                            self._pool_misses),
                    counter("ray_tpu_worker_pool_demand_hits_total",
                            "Missed actor starts served by a demand-"
                            "paged pool worker (ISSUE 11).",
                            self._demand_hits),
                    counter("ray_tpu_worker_pool_reaped_total",
                            "Warm workers reaped on the idle TTL.",
                            self._pool_reaped),
                    # RPC fabric (reference: grpc_server_* / grpc_client_*)
                    gauge("ray_tpu_rpc_frames_in_total",
                          "Control-plane frames received by this process.",
                          _rpc_stats["frames_in"]),
                    gauge("ray_tpu_rpc_frames_out_total",
                          "Control-plane frames sent by this process.",
                          _rpc_stats["frames_out"]),
                    gauge("ray_tpu_rpc_bytes_in_total",
                          "Control-plane bytes received by this process.",
                          _rpc_stats["bytes_in"]),
                    gauge("ray_tpu_rpc_bytes_out_total",
                          "Control-plane bytes sent by this process.",
                          _rpc_stats["bytes_out"]),
                ]
                # object ownership ledger (ISSUE 15): store bytes by
                # spill tier + the watchdog's current suspect count
                tiers = self.store.tier_stats()
                for tier, nbytes in (
                        ("shm", tiers.get("shm_bytes",
                                          store_stats.get("used", 0))),
                        ("disk", tiers.get("disk_bytes", 0)),
                        ("remote", tiers.get("remote_bytes", 0))):
                    snaps.append(make_gauge_snapshot(
                        "ray_tpu_store_bytes",
                        "Object store bytes held, by spill tier.",
                        nbytes,
                        {"node_id": self.node_id, "tier": tier}))
                snaps.append(gauge(
                    "ray_tpu_object_leak_suspects",
                    "Objects the leak watchdog currently flags.",
                    len(self._leak_suspects)))
                snaps.append(gauge(
                    "ray_tpu_object_leak_repairs_total",
                    "Leaked store copies freed by the watchdog repair "
                    "hook.",
                    self._leak_repairs))
                # per-resource availability (reference: resources gauge
                # per resource name)
                for rname, total_amt in self.resources.total.to_dict() \
                        .items():
                    avail = self.resources.available.get(rname) or 0.0
                    snaps.append(make_gauge_snapshot(
                        "ray_tpu_resource_in_use",
                        "Resource units leased out, by resource name.",
                        float(total_amt) - float(avail),
                        {"node_id": self.node_id, "resource": str(rname)}))
                tpu = st.get("tpu") or {}
                if tpu:
                    snaps.append(gauge(
                        "ray_tpu_tpu_utilization",
                        "Fraction of the node's TPU chips leased.",
                        tpu.get("utilization", 0.0)))
                    if "duty_cycle_percent" in tpu:
                        snaps.append(gauge(
                            "ray_tpu_tpu_duty_cycle_percent",
                            "TPU duty cycle percent.",
                            tpu["duty_cycle_percent"]))
                await self.head.call("KvPut", {
                    "key": f"metrics::{self.node_id}::agent".encode(),
                    "value": _json.dumps(snaps).encode(),
                    "ns": "_metrics", "overwrite": True},
                    timeout=CONFIG.control_rpc_timeout_s)
            except Exception:
                pass
            await asyncio.sleep(period)

    async def _get_node_stats(self, conn: Connection, p) -> Dict:
        return getattr(self, "node_stats", {}) or \
            await asyncio.to_thread(self._sample_node_stats)

    async def _list_events(self, conn: Connection, p) -> List[Dict]:
        """This node's structured events (multi-node session dirs are per
        machine; the state API aggregates across agents)."""
        from ray_tpu._private.event import read_events

        p = p or {}
        return await asyncio.to_thread(
            read_events, self.session_dir,
            severity=p.get("severity"), label=p.get("label"),
            limit=int(p.get("limit", 1000)))

    async def _list_workers(self, conn: Connection, p) -> List[Dict]:
        """Live worker-table query (reference: the state API pairs GCS data
        with NodeManager::QueryAllWorkerStates, node_manager.h:217)."""
        out = []
        for w in self.workers.values():
            if w.proc is None:
                # still parked in the spawn admission queue: there is no
                # process (and no pid) to report yet — listing it raced
                # observers that treat every row as a live worker process
                continue
            out.append({
                "worker_id": w.worker_id,
                "node_id": self.node_id,
                "pid": w.proc.pid,
                "state": ("ACTOR" if w.is_actor
                          else "LEASED" if w.leased_to else "IDLE"),
                "actor_id": w.actor_id,
                "env_key": w.env_key,
                "alive": w.alive,
                "direct_addr": w.direct_addr,
            })
        return out

    async def _list_store_objects(self, conn: Connection, p) -> List[Dict]:
        """Per-node object-store contents (reference: list_objects in
        util/state/api.py aggregating core-worker object views)."""
        limit = int(p.get("limit", 1000)) if isinstance(p, dict) else 1000
        return [dict(row, node_id=self.node_id)
                for row in self.store.list_entries(limit)]

    # ------------------------------------ object introspection (ISSUE 15)
    def _introspect_targets(self) -> List[Dict]:
        """Direct addrs of every local process with a ref table: the
        registered drivers plus the live registered workers."""
        targets: List[Dict] = []
        seen = set()
        for info in list(self._driver_clients.values()):
            addr = info.get("direct_addr") or {}
            key = (addr.get("host"), addr.get("port"))
            if addr.get("port") and key not in seen:
                seen.add(key)
                targets.append(addr)
        for w in list(self.workers.values()):
            addr = w.direct_addr or {}
            key = (addr.get("host"), addr.get("port"))
            if (w.alive and w.registered.is_set() and addr.get("port")
                    and key not in seen):
                seen.add(key)
                targets.append(addr)
        return targets

    async def _call_local_process(self, addr: Dict, payload: Dict):
        client = await self.pool.get(addr["host"], addr["port"])
        return await client.call(
            "GetObjectRefs", payload,
            timeout=CONFIG.object_introspect_timeout_s)

    async def _gather_local_ref_dumps(self, limit: int) -> List[Dict]:
        targets = self._introspect_targets()

        async def one(addr: Dict) -> Dict:
            try:
                return await self._call_local_process(addr,
                                                      {"limit": limit})
            except Exception as e:
                return {"error": f"{type(e).__name__}: {e}",
                        "addr": {"host": addr.get("host"),
                                 "port": addr.get("port")}}

        return list(await asyncio.gather(*(one(a) for a in targets)))

    async def _get_object_refs(self, conn: Connection, p) -> Dict:
        """Node-wide object introspection: store tier usage + every local
        process's ref tables with provenance + the watchdog's current
        leak suspects. The head's ObjectSummary fans this out."""
        p = p or {}
        limit = int(p.get("limit", 10000))
        objects = []
        for row in self.store.list_entries(limit):
            info = self._object_owners.get(row["object_id"])
            row = dict(row, node_id=self.node_id)
            if info:
                row["owner"] = {"host": info["owner"].get("host"),
                                "port": info["owner"].get("port")}
                row["creator_task"] = info.get("task") or ""
                row["creator_callsite"] = info.get("callsite") or ""
            objects.append(row)
        return {
            "node_id": self.node_id,
            "store": self.store.stats(),
            "tiers": self.store.tier_stats(),
            "objects": objects,
            "processes": await self._gather_local_ref_dumps(limit),
            "leak_suspects": list(self._leak_suspects),
            "leak_scans": self._leak_scans,
            "leak_repairs": self._leak_repairs,
        }

    async def _leak_watchdog_loop(self) -> None:
        """Default-off leak scan (``object_leak_scan_interval_s`` > 0
        arms it at boot): every interval, interrogate each big sealed
        object's OWNER — an object whose owner reports zero local refs /
        borrowers / task pins (or no longer knows it) yet that remains
        unevicted past ``object_leak_grace_s`` is a leak suspect, as is a
        borrower entry whose owner no longer lists the borrow."""
        while not self._closing:
            interval = float(CONFIG.object_leak_scan_interval_s)
            await asyncio.sleep(interval if interval > 0 else 2.0)
            if interval <= 0:
                continue
            try:
                await self._scan_for_leaks()
            except Exception:
                logging.getLogger("ray_tpu").exception("leak scan failed")

    async def _scan_for_leaks(self) -> List[Dict]:
        min_bytes = int(CONFIG.object_leak_min_bytes)
        grace = float(CONFIG.object_leak_grace_s)
        now = time.time()
        self._leak_scans += 1
        all_entries = self.store.list_entries(100000)
        # remote-tier entries hold no local bytes but ARE still live and
        # restorable: keep their owner attribution, just don't scan them
        present = {row["object_id"] for row in all_entries}
        entries = {row["object_id"]: row for row in all_entries
                   if row.get("tier") != "remote"}
        # the ledger tracks only what the store still holds (any tier)
        for hex_id in [h for h in self._object_owners if h not in present]:
            self._object_owners.pop(hex_id, None)
        # -- big sealed objects, batched one owner round trip per owner
        by_owner: Dict[tuple, List[str]] = {}
        owner_addr: Dict[tuple, Dict] = {}
        for hex_id, row in entries.items():
            if row["size_bytes"] < min_bytes:
                continue
            info = self._object_owners.get(hex_id)
            if not info or not info.get("owner"):
                continue
            key = (info["owner"].get("host"), info["owner"].get("port"))
            owner_addr[key] = info["owner"]
            by_owner.setdefault(key, []).append(hex_id)
        candidates: Dict[str, Dict] = {}

        def add_candidate(key: str, row: Dict) -> None:
            candidates[key] = row

        for key, ids in by_owner.items():
            try:
                reply = await self._call_local_process(
                    owner_addr[key], {"ids": ids})
                refs = reply.get("refs", {})
            except Exception:
                # owner process gone: every big object it owned that the
                # store still holds is orphaned by definition
                for h in ids:
                    add_candidate(h, {
                        "object_id": h, "reason": "owner_unreachable",
                        "size_bytes": entries[h]["size_bytes"],
                        "tier": entries[h]["tier"],
                        "pinned": bool(entries[h].get("pinned")),
                        "callsite": "", "creator": ""})
                continue
            for h in ids:
                v = refs.get(h) or {}
                dropped = not v.get("owned") or v.get("state") == "freed"
                zero_refs = (v.get("local_refs", 0) <= 0
                             and v.get("borrowers", 0) <= 0
                             and v.get("task_pins", 0) <= 0)
                if not (dropped or zero_refs):
                    continue
                add_candidate(h, {
                    "object_id": h,
                    "reason": "owner_dropped" if dropped else "zero_refs",
                    "size_bytes": entries[h]["size_bytes"],
                    "tier": entries[h]["tier"],
                    "pinned": bool(entries[h].get("pinned")),
                    "callsite": v.get("callsite", ""),
                    "creator": v.get("creator", "")})
        # -- orphan borrowers: local borrow entries the owner forgot.
        # Batched like the sealed-object pass: ONE ref_info RPC per
        # distinct owner, not one per borrowed entry.
        borrow_rows: Dict[tuple, List[Tuple[Dict, int]]] = {}
        borrow_owner: Dict[tuple, Dict] = {}
        for dump in await self._gather_local_ref_dumps(10000):
            for row in dump.get("borrowed") or []:
                owner = row.get("owner") or {}
                if not owner.get("port"):
                    continue
                key = (owner.get("host"), owner.get("port"))
                borrow_owner[key] = owner
                borrow_rows.setdefault(key, []).append(
                    (row, dump.get("pid", 0)))
        for key, rows in borrow_rows.items():
            ids = sorted({row["object_id"] for row, _pid in rows})
            try:
                reply = await self._call_local_process(
                    borrow_owner[key], {"ids": ids})
                refs = reply.get("refs") or {}
            except Exception:
                refs = {}
            for row, pid in rows:
                v = refs.get(row["object_id"]) or {}
                if v.get("owned") and v.get("state") != "freed" \
                        and v.get("borrowers", 0) > 0:
                    continue
                add_candidate("borrow:" + row["object_id"], {
                    "object_id": row["object_id"],
                    "reason": "orphan_borrow",
                    "size_bytes": v.get("size_bytes", 0),
                    "tier": "", "pinned": False,
                    "callsite": v.get("callsite", ""),
                    "creator": v.get("creator", ""),
                    "borrower_pid": pid})
        # -- grace accounting: a candidate first seen on an EARLIER scan
        # and older than the grace graduates to suspect
        for stale in [k for k in self._leak_candidates
                      if k not in candidates]:
            self._leak_candidates.pop(stale, None)
        suspects: List[Dict] = []
        for key, row in candidates.items():
            first = self._leak_candidates.setdefault(key, now)
            if first < now and now - first >= grace:
                suspects.append(dict(row, age_s=round(now - first, 1)))
        prev = {s["object_id"] + s["reason"] for s in self._leak_suspects}
        if CONFIG.object_leak_repair_enabled:
            self._repair_leaks(suspects, now)
        self._leak_suspects = suspects
        rec = _events.REC
        if rec.enabled:
            for s in suspects:
                if s["object_id"] + s["reason"] in prev:
                    continue  # already on the timeline
                trace, span = rec.new_trace()
                rec.record("leak_suspect", "object", now, 0.0, trace,
                           span, 0,
                           {"obj": s["object_id"][:16],
                            "bytes": s["size_bytes"],
                            "reason": s["reason"],
                            "callsite": s.get("callsite", "")[:64]})
        return suspects

    def _repair_leaks(self, suspects: List[Dict], now: float) -> None:
        """Repair hook (ISSUE 17): a graduated ``owner_unreachable`` /
        ``zero_refs`` suspect is garbage by definition — its owner can
        never serve another pull (process gone) or holds no reference
        that could reach the bytes again. Free the local store copy
        instead of merely reporting it; the verdict already survived the
        scan grace, so a transient owner blip cannot trip this.
        ``orphan_borrow`` stays report-only: those bytes live in a remote
        process's memory store, not this node's object store."""
        rec = _events.REC
        for s in suspects:
            if s.get("reason") not in ("owner_unreachable", "zero_refs"):
                continue
            hex_id = s.get("object_id") or ""
            if not hex_id or not (self.store.contains(hex_id)
                                  or self.store.is_spilled(hex_id)):
                continue
            self.store.delete(hex_id)
            self._object_owners.pop(hex_id, None)
            self._leak_repairs += 1
            s["repaired"] = True
            if rec.enabled:
                trace, span = rec.new_trace()
                rec.record("leak_repair", "object", now, 0.0, trace, span,
                           0, {"obj": hex_id[:16],
                               "bytes": s.get("size_bytes", 0),
                               "reason": s.get("reason", "")})

    async def _set_resource(self, conn: Connection, p: Dict) -> Dict:
        """Dynamically re-declare a custom resource's total (reference:
        experimental/dynamic_resources.py set_resource). The available
        amount shifts by the same delta, so in-flight leases keep their
        accounting."""
        name = p["resource"]
        new_total = float(p["capacity"])
        delta = new_total - self.resources.total.get(name)
        shift = ResourceSet({name: abs(delta)})
        if delta >= 0:
            self.resources.total.add(shift)
            self.resources.available.add(shift)
        else:
            self.resources.total.subtract(shift, allow_negative=True)
            self.resources.available.subtract(shift, allow_negative=True)
        self._resources_dirty = True
        await self._drain_pending_leases()
        return {"total": self.resources.total.get(name)}


class _ForeignProc:
    """Stand-in Popen for worker processes the agent didn't spawn."""

    def __init__(self, pid: int):
        self.pid = pid

    def poll(self):
        if not self.pid:
            return None
        try:
            os.kill(self.pid, 0)
            return None
        except OSError:
            return 1

    def terminate(self):
        if self.pid:
            try:
                os.kill(self.pid, 15)
            except OSError:
                pass

    def kill(self):
        if self.pid:
            try:
                os.kill(self.pid, 9)
            except OSError:
                pass


def main() -> None:
    import argparse
    import json

    from ray_tpu._private import sanitizer as _sanitizer

    _sanitizer.maybe_install()
    parser = argparse.ArgumentParser()
    parser.add_argument("--node-id", required=True)
    parser.add_argument("--session-dir", required=True)
    parser.add_argument("--store-dir", required=True)
    parser.add_argument("--head-host", required=True)
    parser.add_argument("--head-port", type=int, required=True)
    parser.add_argument("--resources", required=True)  # json
    parser.add_argument("--labels", default="{}")
    parser.add_argument("--object-store-memory", type=int, default=0)
    parser.add_argument("--ready-file", default="")
    args = parser.parse_args()

    async def run():
        import signal

        from ray_tpu._private import proc_profile

        lifecycle.register_self("agent", args.session_dir, args.node_id)
        # chaos rules target processes by node id (workers inherit it via
        # RAY_TPU_NODE_ID; the agent gets its id as an argv flag)
        set_fault_self_id(args.node_id)
        prof = proc_profile.maybe_start()
        agent = NodeAgent(
            node_id=args.node_id,
            session_dir=args.session_dir,
            store_dir=args.store_dir,
            head_host=args.head_host,
            head_port=args.head_port,
            resources=json.loads(args.resources),
            labels=json.loads(args.labels),
            object_store_memory=args.object_store_memory or None,
        )
        # a crashed/SIGKILL'd spawner (driver or CLI runner) must strand
        # nothing: SIGTERM lands here, the handler below tears workers down
        lifecycle.fate_share_with_parent()
        await agent.start()
        if args.ready_file:
            with open(args.ready_file, "w") as f:
                f.write(json.dumps({"unix_path": agent.unix_path,
                                    "tcp_port": agent.tcp_port}))
        stop = asyncio.Event()
        loop = asyncio.get_running_loop()
        try:
            for sig in (signal.SIGTERM, signal.SIGINT):
                loop.add_signal_handler(sig, stop.set)
        except (NotImplementedError, RuntimeError):
            pass
        await stop.wait()
        from ray_tpu._private import events as _ev

        _ev.REC.dump_local("sigterm")
        # close RPC clients cleanly (cancel + await read loops) BEFORE the
        # loop dies: a close() here would strand cancelled tasks and spray
        # "Task was destroyed but it is pending!" into the agent log the
        # log monitor streams to the driver
        try:
            await asyncio.wait_for(agent.aclose_clients(), timeout=2)
        except Exception:
            pass
        # guaranteed teardown: the agent owns its node's process tree
        await asyncio.to_thread(agent.teardown_processes)
        proc_profile.dump(prof, "agent")
        lifecycle.unregister_process(args.session_dir, os.getpid())

    asyncio.run(run())


if __name__ == "__main__":
    main()
