"""Serve tests (reference analog: python/ray/serve/tests/ — in-process
controller + proxy per SURVEY §4 tier 4)."""

import json
import time
import urllib.request

import pytest

import ray_tpu
from ray_tpu import serve


@pytest.fixture(scope="module")
def serve_cluster():
    if not ray_tpu.is_initialized():
        ray_tpu.init(num_cpus=8)
    serve.start(http_options={"port": 0})
    yield
    serve.shutdown()
    ray_tpu.shutdown()


def _http_get(path, port, timeout=30):
    with urllib.request.urlopen(
            f"http://127.0.0.1:{port}{path}", timeout=timeout) as r:
        return r.status, r.read()


def _http_post(path, port, payload, timeout=30):
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}{path}",
        data=json.dumps(payload).encode(),
        headers={"Content-Type": "application/json"})
    with urllib.request.urlopen(req, timeout=timeout) as r:
        return r.status, r.read()


def test_deploy_and_handle_call(serve_cluster):
    @serve.deployment
    class Doubler:
        def __call__(self, x):
            return x * 2

        def triple(self, x):
            return x * 3

    handle = serve.run(Doubler.bind(), name="doubler",
                       route_prefix="/doubler")
    assert handle.remote(21).result(timeout_s=30) == 42
    assert handle.triple.remote(5).result(timeout_s=30) == 15
    st = serve.status("doubler")
    assert st["status"] == "RUNNING"
    serve.delete("doubler")
    assert serve.status("doubler")["status"] == "NOT_FOUND"


def test_function_deployment_http(serve_cluster):
    @serve.deployment
    def echo(request):
        data = request.json()
        return {"echo": data["msg"], "path": request.path}

    serve.run(echo.bind(), name="echo", route_prefix="/echo")
    port = serve.get_http_port()
    status, body = _http_post("/echo/sub?x=1", port, {"msg": "hi"})
    assert status == 200
    out = json.loads(body)
    assert out == {"echo": "hi", "path": "/sub"}
    # healthz + routes endpoints
    status, body = _http_get("/-/healthz", port)
    assert status == 200 and body == b"success"
    status, body = _http_get("/-/routes", port)
    assert json.loads(body).get("/echo") == "echo"
    serve.delete("echo")


def test_model_composition(serve_cluster):
    @serve.deployment
    class Adder:
        def __init__(self, increment):
            self.increment = increment

        def __call__(self, x):
            return x + self.increment

    @serve.deployment
    class Combiner:
        def __init__(self, a, b):
            self.a = a
            self.b = b

        async def __call__(self, x):
            ra, rb = self.a.remote(x), self.b.remote(x)
            return (await ra) + (await rb)

    app = Combiner.bind(Adder.options(name="Add1").bind(1),
                        Adder.options(name="Add2").bind(2))
    handle = serve.run(app, name="compose", route_prefix="/compose")
    assert handle.remote(10).result(timeout_s=60) == 23  # (10+1)+(10+2)
    serve.delete("compose")


def test_multiple_replicas_and_scaling(serve_cluster):
    @serve.deployment(num_replicas=2, max_ongoing_requests=4)
    class Who:
        def __init__(self):
            import os

            self.pid = os.getpid()

        def __call__(self, _):
            return self.pid

    handle = serve.run(Who.bind(), name="who", route_prefix="/who")
    pids = {handle.remote(None).result(timeout_s=30) for _ in range(20)}
    assert len(pids) == 2  # both replicas served traffic
    serve.delete("who")


def test_replica_death_recovery(serve_cluster):
    @serve.deployment(num_replicas=1, health_check_period_s=0.2)
    class Fragile:
        def __call__(self, cmd):
            if cmd == "die":
                import os

                os._exit(1)
            return "alive"

    handle = serve.run(Fragile.bind(), name="fragile",
                       route_prefix="/fragile")
    assert handle.remote("ping").result(timeout_s=30) == "alive"
    try:
        handle.remote("die").result(timeout_s=10)
    except Exception:
        pass
    # the controller health-checks, replaces the replica, traffic resumes
    deadline = time.monotonic() + 60
    ok = False
    while time.monotonic() < deadline:
        try:
            if handle.remote("ping").result(timeout_s=10) == "alive":
                ok = True
                break
        except Exception:
            time.sleep(0.3)
    assert ok, "replica was not replaced after death"
    serve.delete("fragile")


def test_user_config_reconfigure(serve_cluster):
    @serve.deployment(user_config={"threshold": 1})
    class Thresh:
        def __init__(self):
            self.threshold = None

        def reconfigure(self, cfg):
            self.threshold = cfg["threshold"]

        def __call__(self, _):
            return self.threshold

    serve.run(Thresh.bind(), name="thresh", route_prefix="/thresh")
    h = serve.get_app_handle("thresh")
    assert h.remote(None).result(timeout_s=30) == 1
    serve.delete("thresh")


def test_serve_batch(serve_cluster):
    @serve.deployment(max_ongoing_requests=32)
    class BatchModel:
        def __init__(self):
            self.batch_sizes = []

        @serve.batch(max_batch_size=8, batch_wait_timeout_s=0.1)
        async def predict(self, items):
            self.batch_sizes.append(len(items))
            return [i * 10 for i in items]

        async def __call__(self, x):
            return await self.predict(x)

        def sizes(self):
            return self.batch_sizes

    handle = serve.run(BatchModel.bind(), name="batch",
                       route_prefix="/batch")
    responses = [handle.remote(i) for i in range(16)]
    values = sorted(r.result(timeout_s=30) for r in responses)
    assert values == [i * 10 for i in range(16)]
    sizes = serve.get_deployment_handle(
        "BatchModel", "batch").sizes.remote().result(timeout_s=30)
    assert max(sizes) > 1, f"no batching happened: {sizes}"
    serve.delete("batch")


def test_multiplexed_models(serve_cluster):
    @serve.deployment
    class Multi:
        def __init__(self):
            self.loads = []

        @serve.multiplexed(max_num_models_per_replica=2)
        async def get_model(self, model_id):
            self.loads.append(model_id)
            return {"id": model_id, "scale": int(model_id[-1])}

        async def __call__(self, x):
            model_id = serve.get_multiplexed_model_id()
            model = await self.get_model(model_id)
            return x * model["scale"]

    handle = serve.run(Multi.bind(), name="multi", route_prefix="/multi")
    h2 = handle.options(multiplexed_model_id="m2")
    h3 = handle.options(multiplexed_model_id="m3")
    assert h2.remote(10).result(timeout_s=30) == 20
    assert h3.remote(10).result(timeout_s=30) == 30
    assert h2.remote(7).result(timeout_s=30) == 14  # cached, no reload
    serve.delete("multi")


def test_autoscaling_up(serve_cluster):
    @serve.deployment(
        max_ongoing_requests=2,
        autoscaling_config={"min_replicas": 1, "max_replicas": 3,
                            "target_ongoing_requests": 1.0,
                            "upscale_delay_s": 0.5,
                            "downscale_delay_s": 60.0},
        health_check_period_s=0.2)
    class Slow:
        def __call__(self, _):
            time.sleep(0.4)
            return "done"

    handle = serve.run(Slow.bind(), name="auto", route_prefix="/auto")
    # flood with concurrent requests to push ongoing above target
    responses = [handle.remote(None) for _ in range(24)]
    deadline = time.monotonic() + 90  # generous: 1-CPU box under suite load
    scaled = False
    while time.monotonic() < deadline:
        st = serve.status("auto")
        if st["deployments"]["Slow"]["replicas"] >= 2:
            scaled = True
            break
        time.sleep(0.3)
    for r in responses:
        r.result(timeout_s=60)
    assert scaled, f"never scaled up: {serve.status('auto')}"
    serve.delete("auto")
