"""WAL crash-consistency units (ISSUE 8): torn-tail truncation, bad-CRC
skip-and-stop, compaction equivalence (replay(snapshot + suffix) ==
replay(full log)), and a randomized kill-offset fuzz (slow).

These run against the raw log and against HeadServer's replay state
machine — the two layers whose agreement IS the durability contract.
"""

import asyncio
import os
import random
import shutil
import struct

import pytest

from ray_tpu._private.wal import MAGIC, WriteAheadLog, replay, scan


def _run(coro):
    return asyncio.run(coro)


async def _write_log(path, ops, fsync_interval_ms=0.0):
    w = WriteAheadLog(path, fsync_interval_ms=fsync_interval_ms)
    w.start()
    for op, data in ops:
        await w.append(op, data)
    await w.close()
    return w


def _ops(n, start=0):
    return [("kv_put", {"ns": "default", "key": b"k%d" % i,
                        "value": b"v%d" % i})
            for i in range(start, start + n)]


# ---------------------------------------------------------------------------
# round trip + ordering
# ---------------------------------------------------------------------------
def test_append_replay_round_trip(tmp_path):
    path = str(tmp_path / "a.wal")
    _run(_write_log(path, _ops(20)))
    recs = replay(path)
    assert [r[0] for r in recs] == list(range(1, 21))  # seq is dense
    assert recs[0][1] == "kv_put"
    assert recs[19][2]["key"] == b"k19"
    # snapshot_seq filtering: the suffix view compaction relies on
    assert [r[0] for r in replay(path, snapshot_seq=15)] == [16, 17, 18, 19, 20]


def test_group_commit_resolves_concurrent_appends(tmp_path):
    path = str(tmp_path / "g.wal")

    async def main():
        w = WriteAheadLog(path, fsync_interval_ms=5.0)
        w.start()
        seqs = await asyncio.gather(
            *[w.append("op", {"i": i}) for i in range(64)])
        assert sorted(seqs) == list(range(1, 65))
        assert w.fsyncs < 64  # batched: one fsync covers the burst
        await w.close()

    _run(main())
    assert len(replay(path)) == 64


def test_reopen_continues_sequence(tmp_path):
    path = str(tmp_path / "r.wal")
    _run(_write_log(path, _ops(5)))
    w = WriteAheadLog(path)
    assert w.seq == 5

    async def more():
        w.start()
        assert await w.append("op", {}) == 6
        await w.close()

    _run(more())
    assert [r[0] for r in replay(path)] == [1, 2, 3, 4, 5, 6]


# ---------------------------------------------------------------------------
# torn tail + bad CRC
# ---------------------------------------------------------------------------
def test_torn_tail_truncated_and_appendable(tmp_path):
    path = str(tmp_path / "t.wal")
    _run(_write_log(path, _ops(10)))
    size = os.path.getsize(path)
    with open(path, "r+b") as f:
        f.truncate(size - 5)  # kill -9 mid-record
    recs = replay(path)  # repairs: truncates at the last intact record
    assert [r[0] for r in recs] == list(range(1, 10))
    # the repaired log accepts appends and replays cleanly
    _run(_write_log(path, [("late", {})]))
    recs2 = replay(path)
    assert [r[0] for r in recs2] == list(range(1, 11))
    assert recs2[-1][1] == "late"


def test_bad_crc_record_skip_and_stop(tmp_path):
    """A flipped bit mid-log: replay stops AT the corrupt record —
    records after it are unreachable (boundaries are untrusted) and the
    file is truncated there, never a crash."""
    path = str(tmp_path / "c.wal")
    _run(_write_log(path, _ops(10)))
    # corrupt record #4's payload (walk the framing to find it)
    with open(path, "rb") as f:
        data = bytearray(f.read())
    off = len(MAGIC)
    for _ in range(3):
        length, _crc = struct.unpack_from("<II", data, off)
        off += 8 + length
    length, _crc = struct.unpack_from("<II", data, off)
    data[off + 8 + length // 2] ^= 0xFF
    with open(path, "wb") as f:
        f.write(data)
    recs = replay(path)
    assert [r[0] for r in recs] == [1, 2, 3]
    assert os.path.getsize(path) < len(data)  # physically truncated


def test_garbage_preamble_resets_log(tmp_path):
    path = str(tmp_path / "junk.wal")
    with open(path, "wb") as f:
        f.write(b"this is not a wal file at all")
    assert replay(path) == []
    # repaired to a clean empty log that accepts appends
    _run(_write_log(path, _ops(2)))
    assert len(replay(path)) == 2


def test_failed_write_rolls_back_torn_record(tmp_path):
    """A commit that dies mid-write (transient ENOSPC/EIO) must not
    leave a torn record mid-file: recovery's scan would stop THERE and
    silently discard every LATER acked batch. The failed batch's acks
    error, the file rolls back to the last fsynced offset, and
    subsequent appends stay durable."""
    path = str(tmp_path / "fail.wal")

    async def main():
        w = WriteAheadLog(path, fsync_interval_ms=0.0)
        w.start()
        await w.append("ok", {"i": 1})
        good_size = w.size_bytes

        real = w._write_and_sync

        def torn_write(buf):
            # half the bytes land, then the device errors
            w._f.write(buf[:len(buf) // 2])
            w._f.flush()
            raise OSError(28, "No space left on device")

        w._write_and_sync = torn_write
        with pytest.raises(RuntimeError):
            await w.append("doomed", {"i": 2})
        w._write_and_sync = real
        assert os.path.getsize(path) == good_size  # torn bytes gone
        # the log still accepts appends and they survive replay
        await w.append("after", {"i": 3})
        await w.close()

    _run(main())
    recs = replay(path)
    assert [(r[1], r[2]["i"]) for r in recs] == [("ok", 1), ("after", 3)]
    assert [r[0] for r in recs] == [1, 3]  # seq 2 was never acked


# ---------------------------------------------------------------------------
# compaction
# ---------------------------------------------------------------------------
def _reduce(records, kv=None):
    """Reference reducer: the kv materialization of a record stream,
    optionally applied on top of an existing (snapshot) state."""
    kv = dict(kv or {})
    for _seq, op, data in records:
        if op == "kv_put":
            kv[data["key"]] = data["value"]
        elif op == "kv_del":
            kv.pop(data["key"], None)
    return kv


def test_compaction_equivalence_replay_snapshot_plus_suffix(tmp_path):
    """replay(snapshot + rotated log) == replay(full log): rotation drops
    ONLY records the snapshot covers, keeps flushed-after-snapshot
    records AND pending ones."""
    path = str(tmp_path / "comp.wal")
    full = str(tmp_path / "full.wal")

    async def main():
        w = WriteAheadLog(path, fsync_interval_ms=0.0)
        w.start()
        ops = _ops(30) + [("kv_del", {"key": b"k3"}),
                          ("kv_del", {"key": b"k7"})]
        for op, data in ops[:20]:
            await w.append(op, data)
        snapshot_seq = w.seq  # snapshot "saved" here covers seq <= 20
        snapshot_kv = _reduce(scan(path)[0])
        for op, data in ops[20:]:
            await w.append(op, data)
        shutil.copy(path, full)  # the full-log counterfactual
        await w.rotate(snapshot_seq)
        # post-rotate appends land in the fresh file
        await w.append("kv_put", {"ns": "default", "key": b"post",
                                  "value": b"rotate"})
        await w.close()
        return snapshot_seq, snapshot_kv

    snapshot_seq, snapshot_kv = _run(main())
    suffix = replay(path)
    assert all(seq > snapshot_seq for seq, _op, _d in suffix)
    combined = _reduce(suffix, kv=snapshot_kv)
    full_state = _reduce(replay(full))
    full_state[b"post"] = b"rotate"
    assert combined == full_state


def test_headserver_snapshot_plus_wal_equals_full_replay(tmp_path):
    """Same equivalence one layer up: HeadServer's _apply_snapshot +
    _apply_wal_op suffix must land in the same state as replaying every
    op from scratch."""
    from ray_tpu._private.gcs import HeadServer

    def fresh():
        hs = HeadServer(str(tmp_path), 0, persist_path=None)
        return hs

    ops = []
    for i in range(6):
        ops.append(("actor_create", {
            "actor_id": f"a{i}", "spec_wire": {"class_name": "C"},
            "name": f"n{i}", "namespace": "default", "max_restarts": 0,
            "state": "PENDING_CREATION", "addr": None, "node_id": None,
            "num_restarts": 0, "owner_job": "j", "death_cause": "",
            "pid": 0}))
    ops.append(("actor_update", {"actor_id": "a1", "state": "ALIVE",
                                 "addr": {"host": "h", "port": 1},
                                 "pid": 42, "node_id": "nodeA"}))
    ops.append(("actor_update", {"actor_id": "a2", "state": "DEAD",
                                 "death_cause": "boom", "addr": None,
                                 "drop_name": True}))
    ops.append(("kv_put", {"ns": "default", "key": b"x", "value": b"1",
                           "overwrite": True}))
    ops.append(("kv_del", {"ns": "default", "key": b"x"}))
    ops.append(("kv_put", {"ns": "s", "key": b"y", "value": b"2",
                           "overwrite": True}))
    ops.append(("job", {"key": "j", "job": {"job_id": "j",
                                            "state": "RUNNING"}}))
    ops.append(("node_register", {
        "node_id": "nodeA", "incarnation": 7,
        "addr": {"host": "h", "port": 2},
        "resources": {"total": {"CPU": 4}, "available": {"CPU": 4},
                      "labels": {}}, "alive": True}))
    ops.append(("node_dead", {"node_id": "nodeA", "incarnation": 7,
                              "reason": "test"}))
    ops.append(("pg", {"pg": {"pg_id": "p1", "state": "CREATED",
                              "bundles": [{"CPU": 1}], "strategy": "PACK",
                              "placement": ["nodeA"], "name": ""}}))
    ops.append(("pg_remove", {"pg_id": "p1"}))

    full = fresh()
    for op, data in ops:
        full._apply_wal_op(op, data)

    cut = 9
    mid = fresh()
    for op, data in ops[:cut]:
        mid._apply_wal_op(op, data)
    snapshot = mid._snapshot()

    resumed = fresh()
    resumed._apply_snapshot(snapshot)
    for op, data in ops[cut:]:
        resumed._apply_wal_op(op, data)

    def state_of(hs):
        return {
            "kv": hs.kv,
            "jobs": hs.jobs,
            "named": dict(hs.named_actors),
            "actors": {a.actor_id: (a.state, a.addr, a.node_id,
                                    a.num_restarts, a.death_cause, a.pid)
                       for a in hs.actors.values()},
            "nodes": {n.node_id: (n.incarnation, n.alive)
                      for n in hs.nodes.values() if n.alive},
            "fenced": dict(hs.fenced_incarnations),
            "pgs": hs.placement_groups,
        }

    assert state_of(resumed) == state_of(full)


# ---------------------------------------------------------------------------
# randomized kill-offset fuzz
# ---------------------------------------------------------------------------
@pytest.mark.slow
def test_fuzz_random_kill_offsets(tmp_path):
    """Truncate the log at EVERY kind of offset a kill -9 could leave
    behind: replay must never raise and must always yield a seq-dense
    prefix of what was written."""
    path = str(tmp_path / "fuzz.wal")
    _run(_write_log(path, [("op", {"i": i, "pad": os.urandom(i % 97)})
                           for i in range(120)]))
    pristine = str(tmp_path / "pristine.wal")
    shutil.copy(path, pristine)
    size = os.path.getsize(pristine)
    rng = random.Random(1234)
    offsets = {rng.randrange(0, size) for _ in range(60)}
    offsets.update({0, 1, len(MAGIC), size - 1, size})
    for cut in sorted(offsets):
        shutil.copy(pristine, path)
        with open(path, "r+b") as f:
            f.truncate(cut)
        recs = replay(path)  # must not raise
        seqs = [r[0] for r in recs]
        assert seqs == list(range(1, len(seqs) + 1)), \
            f"non-prefix replay at cut={cut}"
        # and the repaired file keeps working
        _run(_write_log(path, [("again", {})]))
        assert replay(path)[-1][1] == "again"


@pytest.mark.slow
def test_fuzz_random_corruption(tmp_path):
    """Flip one byte anywhere: replay yields an intact prefix (checksums
    catch the flip) and never raises."""
    path = str(tmp_path / "flip.wal")
    _run(_write_log(path, [("op", {"i": i}) for i in range(80)]))
    pristine = str(tmp_path / "pristine2.wal")
    shutil.copy(path, pristine)
    size = os.path.getsize(pristine)
    rng = random.Random(99)
    for _ in range(40):
        shutil.copy(pristine, path)
        pos = rng.randrange(len(MAGIC), size)
        with open(path, "r+b") as f:
            f.seek(pos)
            byte = f.read(1)
            f.seek(pos)
            f.write(bytes([byte[0] ^ 0xFF]))
        recs = replay(path)
        seqs = [r[0] for r in recs]
        assert seqs == list(range(1, len(seqs) + 1))
