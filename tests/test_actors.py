"""Actor tests (reference parity: python/ray/tests/test_actor*.py)."""

import time

import pytest

import ray_tpu
from ray_tpu.exceptions import RayActorError


@ray_tpu.remote
class Counter:
    def __init__(self, start=0):
        self.n = start

    def incr(self, k=1):
        self.n += k
        return self.n

    def get(self):
        return self.n

    def fail(self):
        raise RuntimeError("actor method failed")

    def die(self):
        import os

        os._exit(1)


class TestActors:
    def test_create_and_call(self, ray_start_regular):
        c = Counter.remote(5)
        assert ray_tpu.get(c.incr.remote(), timeout=60) == 6

    def test_ordering(self, ray_start_regular):
        c = Counter.remote()
        refs = [c.incr.remote() for _ in range(20)]
        assert ray_tpu.get(refs, timeout=60) == list(range(1, 21))

    def test_state_persists(self, ray_start_regular):
        c = Counter.remote()
        ray_tpu.get(c.incr.remote(10))
        ray_tpu.get(c.incr.remote(5))
        assert ray_tpu.get(c.get.remote()) == 15

    def test_method_error(self, ray_start_regular):
        c = Counter.remote()
        with pytest.raises(RuntimeError):
            ray_tpu.get(c.fail.remote(), timeout=60)
        # actor still alive after method error
        assert ray_tpu.get(c.incr.remote(), timeout=30) == 1

    def test_handle_passing(self, ray_start_regular):
        c = Counter.remote()

        @ray_tpu.remote
        def bump(handle):
            return ray_tpu.get(handle.incr.remote())

        assert ray_tpu.get(bump.remote(c), timeout=60) == 1
        assert ray_tpu.get(c.get.remote()) == 1

    def test_named_actor(self, ray_start_regular):
        Counter.options(name="test_named", namespace="ns1").remote(100)
        h = ray_tpu.get_actor("test_named", namespace="ns1")
        assert ray_tpu.get(h.get.remote(), timeout=60) == 100
        with pytest.raises(ValueError):
            ray_tpu.get_actor("no_such_actor", namespace="ns1")

    def test_get_if_exists(self, ray_start_regular):
        a = Counter.options(name="gie", get_if_exists=True).remote(1)
        ray_tpu.get(a.incr.remote(), timeout=60)
        b = Counter.options(name="gie", get_if_exists=True).remote(1)
        # b is the same actor, not a new one
        assert ray_tpu.get(b.get.remote(), timeout=60) == 2

    def test_kill(self, ray_start_regular):
        c = Counter.options(name="to_kill").remote()
        ray_tpu.get(c.incr.remote(), timeout=60)
        ray_tpu.kill(c)
        time.sleep(0.3)
        with pytest.raises(RayActorError):
            ray_tpu.get(c.incr.remote(), timeout=10)

    def test_actor_death_detected(self, ray_start_regular):
        c = Counter.remote()
        ray_tpu.get(c.incr.remote(), timeout=60)
        c.die.remote()
        time.sleep(1.0)
        with pytest.raises(RayActorError):
            ray_tpu.get(c.incr.remote(), timeout=15)

    def test_max_restarts(self, ray_start_regular):
        c = Counter.options(max_restarts=1).remote()
        assert ray_tpu.get(c.incr.remote(), timeout=60) == 1
        c.die.remote()
        time.sleep(0.5)
        # restarted: state reset, calls flow again
        deadline = time.time() + 60
        while True:
            try:
                assert ray_tpu.get(c.incr.remote(), timeout=30) == 1
                break
            except RayActorError:
                if time.time() > deadline:
                    raise
                time.sleep(0.5)

    def test_async_actor(self, ray_start_regular):
        @ray_tpu.remote
        class AsyncActor:
            async def work(self, t):
                import asyncio

                await asyncio.sleep(t)
                return t

        a = AsyncActor.options(max_concurrency=4).remote()
        start = time.time()
        refs = [a.work.remote(0.4) for _ in range(4)]
        assert ray_tpu.get(refs, timeout=60) == [0.4] * 4
        # concurrent: took ~0.4s, not 1.6s (allow generous slack for 1-core CI)
        assert time.time() - start < 5.0

    def test_actor_pipelining(self, ray_start_regular):
        c = Counter.remote()
        # fire many without waiting; ordering + no loss
        refs = [c.incr.remote() for _ in range(50)]
        assert ray_tpu.get(refs[-1], timeout=60) == 50
