"""Object serialization.

Parity with the reference's serialization context (reference:
``python/ray/_private/serialization.py:110``): cloudpickle for arbitrary
Python, pickle protocol 5 out-of-band buffers for zero-copy of large arrays,
and custom reducers so ``ObjectRef`` / actor handles survive a trip through
task arguments with correct ownership bookkeeping.

TPU-first deviation: ``jax.Array`` values are serialized by pulling them to
host as numpy (device buffers cannot cross processes); on the read side the
numpy view aliases the shared-memory segment so ``jax.device_put`` can stream
straight from shm to HBM without an extra host copy.
"""

from __future__ import annotations

import io
import pickle
from typing import Any, Callable, List, Optional, Tuple

import cloudpickle

# Wire format of a sealed object:
#   [8-byte header][meta][payload buffers]
#   header = <u32 meta_len><u32 num_buffers>
#   meta   = pickled (protocol 5) bytes with out-of-band buffer placeholders
#   then for each buffer: <u64 length><raw bytes, 64-byte aligned>
#
# Typed zero-copy array objects (the device object plane, ISSUE 9) reuse
# the same 8-byte header with num_buffers == ZC_SENTINEL: meta is then a
# fixed struct descriptor (dtype tag, order, shape — never pickle) and
# exactly one raw buffer follows, written straight from the array's
# memory into the store view and read back as a numpy view aliasing the
# store mmap. No pickle pass in either direction.
import struct

_ALIGN = 64

# num_buffers value that can never occur on the pickle path (buffers are
# appended one at a time; 2**32-1 of them is unreachable).
ZC_SENTINEL = 0xFFFFFFFF
_ZC_VERSION = 1
# descriptor prefix: version, order flag ('C'/'F'), ndim, dtype-tag len,
# payload nbytes; then tag bytes, then ndim u64 dims
_ZC_PREFIX = "<BBBBQ"


def _align(n: int) -> int:
    return (n + _ALIGN - 1) & ~(_ALIGN - 1)


class SerializedObject:
    __slots__ = ("meta", "buffers")

    def __init__(self, meta: bytes, buffers: List[pickle.PickleBuffer]):
        self.meta = meta
        self.buffers = buffers

    def total_size(self) -> int:
        size = 8 + _align(len(self.meta))
        for b in self.buffers:
            size += 8 + _align(len(b.raw()))
        return size

    def write_into(self, view: memoryview) -> int:
        """Write the wire format into a writable memoryview; returns bytes used."""
        struct.pack_into("<II", view, 0, len(self.meta), len(self.buffers))
        off = 8
        view[off : off + len(self.meta)] = self.meta
        off += _align(len(self.meta))
        for b in self.buffers:
            raw = b.raw()
            struct.pack_into("<Q", view, off, len(raw))
            off += 8
            view[off : off + len(raw)] = raw
            off += _align(len(raw))
        return off

    def to_bytes(self) -> bytes:
        buf = bytearray(self.total_size())
        used = self.write_into(memoryview(buf))
        return bytes(buf[:used])


class ZeroCopyArray:
    """Serialized form of one contiguous ndarray: header + raw buffer.

    Duck-compatible with SerializedObject (total_size / write_into /
    to_bytes) so every put path — put(), task returns, inline values —
    takes the fast path without call-site changes. ``write_into`` is a
    single memcpy from the array's memory into the store view; there is
    no pickle pass and no intermediate bytes object.
    """

    __slots__ = ("descriptor", "raw", "nbytes")

    def __init__(self, descriptor: bytes, raw, nbytes: int):
        self.descriptor = descriptor
        self.raw = raw  # 1-D uint8 ndarray view of the source array
        self.nbytes = nbytes

    def total_size(self) -> int:
        return 8 + _align(len(self.descriptor)) + _align(self.nbytes)

    def write_into(self, view: memoryview) -> int:
        struct.pack_into("<II", view, 0, len(self.descriptor), ZC_SENTINEL)
        off = 8
        view[off : off + len(self.descriptor)] = self.descriptor
        off += _align(len(self.descriptor))
        view[off : off + self.nbytes] = self.raw
        return off + self.nbytes

    def to_bytes(self) -> bytes:
        buf = bytearray(self.total_size())
        used = self.write_into(memoryview(buf))
        return bytes(buf[:used])


def _dtype_tag(dtype) -> Optional[str]:
    """Stable round-trippable tag for a dtype. ``dtype.str`` for the
    standard kinds; extension dtypes (ml_dtypes bfloat16 & friends
    report an opaque '<V2') fall back to ``dtype.name``, which
    ``np.dtype(name)`` resolves once ml_dtypes is imported."""
    import numpy as np

    if dtype.hasobject:
        return None
    tag = dtype.str
    try:
        if np.dtype(tag) == dtype:
            return tag
    except TypeError:
        pass
    tag = dtype.name
    try:
        if np.dtype(tag) == dtype:
            return tag
    except TypeError:
        pass
    return None


def _resolve_dtype(tag: str):
    import numpy as np

    try:
        return np.dtype(tag)
    except TypeError:
        # extension dtypes register with numpy on import (bfloat16 etc.)
        import ml_dtypes  # noqa: F401

        return np.dtype(tag)


def try_serialize_array(value) -> Optional[ZeroCopyArray]:
    """The typed fast path: a single contiguous numpy/JAX array object.

    Returns None — caller falls back to the pickle path — for anything
    else: non-arrays, object dtypes, and non-contiguous layouts (a
    sliced array's strides cannot be represented as one raw segment
    without a gather; refusing keeps the fast path a pure memcpy).
    """
    import numpy as np

    tname = type(value).__module__
    if tname.startswith("jax") or tname.startswith("jaxlib"):
        try:
            import jax

            if isinstance(value, jax.Array):
                # device buffers cannot cross processes; this is the one
                # host materialization (zero-copy on the CPU backend)
                value = np.asarray(value)
        except ImportError:
            return None
    if type(value) is not np.ndarray:
        return None  # subclasses may carry state the header cannot
    if value.ndim > 255:
        return None
    if value.flags["C_CONTIGUOUS"]:
        order = 0
        base = value
    elif value.flags["F_CONTIGUOUS"]:
        order = 1
        base = value.T  # C-contiguous view over the same memory
    else:
        return None
    tag = _dtype_tag(value.dtype)
    if tag is None:
        return None
    tag_b = tag.encode()
    if len(tag_b) > 255:
        return None
    descriptor = struct.pack(_ZC_PREFIX, _ZC_VERSION, order, value.ndim,
                             len(tag_b), value.nbytes) + tag_b + \
        struct.pack(f"<{value.ndim}Q", *value.shape)
    # raw uint8 view (not memoryview: extension dtypes like bfloat16
    # refuse the buffer protocol, but .view(uint8) on a contiguous
    # array is always a free reinterpretation)
    raw = base.reshape(-1).view(np.uint8) if value.nbytes else \
        np.empty(0, np.uint8)
    return ZeroCopyArray(descriptor, raw, value.nbytes)


def is_zero_copy(data: memoryview) -> bool:
    """Header peek: does this wire object use the typed array format?"""
    if len(data) < 8:
        return False
    _, num_buffers = struct.unpack_from("<II", data, 0)
    return num_buffers == ZC_SENTINEL


def _deserialize_zero_copy(data: memoryview):
    """Rebuild the array as a read-only view aliasing ``data`` (the
    store mmap) — jax.device_put streams from it with no host copy. The
    caller owns pin semantics: the view must not outlive the store
    segment (see Worker._pin_escaping_view / raylint R9)."""
    import numpy as np

    meta_len, _ = struct.unpack_from("<II", data, 0)
    off = 8
    version, order, ndim, tag_len, nbytes = struct.unpack_from(
        _ZC_PREFIX, data, off)
    if version != _ZC_VERSION:
        raise ValueError(f"unknown zero-copy array version {version}")
    pos = off + struct.calcsize(_ZC_PREFIX)
    tag = bytes(data[pos : pos + tag_len]).decode()
    pos += tag_len
    shape = struct.unpack_from(f"<{ndim}Q", data, pos)
    off += _align(meta_len)
    dtype = _resolve_dtype(tag)
    arr = np.frombuffer(data[off : off + nbytes], dtype=dtype)
    out = np.reshape(arr, shape, order="F" if order else "C")
    try:
        # sealed objects are immutable: a writable alias (the native
        # arena hands out writable buffers) would let user code corrupt
        # a segment other processes share
        out.flags.writeable = False
    except ValueError:
        pass
    return out


def _jax_array_reducer(arr):
    import numpy as np

    return (_restore_numpy, (np.asarray(arr),))


def _restore_numpy(np_arr):
    return np_arr


class _Pickler(cloudpickle.Pickler):
    def __init__(self, file, buffer_callback):
        super().__init__(file, protocol=5, buffer_callback=buffer_callback)

    def reducer_override(self, obj):
        # jax.Array must come to host before crossing a process boundary.
        tname = type(obj).__module__
        if tname.startswith("jaxlib") or tname.startswith("jax"):
            try:
                import jax

                if isinstance(obj, jax.Array):
                    return _jax_array_reducer(obj)
            except ImportError:
                pass
        # Delegate to cloudpickle's own override (functions/classes by value).
        return super().reducer_override(obj)


class SerializationContext:
    """Per-worker serialization context with pluggable reducers for refs."""

    def __init__(self):
        self._object_ref_reducer: Optional[Callable] = None
        self._actor_handle_reducer: Optional[Callable] = None
        self._out_of_band_threshold = 1024  # buffers below this are inlined

    def set_object_ref_reducer(self, reducer: Callable) -> None:
        self._object_ref_reducer = reducer

    def set_actor_handle_reducer(self, reducer: Callable) -> None:
        self._actor_handle_reducer = reducer

    def serialize(self, value: Any):
        # typed fast path first: a bare contiguous array skips the whole
        # pickle machinery (ZeroCopyArray is duck-compatible downstream)
        zc = try_serialize_array(value)
        if zc is not None:
            return zc
        buffers: List[pickle.PickleBuffer] = []

        def buffer_cb(pb: pickle.PickleBuffer) -> bool:
            if len(pb.raw()) < self._out_of_band_threshold:
                return True  # inline small buffers into the pickle stream
            buffers.append(pb)
            return False

        file = io.BytesIO()
        pickler = _Pickler(file, buffer_cb)
        ctx = _reducer_context
        ctx.object_ref_reducer = self._object_ref_reducer
        ctx.actor_handle_reducer = self._actor_handle_reducer
        try:
            pickler.dump(value)
        finally:
            ctx.object_ref_reducer = None
            ctx.actor_handle_reducer = None
        return SerializedObject(file.getvalue(), buffers)

    def serialize_memoized(self, value: Any, memo: "SerializeMemo") -> bytes:
        """Serialize through a per-batch memo (ISSUE 18): submit_many
        batches routinely share argument objects — a config dict, a model
        handle, a closure — across every call; the shared object pickles
        ONCE per batch instead of once per task."""
        blob = memo.lookup(value)
        if blob is None:
            blob = self.serialize(value).to_bytes()
            memo.store(value, blob)
        return blob

    def deserialize(self, data: memoryview) -> Any:
        meta_len, num_buffers = struct.unpack_from("<II", data, 0)
        if num_buffers == ZC_SENTINEL:
            return _deserialize_zero_copy(data)
        off = 8
        meta = data[off : off + meta_len]
        off += _align(meta_len)
        buffers = []
        for _ in range(num_buffers):
            (blen,) = struct.unpack_from("<Q", data, off)
            off += 8
            buffers.append(data[off : off + blen])
            off += _align(blen)
        return pickle.loads(meta, buffers=buffers)


class SerializeMemo:
    """Identity-keyed serialization memo scoped to one submit_many batch.

    Keyed by ``id(value)`` with the value itself pinned in the entry: the
    pin keeps the object alive for the memo's lifetime, so a recycled id
    can never alias a different object, and the ``is`` check makes the
    hit exact. Mutation between calls of the SAME batch is not a hazard —
    a batch snapshot is one submission instant, exactly like positional
    args captured by a single ``submit_task`` call."""

    __slots__ = ("_by_id",)

    def __init__(self):
        self._by_id: dict = {}

    def lookup(self, value: Any) -> Optional[bytes]:
        hit = self._by_id.get(id(value))
        if hit is not None and hit[0] is value:
            return hit[1]
        return None

    def store(self, value: Any, blob: bytes) -> None:
        self._by_id[id(value)] = (value, blob)


import threading


class _ReducerContext(threading.local):
    """Per-thread reducer state: concurrent serializations (actor threads,
    the IO loop, the driver thread) must not clobber each other's collected
    nested-ref lists."""

    def __init__(self):
        self.object_ref_reducer: Optional[Callable] = None
        self.actor_handle_reducer: Optional[Callable] = None
        self.collected_refs = None


_reducer_context = _ReducerContext()


def get_reducer_context() -> _ReducerContext:
    return _reducer_context


def dumps(value: Any) -> bytes:
    """Plain cloudpickle for control-plane payloads (functions, specs)."""
    return cloudpickle.dumps(value, protocol=5)


def loads(data: bytes) -> Any:
    return pickle.loads(data)
