"""State API coverage (reference: python/ray/util/state/api.py —
list_actors :782, list_tasks :1014, list_objects, list_workers, summaries;
VERDICT r1 weak #6)."""

import time

import numpy as np
import pytest

import ray_tpu
from ray_tpu.util import state

# the module-scoped `populated` fixture holds a plasma ref for the whole
# module BY DESIGN (list_objects needs a resident object to see), so the
# per-test ref-leak gate (ISSUE 15) must not count it
pytestmark = pytest.mark.ref_leaks_ok


@pytest.fixture(scope="module")
def populated(ray_start_regular):
    @ray_tpu.remote
    class Stateful:
        def ping(self):
            return "pong"

    @ray_tpu.remote
    def work(x):
        return x + 1

    actor = Stateful.options(name="state-api-actor").remote()
    ray_tpu.get(actor.ping.remote(), timeout=60)
    ray_tpu.get([work.remote(i) for i in range(5)], timeout=60)
    big_ref = ray_tpu.put(np.zeros(300_000, np.float64))  # plasma-resident
    yield {"actor": actor, "big_ref": big_ref}


def test_list_nodes(populated):
    nodes = state.list_nodes()
    assert len(nodes) == 1
    assert nodes[0]["state"] == "ALIVE"
    assert nodes[0]["resources_total"].get("CPU") == 4.0


def test_list_actors_and_filters(populated):
    actors = state.list_actors()
    assert any(a["name"] == "state-api-actor" for a in actors)
    alive = state.list_actors(filters=[("state", "=", "ALIVE")])
    assert all(a["state"] == "ALIVE" for a in alive)
    none = state.list_actors(filters=[("state", "=", "NO_SUCH_STATE")])
    assert none == []


def test_list_tasks_records_finished(populated):
    tasks = state.list_tasks()
    assert any(t.get("name", "").endswith("work")
               and t.get("state") == "FINISHED" for t in tasks)
    limited = state.list_tasks(limit=2)
    assert len(limited) <= 2


def test_list_workers(populated):
    workers = state.list_workers()
    assert workers, "no workers listed"
    assert all(w["node_id"] for w in workers)
    assert any(w["state"] == "ACTOR" for w in workers), workers
    assert all(isinstance(w.get("pid"), int) for w in workers)


def test_list_objects_sees_plasma_object(populated):
    ref = populated["big_ref"]
    deadline = time.time() + 10
    found = False
    while time.time() < deadline and not found:
        objs = state.list_objects()
        found = any(o["object_id"] == ref.hex() for o in objs)
        if not found:
            time.sleep(0.2)
    assert found, "plasma object not listed"
    sizes = [o["size_bytes"] for o in state.list_objects()
             if o["object_id"] == ref.hex()]
    assert sizes and sizes[0] >= 300_000 * 8


def test_summaries(populated):
    ts = state.summarize_tasks()
    work_key = next(k for k in ts if k.endswith("work"))
    assert ts[work_key].get("FINISHED", 0) >= 5
    acts = state.summarize_actors()
    assert any(v.get("ALIVE") for v in acts.values())
    objs = state.summarize_objects()
    assert objs and all(v["count"] >= 1 for v in objs.values())


def test_filter_ops_validate(populated):
    with pytest.raises(ValueError):
        state.list_actors(filters=[("state", "~", "ALIVE")])


def test_filter_predicate_operators():
    """Reference predicate set (python/ray/util/state/api.py filters):
    ordering ops are numeric-aware; contains matches substrings."""
    from ray_tpu.util.state import _apply_filters

    rows = [{"pid": 5, "name": "worker-a"},
            {"pid": 30, "name": "worker-b"},
            {"pid": 200, "name": "driver"}]
    # numeric ordering (string compare would put "200" < "5")
    assert len(_apply_filters(rows, [("pid", ">", 10)])) == 2
    assert len(_apply_filters(rows, [("pid", "<=", 30)])) == 2
    assert len(_apply_filters(rows, [("pid", ">=", 200)])) == 1
    assert len(_apply_filters(rows, [("pid", "<", 5)])) == 0
    assert len(_apply_filters(rows, [("name", "contains", "worker")])) == 2
    assert len(_apply_filters(rows, [("name", "!contains", "work")])) == 1
    # chaining ANDs
    assert len(_apply_filters(
        rows, [("pid", ">", 10), ("name", "contains", "worker")])) == 1
    # missing keys never match ordering or contains ops
    assert len(_apply_filters(rows, [("zzz", ">", 0)])) == 0
    assert len(_apply_filters(rows, [("zzz", "contains", "x")])) == 0
    assert len(_apply_filters(rows, [("zzz", "!contains", "x")])) == 0
    import pytest as _pytest

    with _pytest.raises(ValueError):
        _apply_filters(rows, [("pid", "~", 1)])
