"""DataContext — per-process execution configuration (reference:
python/ray/data/context.py DataContext / DatasetContext: a thread-safe
singleton of tunables read by the planner and streaming executor).
"""

from __future__ import annotations

import dataclasses
import threading
from typing import ClassVar, Optional

from ray_tpu._private.config import CONFIG


@dataclasses.dataclass
class DataContext:
    """Knobs for the streaming execution engine.

    - ``read_parallelism``: default number of read tasks per datasource
    - ``max_tasks_in_flight_per_op``: bounded concurrent tasks per map op
    - ``per_op_buffer``: bundles buffered between operators (backpressure)
    - ``output_buffer``: bundles buffered at the consumer edge

    The ``shuffle_*`` / ``iter_prefetch`` / ``exec_idle_wait`` knobs
    (streaming multi-node shuffle, ISSUE 12) seed from the
    ``data_*`` config flags so they stay env-overridable per process.
    """

    read_parallelism: int = 8
    max_tasks_in_flight_per_op: int = 8
    per_op_buffer: int = 32
    output_buffer: int = 16
    # bytes of queued block payload the pipeline may hold before dispatch
    # is restricted to the most-downstream op (0 = unlimited); enforced by
    # ResourceBudgetBackpressurePolicy via the ResourceManager
    execution_memory_limit: int = 0
    # policy classes consulted on every dispatch (None = defaults:
    # concurrency cap, streaming output buffer, resource budget)
    backpressure_policies: Optional[list] = None
    # --- streaming shuffle (ISSUE 12) ---
    # False = legacy materializing AllToAll exchange for shuffle/sort
    streaming_shuffle: bool = dataclasses.field(
        default_factory=lambda: bool(CONFIG.data_streaming_shuffle))
    # byte budget over admitted-but-unfinished reducers' input shards
    shuffle_max_inflight_shard_bytes: int = dataclasses.field(
        default_factory=lambda: int(CONFIG.data_shuffle_inflight_bytes))
    shuffle_max_reduce_retries: int = dataclasses.field(
        default_factory=lambda: int(
            CONFIG.data_shuffle_max_reduce_retries))
    shuffle_max_concurrency: int = dataclasses.field(
        default_factory=lambda: int(CONFIG.data_shuffle_max_concurrency))
    # extra .options() for shuffle map / reduce tasks (resource pinning)
    shuffle_map_remote_args: Optional[dict] = None
    shuffle_reduce_remote_args: Optional[dict] = None
    # consumer-side block prefetch window (Dataset._iter_blocks)
    iter_prefetch_blocks: int = dataclasses.field(
        default_factory=lambda: int(CONFIG.data_iter_prefetch_blocks))
    # executor drive loop fallback wake period (event-paced, ISSUE 12)
    exec_idle_wait_s: float = dataclasses.field(
        default_factory=lambda: float(CONFIG.data_exec_idle_wait_s))

    _lock: ClassVar[threading.Lock] = threading.Lock()
    _current: ClassVar[Optional["DataContext"]] = None

    @classmethod
    def get_current(cls) -> "DataContext":
        with cls._lock:
            if cls._current is None:
                cls._current = cls()
            return cls._current

    @classmethod
    def _set_current(cls, ctx: "DataContext") -> None:
        with cls._lock:
            cls._current = ctx
