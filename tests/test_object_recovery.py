"""Lineage reconstruction of lost plasma objects (reference:
src/ray/core_worker/object_recovery_manager.h — the owner resubmits the
creating task when an object's locations die; SURVEY §5 failure
detection / hard part 1)."""

import time

import numpy as np
import pytest

import ray_tpu
from ray_tpu.cluster_utils import Cluster


@pytest.fixture()
def two_node_cluster():
    cluster = Cluster(initialize_head=True, head_node_args={"num_cpus": 2})
    ray_tpu.init(_node=cluster.head_node)
    yield cluster
    ray_tpu.shutdown()
    cluster.shutdown()


def test_object_reconstruction_after_node_death(two_node_cluster):
    cluster = two_node_cluster
    node_a = cluster.add_node(num_cpus=2, resources={"side": 2})
    cluster.wait_for_nodes()

    @ray_tpu.remote(max_retries=2, resources={"side": 1})
    def produce():
        # big enough to live in the object store, not inline
        return np.full(200_000, 7, np.int64)

    ref = produce.remote()
    ready, _ = ray_tpu.wait([ref], num_returns=1, timeout=120)
    assert ready, "produce() did not finish"

    # the only copy lives on node A; kill it, then give the resubmitted
    # task somewhere feasible to run
    cluster.remove_node(node_a)
    cluster.add_node(num_cpus=2, resources={"side": 2})
    cluster.wait_for_nodes()
    time.sleep(2.5)  # node-death detection lag (~2s health check)

    value = ray_tpu.get(ref, timeout=180)
    assert value.shape == (200_000,)
    assert int(value[0]) == 7


def test_reconstruction_respects_max_retries(two_node_cluster):
    cluster = two_node_cluster
    node_a = cluster.add_node(num_cpus=2, resources={"side": 2})
    cluster.wait_for_nodes()

    @ray_tpu.remote(max_retries=0, resources={"side": 1})
    def produce_no_retry():
        return np.full(150_000, 3, np.int64)

    ref = produce_no_retry.remote()
    ready, _ = ray_tpu.wait([ref], num_returns=1, timeout=120)
    assert ready
    cluster.remove_node(node_a)
    cluster.add_node(num_cpus=2, resources={"side": 2})
    cluster.wait_for_nodes()
    time.sleep(2.5)
    # max_retries=0: the object is gone and must NOT be reconstructed
    with pytest.raises(Exception):
        ray_tpu.get(ref, timeout=20)
