"""R8 — config knob hygiene: reads, declarations, and docs must agree.

Three invariants over the ``_flag(...)`` table:

- **Unknown read**: ``CONFIG.<flag>`` resolves through
  ``_Config.__getattr__`` which raises ``AttributeError`` for names
  missing from the table — but only *when the line executes*, which for
  rarely-taken paths (failure handling, chaos branches) is production,
  not tests.
- **Dead knob** (full-tree runs only — absence evidence): a flag
  declared in the table but never read anywhere (``CONFIG.name``,
  ``getattr(CONFIG, "name")``, the quoted name, or its
  ``RAY_TPU_NAME`` env form) is config surface that lies to operators —
  setting it does nothing. The PR 19 audit found 13 of these, declared
  for reference parity with mechanisms that were never built.
- **Doc drift**: a knob named in one of README's ``**Knobs**``
  paragraphs that is not in the table documents an override that
  silently doesn't exist (the reverse direction — undocumented knobs —
  is deliberate: internal tuning knobs outnumber operator-facing ones).

Detection: the flag table is parsed from ``config.py``'s ``_flag("name",
default)`` calls; reads are scanned per module (AST for attribute/
getattr forms, source text for quoted/env forms to catch dynamic
lookups); README is scanned only when the index carries a project root.
"""

from __future__ import annotations

import ast
import os
import re
from typing import List, Set, Tuple

from ..model import ModuleInfo, Violation

RULE_ID = "R8"
SUMMARY = ("config knob drift: CONFIG.<name> missing from the _flag "
           "table, a declared knob never read anywhere (dead config "
           "surface), or a README-documented knob that doesn't exist")

# knob-name shape inside a README **Knobs** paragraph; uppercase tokens
# (RAY_TPU_* env hooks) and dotted tokens (filenames) never match
_KNOB_TOKEN_RE = re.compile(r"`([a-z][a-z0-9_]*_[a-z0-9_]+)`")
# raw env hooks documented alongside knobs but intentionally not flags
_ENV_HOOK_ALLOWLIST = {"fault_injection", "fault_file"}
# dead-knob scanning needs the whole tree as evidence; subset runs
# (fixtures, --changed, single dirs) can't prove absence
_FULL_TREE_MIN_MODULES = 100

_CONFIG_METHODS = {"apply_cluster_config", "snapshot", "to_json"}
_CONFIG_FILE_SUFFIX = "_private/config.py"


def _flag_decls(index) -> List[Tuple[ModuleInfo, ast.Call, str]]:
    out: List[Tuple[ModuleInfo, ast.Call, str]] = []
    for mod in index.modules:
        if not mod.relpath.replace("\\", "/").endswith(_CONFIG_FILE_SUFFIX):
            continue
        for node in ast.walk(mod.tree):
            if (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Name)
                    and node.func.id == "_flag" and node.args
                    and isinstance(node.args[0], ast.Constant)
                    and isinstance(node.args[0].value, str)):
                out.append((mod, node, node.args[0].value))
    return out


def _check_dead_knobs(index, decls) -> List[Violation]:
    if len(index.modules) < _FULL_TREE_MIN_MODULES:
        return []
    alive: Set[str] = set()
    names = [name for _m, _n, name in decls]
    for mod in index.modules:
        if mod.relpath.replace("\\", "/").endswith(_CONFIG_FILE_SUFFIX):
            continue
        src = mod.source
        for name in names:
            if name in alive:
                continue
            if (f"CONFIG.{name}" in src or f'"{name}"' in src
                    or f"'{name}'" in src
                    or f"RAY_TPU_{name.upper()}" in src):
                alive.add(name)
    out: List[Violation] = []
    for mod, node, name in decls:
        if name in alive:
            continue
        out.append(mod.violation(
            RULE_ID, node,
            f"config knob '{name}' is declared here but never read "
            f"anywhere in the tree (no CONFIG.{name}, getattr, quoted "
            f"name, or RAY_TPU_{name.upper()} reference) — setting it "
            f"does nothing; wire it to the mechanism or delete the "
            f"declaration"))
    return out


def _check_readme_drift(index, flags: Set[str]) -> List[Violation]:
    root = getattr(index, "project_root", None)
    if not root:
        return []
    readme = os.path.join(root, "README.md")
    try:
        with open(readme, "r", encoding="utf-8") as f:
            lines = f.read().splitlines()
    except OSError:
        return []
    out: List[Violation] = []
    in_knobs = False
    for i, line in enumerate(lines, start=1):
        if not line.strip():
            in_knobs = False
            continue
        if line.lstrip().startswith("**Knobs**"):
            in_knobs = True
        if not in_knobs:
            continue
        for m in _KNOB_TOKEN_RE.finditer(line):
            name = m.group(1)
            if name in flags or name in _ENV_HOOK_ALLOWLIST:
                continue
            out.append(Violation(
                rule=RULE_ID, path="README.md", line=i,
                col=m.start() + 1,
                message=(f"README documents knob '{name}' in a "
                         f"**Knobs** paragraph, but config.py's _flag "
                         f"table doesn't declare it — the documented "
                         f"RAY_TPU_{name.upper()} override silently "
                         f"does nothing; fix the doc or declare the "
                         f"flag"),
                symbol="<readme>", snippet=line.strip()))
    return out


def check(index) -> List[Violation]:
    decls = _flag_decls(index)
    flags = {name for _m, _n, name in decls}
    if not flags:
        # config.py not in the analyzed set (e.g. linting a fixture dir):
        # nothing to check against
        return []
    out: List[Violation] = []
    out.extend(_check_dead_knobs(index, decls))
    out.extend(_check_readme_drift(index, flags))
    for mod in index.modules:
        if mod.relpath.replace("\\", "/").endswith(_CONFIG_FILE_SUFFIX):
            continue
        for node in ast.walk(mod.tree):
            name = None
            target = None
            if (isinstance(node, ast.Attribute)
                    and isinstance(node.value, ast.Name)
                    and node.value.id == "CONFIG"):
                name, target = node.attr, node
            elif (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Name)
                    and node.func.id == "getattr" and len(node.args) >= 2
                    and isinstance(node.args[0], ast.Name)
                    and node.args[0].id == "CONFIG"
                    and isinstance(node.args[1], ast.Constant)
                    and isinstance(node.args[1].value, str)):
                name, target = node.args[1].value, node
            if name is None:
                continue
            if name.startswith("_") or name in _CONFIG_METHODS:
                continue
            if name not in flags:
                out.append(mod.violation(
                    RULE_ID, target,
                    f"CONFIG.{name} is not declared in config.py's _flag "
                    f"table: _Config.__getattr__ will raise "
                    f"AttributeError the first time this line runs — "
                    f"declare the flag with a typed default or fix the "
                    f"name"))
    return out
