"""Multi-agent environments + rollout (reference: rllib/env/
multi_agent_env.py MultiAgentEnv and the multi-agent sampling path in
evaluation/rollout_worker.py — dict-keyed obs/rewards per agent, a
policy_mapping_fn routing each agent to a policy).

The JAX shape: per-policy inference batches are built by grouping live
agents by their mapped policy each step, so one jitted forward serves all
agents of a policy regardless of how many are alive. Batch shapes vary
with the number of live agents; CPU-side inference handles that (ragged
steps are the nature of multi-agent), while learner updates stay
fixed-shape row batches.
"""

from __future__ import annotations

import time
from typing import Any, Callable, Dict, List, Optional

import numpy as np


class MultiAgentEnv:
    """Dict-keyed multi-agent env (reference: env/multi_agent_env.py).

    Contract: ``reset() -> (obs_dict, info_dict)``;
    ``step(action_dict) -> (obs, rewards, terminateds, truncateds, infos)``
    with per-agent dicts; terminateds/truncateds carry the special
    ``"__all__"`` key ending the episode for everyone.
    """

    possible_agents: List[str] = []

    def reset(self, *, seed: Optional[int] = None):
        raise NotImplementedError

    def step(self, action_dict: Dict[str, Any]):
        raise NotImplementedError

    def close(self) -> None:
        pass

    @property
    def observation_spaces(self) -> Dict[str, Any]:
        raise NotImplementedError

    @property
    def action_spaces(self) -> Dict[str, Any]:
        raise NotImplementedError


class MultiAgentEnvRunner:
    """Rollout actor for MultiAgentEnv (reference: the multi-agent branch
    of RolloutWorker.sample): collects per-POLICY row batches with
    per-agent GAE-ready reward/done streams."""

    def __init__(self, env_creator: Callable[[], MultiAgentEnv],
                 rollout_fragment_length: int,
                 module_specs: Dict[str, Any],
                 policy_mapping_fn: Callable[[str], str],
                 seed: int = 0, gamma: float = 0.99):
        import jax

        self.env = env_creator()
        self.T = rollout_fragment_length
        self.gamma = gamma
        self.policy_mapping_fn = policy_mapping_fn
        self.modules = {pid: spec.build()
                        for pid, spec in module_specs.items()}
        self._jit_explore = {
            pid: jax.jit(m.explore_action)
            for pid, m in self.modules.items()}
        self._rng = jax.random.key(seed)
        self._obs, _ = self.env.reset(seed=seed)
        self._ep_return = 0.0
        self._ep_len = 0
        self._completed: List[Dict] = []

    def ping(self) -> bool:
        return True

    def sample(self, weights: Dict[str, Any]) -> Dict[str, Any]:
        """Run T env steps; returns {"agent_batches": {pid: {agent_id:
        rows}}, "episodes": [...], "env_steps": n}. Rows are PER-AGENT
        streams so GAE's time recursion never crosses agents sharing a
        policy."""
        import jax

        # per-(policy, agent) row buffers
        buf: Dict[tuple, Dict[str, List]] = {}

        def agent_buf(pid: str, agent_id: str) -> Dict[str, List]:
            return buf.setdefault((pid, agent_id), {
                "obs": [], "actions": [], "logp": [], "vf": [],
                "rewards": [], "dones": []})
        env_steps = 0
        t0 = time.perf_counter()
        for _ in range(self.T):
            # group live agents by policy for batched inference
            by_policy: Dict[str, List[str]] = {}
            for agent_id in self._obs:
                by_policy.setdefault(
                    self.policy_mapping_fn(agent_id), []).append(agent_id)
            actions: Dict[str, Any] = {}
            step_meta: Dict[str, tuple] = {}  # agent -> (pid, logp, vf)
            for pid, agent_ids in by_policy.items():
                batch = np.stack([np.asarray(self._obs[a], np.float32)
                                  for a in agent_ids])
                self._rng, key = jax.random.split(self._rng)
                act, logp, vf = self._jit_explore[pid](
                    weights[pid], batch, key)
                act = np.asarray(act)
                logp, vf = np.asarray(logp), np.asarray(vf)
                for i, a in enumerate(agent_ids):
                    actions[a] = act[i]
                    step_meta[a] = (pid, logp[i], vf[i])
            obs2, rewards, terms, truncs, _ = self.env.step(actions)
            done_all = terms.get("__all__", False) or \
                truncs.get("__all__", False)
            for a, action in actions.items():
                pid, logp, vf = step_meta[a]
                done = bool(terms.get(a, False) or truncs.get(a, False)
                            or done_all)
                ab = agent_buf(pid, a)
                ab["obs"].append(np.asarray(self._obs[a], np.float32))
                ab["actions"].append(np.asarray(action))
                ab["logp"].append(np.float32(logp))
                ab["vf"].append(np.float32(vf))
                ab["rewards"].append(np.float32(rewards.get(a, 0.0)))
                ab["dones"].append(np.float32(done))
            self._ep_return += float(sum(rewards.values()))
            self._ep_len += 1
            env_steps += 1
            if done_all:
                self._completed.append({
                    "episode_return": self._ep_return,
                    "episode_len": self._ep_len})
                self._obs, _ = self.env.reset()
                self._ep_return, self._ep_len = 0.0, 0
            else:
                self._obs = obs2

        agent_batches: Dict[str, Dict[str, Dict[str, np.ndarray]]] = {}
        for (pid, agent_id), cols in buf.items():
            if not cols["obs"]:
                continue
            agent_batches.setdefault(pid, {})[agent_id] = {
                k: np.stack(v) if k in ("obs", "actions")
                else np.asarray(v, np.float32)
                for k, v in cols.items()}
        episodes, self._completed = self._completed, []
        return {"agent_batches": agent_batches, "episodes": episodes,
                "env_steps": env_steps,
                "sample_time_s": time.perf_counter() - t0}

    def stop(self) -> bool:
        self.env.close()
        return True
