"""ResultGrid (reference: python/ray/tune/result_grid.py)."""

from __future__ import annotations

from typing import List, Optional

from ray_tpu.train._checkpoint import Checkpoint
from ray_tpu.train.base_trainer import Result
from ray_tpu.tune.experiment import Trial


class ResultGrid:
    def __init__(self, trials: List[Trial], metric: Optional[str] = None,
                 mode: str = "max"):
        self._trials = trials
        self._metric = metric
        self._mode = mode
        self._results = [self._trial_to_result(t) for t in trials]

    @staticmethod
    def _trial_to_result(trial: Trial) -> Result:
        return Result(
            metrics=trial.last_result or None,
            checkpoint=(Checkpoint(trial.checkpoint_path)
                        if trial.checkpoint_path else None),
            path=trial.local_dir,
            error=(RuntimeError(trial.error_msg)
                   if trial.error_msg else None),
            config=trial.config,
        )

    def __len__(self) -> int:
        return len(self._results)

    def __getitem__(self, i: int) -> Result:
        return self._results[i]

    def __iter__(self):
        return iter(self._results)

    @property
    def errors(self) -> List[Exception]:
        return [r.error for r in self._results if r.error]

    @property
    def num_errors(self) -> int:
        return len(self.errors)

    @property
    def num_terminated(self) -> int:
        return sum(1 for t in self._trials if t.status == Trial.TERMINATED)

    def get_best_result(self, metric: Optional[str] = None,
                        mode: Optional[str] = None,
                        scope: str = "last") -> Result:
        metric = metric or self._metric
        mode = mode or self._mode
        if metric is None:
            raise ValueError("metric is required (set it in TuneConfig or "
                             "pass it to get_best_result)")
        sign = 1 if mode == "max" else -1

        def key(pair):
            trial, _ = pair
            if scope == "all":
                best = trial.best_metric(metric, mode)
                return sign * best if best is not None else float("-inf")
            v = (trial.last_result or {}).get(metric)
            return sign * v if v is not None else float("-inf")

        candidates = [(t, r) for t, r in zip(self._trials, self._results)
                      if r.metrics]
        if not candidates:
            raise RuntimeError("no trial produced results")
        return max(candidates, key=key)[1]

    def get_dataframe(self):
        import pandas as pd

        rows = []
        for t in self._trials:
            row = dict(t.last_result or {})
            row["trial_id"] = t.trial_id
            row["status"] = t.status
            for k, v in t.config.items():
                if isinstance(v, (int, float, str, bool)) or v is None:
                    row[f"config/{k}"] = v
            rows.append(row)
        return pd.DataFrame(rows)
