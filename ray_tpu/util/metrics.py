"""User-defined metrics (reference: python/ray/util/metrics.py
Counter/Gauge/Histogram → includes/metric.pxi; exported in Prometheus text
format the way the reference's dashboard agent exposes them).

Metrics are process-local and aggregated through the head KV: each process
periodically publishes its serialized metric snapshot under
``metrics::{node}::{pid}``; ``prometheus_text()`` merges all snapshots.
"""

from __future__ import annotations

import bisect
import json
import os
import threading
import time
from typing import Dict, List, Optional, Sequence, Tuple

_REGISTRY: Dict[str, "Metric"] = {}
_registry_lock = threading.Lock()
_FLUSH_PERIOD_S = 2.0
_flusher_started = False


def _tag_key(tags: Optional[Dict[str, str]]) -> Tuple:
    return tuple(sorted((tags or {}).items()))


class Metric:
    kind = "untyped"

    def __init__(self, name: str, description: str = "",
                 tag_keys: Sequence[str] = ()):
        self.name = name
        self.description = description
        self.tag_keys = tuple(tag_keys)
        self._default_tags: Dict[str, str] = {}
        self._values: Dict[Tuple, float] = {}
        self._lock = threading.Lock()
        with _registry_lock:
            _REGISTRY[name] = self
        _ensure_flusher()

    def set_default_tags(self, tags: Dict[str, str]) -> "Metric":
        self._default_tags = dict(tags)
        return self

    def _merged(self, tags: Optional[Dict[str, str]]) -> Dict[str, str]:
        return {**self._default_tags, **(tags or {})}

    # ------------------------------------------------------------- export
    def snapshot(self) -> Dict:
        with self._lock:
            return {
                "name": self.name, "kind": self.kind,
                "description": self.description,
                "values": [[list(k), v] for k, v in self._values.items()],
            }


class Counter(Metric):
    kind = "counter"

    def inc(self, value: float = 1.0,
            tags: Optional[Dict[str, str]] = None) -> None:
        if value < 0:
            raise ValueError("counters only increase")
        key = _tag_key(self._merged(tags))
        with self._lock:
            self._values[key] = self._values.get(key, 0.0) + value


class Gauge(Metric):
    kind = "gauge"

    def set(self, value: float,
            tags: Optional[Dict[str, str]] = None) -> None:
        with self._lock:
            self._values[_tag_key(self._merged(tags))] = float(value)


class Histogram(Metric):
    kind = "histogram"

    def __init__(self, name: str, description: str = "",
                 boundaries: Sequence[float] = (),
                 tag_keys: Sequence[str] = ()):
        super().__init__(name, description, tag_keys)
        self.boundaries = sorted(boundaries) or [
            0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1, 2.5, 5, 10]
        self._counts: Dict[Tuple, List[int]] = {}
        self._sums: Dict[Tuple, float] = {}

    def observe(self, value: float,
                tags: Optional[Dict[str, str]] = None) -> None:
        key = _tag_key(self._merged(tags))
        with self._lock:
            counts = self._counts.setdefault(
                key, [0] * (len(self.boundaries) + 1))
            counts[bisect.bisect_left(self.boundaries, value)] += 1
            self._sums[key] = self._sums.get(key, 0.0) + value

    def snapshot(self) -> Dict:
        with self._lock:
            return {
                "name": self.name, "kind": self.kind,
                "description": self.description,
                "boundaries": self.boundaries,
                "counts": [[list(k), v] for k, v in self._counts.items()],
                "sums": [[list(k), v] for k, v in self._sums.items()],
            }


def make_gauge_snapshot(name: str, description: str, value: float,
                        tags: Optional[Dict[str, str]] = None) -> Dict:
    """One-off gauge in the exact snapshot schema prometheus_text()
    merges — for publishers (the node agent) that don't keep Metric
    registries."""
    tag_list = [[k, v] for k, v in (tags or {}).items()]
    return {"name": name, "kind": "gauge", "description": description,
            "values": [[tag_list, value]]}


def make_counter_snapshot(name: str, description: str, value: float,
                          tags: Optional[Dict[str, str]] = None) -> Dict:
    """Counter-kind snapshot for monotonically increasing runtime totals
    (chunks served, pull bytes, ...). Distinct from make_gauge_snapshot
    because the merge in prometheus_text() SUMS counters across
    publishers that share a tag set, while gauges overwrite."""
    tag_list = [[k, v] for k, v in (tags or {}).items()]
    return {"name": name, "kind": "counter", "description": description,
            "values": [[tag_list, value]]}


# ------------------------------------------------------------- aggregation
def _ensure_flusher() -> None:
    global _flusher_started
    if _flusher_started:
        return
    _flusher_started = True

    def flush_loop():
        while True:
            time.sleep(_FLUSH_PERIOD_S)
            try:
                flush_now()
            except Exception:
                pass

    threading.Thread(target=flush_loop, daemon=True,
                     name="metrics-flush").start()


def flush_now() -> None:
    """Publish this process's snapshots to the head KV."""
    from ray_tpu._private import worker as worker_mod

    w = worker_mod.global_worker
    if w is None or not w.connected:
        return
    with _registry_lock:
        snaps = [m.snapshot() for m in _REGISTRY.values()]
    if not snaps:
        return
    key = f"metrics::{w.node_id}::{os.getpid()}".encode()
    w.kv().put(key, json.dumps(snaps).encode(), namespace="_metrics")


def collect_cluster_metrics() -> List[Dict]:
    """All published snapshots across processes (driver-side)."""
    from ray_tpu._private import worker as worker_mod

    w = worker_mod.global_worker
    if w is None or not w.connected:
        return []
    kv = w.kv()
    out = []
    for key in kv.keys(b"metrics::", namespace="_metrics"):
        raw = kv.get(bytes(key), namespace="_metrics")
        if raw:
            out.extend(json.loads(raw))
    return out


def _escape_label_value(v) -> str:
    """Exposition-format label-value escaping (Prometheus text format
    0.0.4): backslash, double-quote and newline must be escaped or a
    value containing any of them corrupts every later line of the
    scrape."""
    return (str(v).replace("\\", r"\\").replace('"', r'\"')
            .replace("\n", r"\n"))


def _escape_help(text: str) -> str:
    """# HELP lines escape backslash and newline (but not quotes)."""
    return str(text).replace("\\", r"\\").replace("\n", r"\n")


def _fmt_tags(tag_list: List) -> str:
    if not tag_list:
        return ""
    inner = ",".join(f'{k}="{_escape_label_value(v)}"' for k, v in tag_list)
    return "{" + inner + "}"


def prometheus_text() -> str:
    """Merge all processes' snapshots into Prometheus exposition format
    (what the reference's metrics agent serves to Prometheus). Always
    includes baseline liveness gauges (reference: metric_defs.cc system
    metrics) so the endpoint is non-empty before any user metrics exist."""
    return render_prometheus(collect_cluster_metrics())


def render_prometheus(snapshots: List[Dict]) -> str:
    """Exposition-format rendering over an explicit snapshot list — the
    piece the head's scrape endpoint (ISSUE 14) shares with the
    driver-side ``prometheus_text()``: the head reads the ``_metrics`` KV
    namespace directly instead of round-tripping through a worker."""
    lines_prefix = [
        "# HELP ray_tpu_cluster_up Dashboard liveness gauge.",
        "# TYPE ray_tpu_cluster_up gauge",
        "ray_tpu_cluster_up 1",
        "# HELP ray_tpu_collect_time_seconds Unix time of this scrape.",
        "# TYPE ray_tpu_collect_time_seconds gauge",
        f"ray_tpu_collect_time_seconds {time.time():.3f}",
    ]
    merged: Dict[str, Dict] = {}
    for snap in snapshots:
        cur = merged.setdefault(snap["name"], snap)
        if cur is snap:
            continue
        if snap["kind"] == "histogram":
            for k, v in snap.get("counts", []):
                for existing in cur["counts"]:
                    if existing[0] == k:
                        existing[1] = [a + b for a, b in zip(existing[1], v)]
                        break
                else:
                    cur["counts"].append([k, v])
            for k, v in snap.get("sums", []):
                for existing in cur["sums"]:
                    if existing[0] == k:
                        existing[1] += v
                        break
                else:
                    cur["sums"].append([k, v])
        else:
            for k, v in snap.get("values", []):
                for existing in cur["values"]:
                    if existing[0] == k:
                        existing[1] = (existing[1] + v
                                       if snap["kind"] == "counter" else v)
                        break
                else:
                    cur["values"].append([k, v])
    lines = list(lines_prefix)
    for snap in merged.values():
        name = snap["name"]
        # conformance (ISSUE 15 satellite): HELP is escaped, TYPE falls
        # back to "untyped" for unknown kinds rather than emitting a
        # token Prometheus rejects
        kind = snap["kind"] if snap["kind"] in (
            "counter", "gauge", "histogram", "summary") else "untyped"
        lines.append(
            f"# HELP {name} {_escape_help(snap.get('description') or '')}")
        lines.append(f"# TYPE {name} {kind}")
        if snap["kind"] == "histogram":
            for key, counts in snap.get("counts", []):
                cum = 0
                for bound, c in zip(snap["boundaries"], counts):
                    cum += c
                    tag = _fmt_tags(list(key) + [["le", bound]])
                    lines.append(f"{name}_bucket{tag} {cum}")
                cum += counts[-1]
                tag = _fmt_tags(list(key) + [["le", "+Inf"]])
                lines.append(f"{name}_bucket{tag} {cum}")
                lines.append(
                    f"{name}_count{_fmt_tags(list(key))} {cum}")
            for key, s in snap.get("sums", []):
                lines.append(f"{name}_sum{_fmt_tags(list(key))} {s}")
        else:
            for key, v in snap.get("values", []):
                lines.append(f"{name}{_fmt_tags(list(key))} {v}")
    return "\n".join(lines) + "\n"


class CallbackGauge(Metric):
    """Gauge whose value is read from a zero-argument callable at snapshot
    time — for core-runtime counters kept as plain ints on hot paths
    (reference: metric_defs.cc task/worker counters; a lock per increment
    would tax the submission path this framework just batched)."""

    kind = "gauge"

    def __init__(self, name: str, description: str, fn):
        super().__init__(name, description)
        self._fn = fn

    def snapshot(self) -> Dict:
        try:
            value = float(self._fn())
        except Exception:
            value = 0.0
        return {"name": self.name, "kind": self.kind,
                "description": self.description,
                "values": [[list(_tag_key(self._default_tags)), value]]}
