"""Mixtral-style sparse Mixture-of-Experts decoder with expert parallelism.

New first-class capability (reference has no MoE or expert parallelism —
SURVEY §2.5 marks EP as absent): top-k token routing with capacity-bounded
einsum dispatch, experts sharded over the mesh ``expert`` axis so GSPMD
lowers the dispatch/combine einsums to all_to_all over ICI.

TPU shape discipline: routing is static-shape throughout — top-k gates,
one-hot dispatch masks (B,S,E,C), no gather/scatter with dynamic sizes —
so XLA tiles the expert FFNs onto the MXU like any dense matmul batch.
Aux load-balancing loss (Switch Transformer, Fedus 2021) keeps routing
uniform.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp

from ray_tpu.models.llama import _rms_norm, _rope
from ray_tpu.ops.attention import attention
from ray_tpu.parallel.sharding import constrain


@dataclasses.dataclass(frozen=True)
class MoEConfig:
    vocab_size: int = 32000
    hidden: int = 512
    mlp_hidden: int = 1024
    num_layers: int = 4
    num_heads: int = 8
    num_kv_heads: int = 8
    num_experts: int = 8
    experts_per_token: int = 2  # top-k
    capacity_factor: float = 1.25
    rope_theta: float = 10000.0
    rms_eps: float = 1e-5
    aux_loss_coeff: float = 0.01
    dtype: Any = jnp.bfloat16
    param_dtype: Any = jnp.float32

    @property
    def head_dim(self) -> int:
        return self.hidden // self.num_heads

    @staticmethod
    def tiny(vocab_size: int = 256) -> "MoEConfig":
        return MoEConfig(vocab_size=vocab_size, hidden=64, mlp_hidden=128,
                         num_layers=2, num_heads=4, num_kv_heads=4,
                         num_experts=4)

    @staticmethod
    def mixtral_8x7b_proxy() -> "MoEConfig":
        """Mixtral-8x7B-shaped config (for flops math; full size needs a
        pod slice)."""
        return MoEConfig(vocab_size=32000, hidden=4096, mlp_hidden=14336,
                         num_layers=32, num_heads=32, num_kv_heads=8,
                         num_experts=8, experts_per_token=2)


def moe_logical_axes(cfg: MoEConfig) -> Dict[str, Any]:
    layer = {
        "wq": ("embed", "heads", "head_dim"),
        "wk": ("embed", "kv_heads", "head_dim"),
        "wv": ("embed", "kv_heads", "head_dim"),
        "wo": ("heads", "head_dim", "embed"),
        "router": ("embed", "expert"),
        # expert FFN stacks: leading 'expert' dim shards over the EP axis
        "we_gate": ("expert", "embed", "mlp"),
        "we_up": ("expert", "embed", "mlp"),
        "we_down": ("expert", "mlp", "embed"),
        "attn_norm": ("norm",),
        "mlp_norm": ("norm",),
    }
    layers = {k: (None,) + v for k, v in layer.items()}
    return {
        "embed": ("vocab", "embed"),
        "layers": layers,
        "final_norm": ("norm",),
        "lm_head": ("embed", "vocab"),
    }


def init_moe(cfg: MoEConfig, key: jax.Array) -> Dict[str, Any]:
    h, m, E = cfg.hidden, cfg.mlp_hidden, cfg.num_experts
    nh, nkv, hd, L = (cfg.num_heads, cfg.num_kv_heads, cfg.head_dim,
                      cfg.num_layers)
    ks = jax.random.split(key, 12)
    pd = cfg.param_dtype

    def tn(k, shape, fan_in):
        return (jax.random.truncated_normal(k, -2, 2, shape, jnp.float32)
                * fan_in ** -0.5).astype(pd)

    layers = {
        "wq": tn(ks[0], (L, h, nh, hd), h),
        "wk": tn(ks[1], (L, h, nkv, hd), h),
        "wv": tn(ks[2], (L, h, nkv, hd), h),
        "wo": tn(ks[3], (L, nh, hd, h), nh * hd),
        "router": tn(ks[4], (L, h, E), h),
        "we_gate": tn(ks[5], (L, E, h, m), h),
        "we_up": tn(ks[6], (L, E, h, m), h),
        "we_down": tn(ks[7], (L, E, m, h), m),
        "attn_norm": jnp.ones((L, h), pd),
        "mlp_norm": jnp.ones((L, h), pd),
    }
    return {
        "embed": tn(ks[8], (cfg.vocab_size, h), h),
        "layers": layers,
        "final_norm": jnp.ones((h,), pd),
        "lm_head": tn(ks[9], (h, cfg.vocab_size), h),
    }


def _moe_ffn(cfg: MoEConfig, x: jax.Array, lp: Dict[str, jax.Array]
             ) -> Tuple[jax.Array, jax.Array]:
    """Sparse expert FFN. x: [B,S,H] -> ([B,S,H], aux_loss)."""
    dt = cfg.dtype
    B, S, H = x.shape
    E, K = cfg.num_experts, cfg.experts_per_token
    C = max(1, int(cfg.capacity_factor * S * K / E))  # per-expert capacity

    # ---- routing (fp32 for numerics)
    logits = jnp.einsum("bsh,he->bse", x.astype(jnp.float32),
                        lp["router"].astype(jnp.float32))
    probs = jax.nn.softmax(logits, axis=-1)  # [B,S,E]
    gate_vals, expert_idx = jax.lax.top_k(probs, K)  # [B,S,K]
    gate_vals = gate_vals / jnp.maximum(
        gate_vals.sum(-1, keepdims=True), 1e-9)

    # ---- capacity-bounded dispatch masks, static shapes only
    # position of each (token, k) in its expert's buffer
    onehot = jax.nn.one_hot(expert_idx, E, dtype=jnp.int32)  # [B,S,K,E]
    flat = onehot.reshape(B, S * K, E)
    pos_in_expert = (jnp.cumsum(flat, axis=1) - flat).reshape(B, S, K, E)
    keep = (pos_in_expert < C) & (onehot > 0)  # overflow tokens drop
    # dispatch [B,S,E,C]: token -> (expert, slot)
    slot_oh = jax.nn.one_hot(pos_in_expert, C, dtype=x.dtype)  # [B,S,K,E,C]
    keep_f = keep.astype(x.dtype)  # onehot is folded into `keep` already
    dispatch = jnp.einsum("bske,bskec->bsec", keep_f, slot_oh)
    combine = jnp.einsum("bsk,bske,bskec->bsec",
                         gate_vals.astype(x.dtype), keep_f, slot_oh)

    # ---- expert compute; EP shards the leading E dim -> all_to_all
    expert_in = jnp.einsum("bsec,bsh->ebch", dispatch, x)  # [E,B,C,H]
    expert_in = constrain(expert_in, ("expert", "batch", None, "embed"))
    gate = jnp.einsum("ebch,ehm->ebcm", expert_in, lp["we_gate"].astype(dt))
    up = jnp.einsum("ebch,ehm->ebcm", expert_in, lp["we_up"].astype(dt))
    act = jax.nn.silu(gate) * up
    out = jnp.einsum("ebcm,emh->ebch", act, lp["we_down"].astype(dt))
    out = constrain(out, ("expert", "batch", None, "embed"))
    y = jnp.einsum("ebch,bsec->bsh", out, combine)

    # ---- Switch-style load-balancing aux loss
    me = probs.mean(axis=(0, 1))                        # router prob mass
    ce = (onehot.sum(2) > 0).astype(jnp.float32).mean(axis=(0, 1))
    aux = E * jnp.sum(me * ce)
    return y, aux


def _moe_layer(cfg: MoEConfig, x: jax.Array, lp: Dict[str, jax.Array],
               positions: jax.Array) -> Tuple[jax.Array, jax.Array]:
    dt = cfg.dtype
    h = _rms_norm(x, lp["attn_norm"], cfg.rms_eps)
    q = jnp.einsum("bsh,hnd->bsnd", h, lp["wq"].astype(dt))
    k = jnp.einsum("bsh,hnd->bsnd", h, lp["wk"].astype(dt))
    v = jnp.einsum("bsh,hnd->bsnd", h, lp["wv"].astype(dt))
    q = _rope(q, positions, cfg.rope_theta)
    k = _rope(k, positions, cfg.rope_theta)
    attn = attention(q, k, v, impl="reference", causal=True)
    x = x + jnp.einsum("bsnd,ndh->bsh", attn, lp["wo"].astype(dt))
    h = _rms_norm(x, lp["mlp_norm"], cfg.rms_eps)
    y, aux = _moe_ffn(cfg, h, lp)
    return x + y, aux


def moe_forward(params: Dict[str, Any], tokens: jax.Array,
                cfg: MoEConfig) -> Tuple[jax.Array, jax.Array]:
    """tokens [B,S] -> (logits [B,S,V], total_aux_loss)."""
    dt = cfg.dtype
    x = params["embed"].astype(dt)[tokens]
    x = constrain(x, ("batch", "seq", "embed"))
    B, S = tokens.shape
    positions = jnp.broadcast_to(jnp.arange(S), (B, S))

    def scan_fn(carry, lp):
        x, aux = carry
        x, layer_aux = _moe_layer(cfg, x, lp, positions)
        return (x, aux + layer_aux), None

    (x, aux), _ = jax.lax.scan(scan_fn, (x, jnp.float32(0.0)),
                               params["layers"])
    x = _rms_norm(x, params["final_norm"], cfg.rms_eps)
    logits = jnp.einsum("bsh,hv->bsv", x, params["lm_head"].astype(dt))
    return logits.astype(jnp.float32), aux


def moe_loss(params: Dict[str, Any], batch: Dict[str, jax.Array],
             cfg: MoEConfig) -> jax.Array:
    logits, aux = moe_forward(params, batch["inputs"], cfg)
    logp = jax.nn.log_softmax(logits, axis=-1)
    nll = -jnp.take_along_axis(
        logp, batch["targets"][..., None], axis=-1)[..., 0]
    return nll.mean() + cfg.aux_loss_coeff * aux / cfg.num_layers
