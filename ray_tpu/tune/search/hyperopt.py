"""HyperOptSearch adapter (reference: python/ray/tune/search/hyperopt/
hyperopt_search.py). Gated: `hyperopt` is not in this image's baked
package set — construction raises a clear ImportError."""

from __future__ import annotations

from typing import Dict, Optional

from ray_tpu.tune.search.sample import Categorical, Float, Integer
from ray_tpu.tune.search.searcher import Searcher


class HyperOptSearch(Searcher):
    def __init__(self, space: Optional[Dict] = None,
                 metric: Optional[str] = None, mode: Optional[str] = None,
                 n_initial_points: int = 20, random_state_seed: int = 0,
                 **kwargs):
        try:
            import hyperopt  # noqa: F401
        except ImportError as e:
            raise ImportError(
                "HyperOptSearch requires `hyperopt`, which is not "
                "installed in this environment. Use "
                "BasicVariantGenerator (random/grid) instead.") from e
        super().__init__(metric, mode)
        import numpy as np

        self._space = space or {}
        self._rng = np.random.default_rng(random_state_seed)
        self._n_initial = n_initial_points
        self._tid_map: Dict[str, int] = {}
        self._build()

    def _build(self) -> None:
        import hyperopt
        import numpy as np
        from hyperopt import hp

        self._hp_space = {}
        for k, dom in self._space.items():
            if isinstance(dom, Categorical):
                self._hp_space[k] = hp.choice(k, list(dom.categories))
            elif isinstance(dom, Integer):
                self._hp_space[k] = hp.uniformint(k, dom.lower,
                                                  dom.upper - 1)
            elif isinstance(dom, Float):
                if getattr(dom, "log", False):
                    self._hp_space[k] = hp.loguniform(
                        k, np.log(dom.lower), np.log(dom.upper))
                else:
                    self._hp_space[k] = hp.uniform(k, dom.lower, dom.upper)
            else:
                self._hp_space[k] = dom
        self._domain = hyperopt.Domain(lambda c: 0, self._hp_space)
        self._hpopt_trials = hyperopt.Trials()

    def set_search_properties(self, metric, mode, config) -> bool:
        """Adopt the Tuner-supplied metric/mode/param_space (reference:
        hyperopt_search.py set_search_properties)."""
        super().set_search_properties(metric, mode, config)
        if config and not self._space:
            self._space = dict(config)
            self._build()
        return True

    def suggest(self, trial_id: str) -> Optional[Dict]:
        import hyperopt

        new_id = len(self._hpopt_trials.trials)
        seed = int(self._rng.integers(2 ** 31 - 1))
        if new_id < self._n_initial:
            new = hyperopt.rand.suggest([new_id], self._domain,
                                        self._hpopt_trials, seed)
        else:
            new = hyperopt.tpe.suggest([new_id], self._domain,
                                       self._hpopt_trials, seed)
        self._hpopt_trials.insert_trial_docs(new)
        self._hpopt_trials.refresh()
        self._tid_map[trial_id] = new_id
        vals = {k: v[0] for k, v in new[0]["misc"]["vals"].items() if v}
        return hyperopt.space_eval(self._hp_space, vals)

    def on_trial_complete(self, trial_id, result=None,
                          error: bool = False) -> None:
        import hyperopt

        tid = self._tid_map.pop(trial_id, None)
        if tid is None:
            return
        trial = self._hpopt_trials.trials[tid]
        if error or not result or self.metric not in result:
            trial["state"] = hyperopt.JOB_STATE_ERROR
        else:
            val = float(result[self.metric])
            loss = -val if self.mode == "max" else val
            trial["state"] = hyperopt.JOB_STATE_DONE
            trial["result"] = {"loss": loss, "status": "ok"}
        self._hpopt_trials.refresh()
