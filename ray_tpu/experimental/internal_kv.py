"""Cluster-wide KV (reference: python/ray/experimental/internal_kv.py —
the GCS KV the dashboard/serve/autoscaler share)."""

from __future__ import annotations

from typing import List, Optional


def _worker():
    from ray_tpu._private import worker as worker_mod

    w = worker_mod.global_worker
    if w is None or not w.connected:
        raise RuntimeError("ray_tpu.init() must be called first")
    return w


def _kv_initialized() -> bool:
    from ray_tpu._private import worker as worker_mod

    w = worker_mod.global_worker
    return w is not None and w.connected


def _ns(namespace: Optional[bytes]) -> str:
    ns = namespace or b"default"
    return ns.decode() if isinstance(ns, bytes) else ns


def _internal_kv_put(key: bytes, value: bytes, overwrite: bool = True,
                     namespace: Optional[bytes] = None) -> bool:
    # head_call: outage-tolerant — queues behind the head watchdog's
    # reconnect for up to gcs_outage_queue_s during a head bounce, then
    # raises a typed HeadUnavailableError (same for every KV op below)
    w = _worker()
    return w.head_call("KvPut", {
        "ns": _ns(namespace), "key": key, "value": value,
        "overwrite": overwrite})


def _internal_kv_get(key: bytes,
                     namespace: Optional[bytes] = None) -> Optional[bytes]:
    w = _worker()
    out = w.head_call("KvGet", {"ns": _ns(namespace), "key": key})
    return bytes(out) if out is not None else None


def _internal_kv_del(key: bytes,
                     namespace: Optional[bytes] = None) -> int:
    w = _worker()
    return w.head_call("KvDel", {"ns": _ns(namespace), "key": key})


def _internal_kv_exists(key: bytes,
                        namespace: Optional[bytes] = None) -> bool:
    w = _worker()
    return w.head_call("KvExists", {"ns": _ns(namespace), "key": key})


def _internal_kv_list(prefix: bytes,
                      namespace: Optional[bytes] = None) -> List[bytes]:
    w = _worker()
    keys = w.head_call("KvKeys", {"ns": _ns(namespace), "prefix": prefix})
    return [bytes(k) for k in keys]
