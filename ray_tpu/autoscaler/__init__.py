"""Cluster autoscaler (reference: python/ray/autoscaler/ —
``StandardAutoscaler`` _private/autoscaler.py:171, ``Monitor``
_private/monitor.py:126, ``NodeProvider`` ABC node_provider.py, bin-packing
resource_demand_scheduler.py).

TPU-first deviations: demand arrives as per-node ``pending`` lease summaries
in the agents' resource heartbeats (no separate load-metrics pipeline), and
node types model TPU pod slices — a type with ``{"TPU": 4}`` scales in whole
slice-host units, never fractions of a slice.
"""

from ray_tpu.autoscaler.autoscaler import StandardAutoscaler
from ray_tpu.autoscaler.monitor import Monitor
from ray_tpu.autoscaler.node_provider import LocalNodeProvider, NodeProvider
from ray_tpu.autoscaler.sdk import request_resources

__all__ = [
    "StandardAutoscaler",
    "Monitor",
    "NodeProvider",
    "LocalNodeProvider",
    "request_resources",
]
