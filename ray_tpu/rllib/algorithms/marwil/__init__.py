from ray_tpu.rllib.algorithms.marwil.marwil import MARWIL, MARWILConfig

__all__ = ["MARWIL", "MARWILConfig"]
