"""Torch train-loop helpers (reference:
python/ray/train/torch/train_loop_utils.py — ``prepare_model`` wraps in
DDP, ``prepare_data_loader`` adds a DistributedSampler)."""

from __future__ import annotations

from typing import Any


def prepare_model(model: Any, *, wrap_ddp: bool = True) -> Any:
    """Move to the right device and wrap in DDP when distributed."""
    import torch
    import torch.distributed as dist

    device = torch.device("cpu")  # CPU torch image; TPU path is JaxTrainer
    model = model.to(device)
    if wrap_ddp and dist.is_initialized() and dist.get_world_size() > 1:
        from torch.nn.parallel import DistributedDataParallel

        model = DistributedDataParallel(model)
    return model


def prepare_data_loader(data_loader: Any, *, add_dist_sampler: bool = True
                        ) -> Any:
    """Re-create the DataLoader with a DistributedSampler per worker."""
    import torch.distributed as dist
    from torch.utils.data import DataLoader, DistributedSampler

    if not (add_dist_sampler and dist.is_initialized()
            and dist.get_world_size() > 1):
        return data_loader
    sampler = DistributedSampler(data_loader.dataset)
    return DataLoader(
        data_loader.dataset,
        batch_size=data_loader.batch_size,
        sampler=sampler,
        num_workers=0,
        collate_fn=data_loader.collate_fn,
        drop_last=data_loader.drop_last,
    )
