"""The multichip dryrun gate must fail LOUDLY, not silently shrink
(VERDICT r3 weak #6 / next-round #10): if JAX initialized its backend
before `_ensure_virtual_devices` could plant the virtual-device flags, the
gate raises instead of quietly running on fewer devices."""

import os
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def test_ensure_virtual_devices_fails_loudly_when_backend_preinitialized():
    code = (
        "import jax\n"
        "jax.config.update('jax_platforms', 'cpu')\n"
        "jax.config.update('jax_num_cpu_devices', 1)\n"
        "assert len(jax.devices()) == 1  # backend now initialized at 1\n"
        "import __graft_entry__ as g\n"
        "g._ensure_virtual_devices(8)\n"
    )
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    env.pop("XLA_FLAGS", None)
    proc = subprocess.run([sys.executable, "-c", code], env=env, cwd=REPO,
                          capture_output=True, text=True, timeout=300)
    assert proc.returncode != 0, (
        "gate silently accepted a 1-device backend:\n" + proc.stdout)
    assert "could not provision" in (proc.stdout + proc.stderr)
