"""PPO (reference: rllib/algorithms/ppo/ppo.py — PPOConfig + PPO; the
training_step mirrors the new-stack flow: sample fragments from env
runners → GAE → LearnerGroup minibatch-SGD → sync weights).
"""

from __future__ import annotations

from typing import Dict

import numpy as np

import ray_tpu
from ray_tpu.rllib.algorithms.algorithm import Algorithm
from ray_tpu.rllib.algorithms.algorithm_config import AlgorithmConfig
from ray_tpu.rllib.core.learner import PPOLearner
from ray_tpu.rllib.utils.gae import compute_gae


class PPOConfig(AlgorithmConfig):
    def __init__(self, algo_class=None):
        super().__init__(algo_class=algo_class or PPO)
        # PPO-specific knobs (reference: ppo.py PPOConfig.training)
        self.lambda_ = 0.95
        self.clip_param = 0.2
        self.vf_clip_param = 10.0
        self.vf_loss_coeff = 0.5
        self.entropy_coeff = 0.0
        self.use_gae = True

    def _training_keys(self):
        return {"lambda_", "clip_param", "vf_clip_param", "vf_loss_coeff",
                "entropy_coeff", "use_gae"}

    def learner_config_dict(self) -> Dict:
        d = super().learner_config_dict()
        d.update({
            "clip_param": self.clip_param,
            "vf_clip_param": self.vf_clip_param,
            "vf_loss_coeff": self.vf_loss_coeff,
            "entropy_coeff": self.entropy_coeff,
        })
        return d


class PPO(Algorithm):
    learner_cls = PPOLearner

    @classmethod
    def get_default_config(cls):
        return PPOConfig(algo_class=cls)

    def training_step(self) -> Dict:
        cfg = self.config
        weights = self.learner_group.get_weights()
        weights_ref = ray_tpu.put(weights)

        samples = []
        env_steps = 0
        while env_steps < cfg.train_batch_size:
            batch_parts = self._sample_from_runners(weights_ref)
            samples.extend(batch_parts)
            env_steps += sum(s["env_steps"] for s in batch_parts)
            if not batch_parts:
                break

        train_batch = self._postprocess(samples)
        metrics = self.learner_group.update(train_batch)
        metrics["env_steps_this_iter"] = env_steps
        return metrics

    def _postprocess(self, samples) -> Dict[str, np.ndarray]:
        """GAE per fragment, then flatten (T, E) → rows."""
        cfg = self.config
        parts = {k: [] for k in
                 ("obs", "actions", "logp", "advantages", "value_targets")}
        for s in samples:
            adv, vt = compute_gae(
                s["rewards"], s["vf"], s["dones"], s["last_vf"],
                gamma=cfg.gamma, lam=cfg.lambda_)
            flat = lambda a: a.reshape((-1,) + a.shape[2:])
            # drop autoreset transitions (gymnasium next-step autoreset:
            # the action there was ignored by the env)
            mask = flat(s["valid"])
            parts["obs"].append(flat(s["obs"])[mask])
            parts["actions"].append(flat(s["actions"])[mask])
            parts["logp"].append(flat(s["logp"])[mask])
            parts["advantages"].append(flat(adv)[mask])
            parts["value_targets"].append(flat(vt)[mask])
        return {k: np.concatenate(v) for k, v in parts.items()}
