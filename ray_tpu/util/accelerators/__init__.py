"""Accelerator helper constants + TPU pod-slice utilities (reference:
python/ray/util/accelerators/__init__.py + accelerators/tpu.py helpers the
slice-head scheduling docstring points at, _private/accelerators/tpu.py
:366-367)."""

from ray_tpu.util.accelerators.tpu import (
    pod_slice_head_resource,
    pod_slice_resource,
    reserve_tpu_slice,
    slice_hosts,
)

# accelerator type constants (reference: util/accelerators/accelerators.py)
NVIDIA_TESLA_V100 = "V100"
NVIDIA_TESLA_A100 = "A100"
NVIDIA_H100 = "H100"
GOOGLE_TPU_V4 = "TPU-V4"
GOOGLE_TPU_V5E = "TPU-V5E"
GOOGLE_TPU_V5P = "TPU-V5P"
GOOGLE_TPU_V6E = "TPU-V6E"

__all__ = [
    "pod_slice_head_resource", "pod_slice_resource", "reserve_tpu_slice",
    "slice_hosts",
    "NVIDIA_TESLA_V100", "NVIDIA_TESLA_A100", "NVIDIA_H100",
    "GOOGLE_TPU_V4", "GOOGLE_TPU_V5E", "GOOGLE_TPU_V5P", "GOOGLE_TPU_V6E",
]
