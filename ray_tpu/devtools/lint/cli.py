"""CLI for the raylint invariant checker.

Usage:
    python -m ray_tpu.devtools.lint ray_tpu [options]

Exit codes: 0 clean (grandfathered-only is clean), 1 violations or parse
errors (or stale baseline under --strict-baseline), 2 usage error.
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
from typing import List, Optional

from . import baseline as baseline_mod
from .engine import default_baseline_path, run_lint
from .rules import rule_catalog


def _changed_files(project_root: str) -> List[str]:
    """Project-relative .py files touched vs HEAD (staged, unstaged, and
    untracked) — the ``--changed`` report scope."""
    out: List[str] = []
    for cmd in (["git", "diff", "--name-only", "HEAD"],
                ["git", "ls-files", "--others", "--exclude-standard"]):
        try:
            res = subprocess.run(cmd, cwd=project_root, capture_output=True,
                                 text=True, timeout=30)
        except (OSError, subprocess.TimeoutExpired):
            continue
        if res.returncode != 0:
            continue
        for line in res.stdout.splitlines():
            line = line.strip()
            if line.endswith(".py") and line not in out:
                out.append(line)
    return out


def _to_sarif(result) -> dict:
    """SARIF 2.1.0 — one run, one rule descriptor per rule id, one
    result per failing violation (grandfathered hits are omitted: SARIF
    consumers treat every result as actionable)."""
    catalog = {r["id"]: r["summary"] for r in rule_catalog()}
    rule_ids = sorted({v.rule for v in result.violations} | set(catalog))
    return {
        "$schema": ("https://raw.githubusercontent.com/oasis-tcs/"
                    "sarif-spec/master/Schemata/sarif-schema-2.1.0.json"),
        "version": "2.1.0",
        "runs": [{
            "tool": {"driver": {
                "name": "raylint",
                "informationUri": "ray_tpu/devtools/lint",
                "rules": [{"id": rid,
                           "shortDescription":
                               {"text": catalog.get(rid, rid)}}
                          for rid in rule_ids],
            }},
            "results": [{
                "ruleId": v.rule,
                "level": "error",
                "message": {"text": v.message},
                "locations": [{"physicalLocation": {
                    "artifactLocation": {"uri": v.path},
                    "region": {"startLine": v.line,
                               "startColumn": v.col + 1},
                }}],
                "partialFingerprints": {"raylintKey/v1": v.key()},
            } for v in result.violations],
        }],
    }


def _build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="python -m ray_tpu.devtools.lint",
        description=("AST/CFG invariant checker for the ray_tpu runtime's "
                     "concurrency, serialization, and lifecycle "
                     "contracts."))
    p.add_argument("paths", nargs="*", default=["ray_tpu"],
                   help="files/directories to analyze (default: ray_tpu)")
    p.add_argument("--project-root", default=None,
                   help="root for relative paths in reports (default: cwd)")
    p.add_argument("--rules", default=None,
                   help="comma-separated subset, e.g. R1,R4 (default: all)")
    p.add_argument("--format", choices=("text", "json", "sarif"),
                   default="text")
    p.add_argument("--changed", action="store_true",
                   help="report only violations in files changed vs git "
                        "HEAD (plus untracked); the call-graph index "
                        "still covers all of `paths`, so cross-module "
                        "rules keep full precision")
    p.add_argument("--dump-lock-graph", metavar="PATH", default=None,
                   help="also write the R12 static lock-order graph as "
                        "JSON (consumed by the RAY_TPU_SANITIZE=1 "
                        "runtime sanitizer)")
    p.add_argument("--baseline", default=None,
                   help="baseline JSON (default: the checked-in "
                        "devtools/lint/baseline.json)")
    p.add_argument("--no-baseline", action="store_true",
                   help="ignore the baseline: every violation fails")
    p.add_argument("--update-baseline", action="store_true",
                   help="rewrite the baseline to exactly the current "
                        "unsuppressed violations (review the diff: it "
                        "must only shrink)")
    p.add_argument("--strict-baseline", action="store_true",
                   help="also fail on stale baseline entries (used by the "
                        "tier-1 test so the baseline monotonically "
                        "shrinks)")
    p.add_argument("--list-rules", action="store_true")
    return p


def main(argv: Optional[List[str]] = None) -> int:
    args = _build_parser().parse_args(argv)
    if args.list_rules:
        for r in rule_catalog():
            print(f"{r['id']}: {r['summary']}")
        return 0

    rules = ([r.strip() for r in args.rules.split(",") if r.strip()]
             if args.rules else None)
    if rules:
        known = {r["id"] for r in rule_catalog()}
        bad = [r for r in rules if r.upper() not in known]
        if bad:
            print(f"unknown rule(s): {', '.join(bad)} "
                  f"(valid: {', '.join(sorted(known))})", file=sys.stderr)
            return 2
    if args.update_baseline and rules:
        # A subset run only produces that subset's violations; rewriting
        # the baseline from it would silently delete every other rule's
        # grandfathered entries.
        print("--update-baseline requires a full-rule run (drop --rules)",
              file=sys.stderr)
        return 2
    baseline_path = None if args.no_baseline else (
        args.baseline or default_baseline_path())

    report_only = None
    if args.changed:
        root = args.project_root or os.getcwd()
        report_only = _changed_files(root)

    result = run_lint(args.paths, project_root=args.project_root,
                      rules=rules, baseline_path=baseline_path,
                      report_only=report_only)

    if args.dump_lock_graph:
        from . import concurrency
        graph = concurrency.get(result._index).static_graph()
        with open(args.dump_lock_graph, "w", encoding="utf-8") as f:
            json.dump(graph, f, indent=1, sort_keys=True)
            f.write("\n")

    if args.update_baseline:
        target = args.baseline or default_baseline_path()
        entries = baseline_mod.counts(result.violations
                                      + result.grandfathered)
        old = baseline_mod.load(target)
        baseline_mod.save(target, entries)
        grew = sum(entries.values()) > sum(old.values())
        print(f"baseline written: {target} "
              f"({sum(entries.values())} entries, was {sum(old.values())})")
        if grew:
            print("WARNING: baseline GREW — the tier-1 contract only "
                  "allows it to shrink; fix or `# raylint: disable=` new "
                  "violations instead", file=sys.stderr)
        return 0

    if args.format == "json":
        print(json.dumps(result.to_dict(), indent=1))
    elif args.format == "sarif":
        print(json.dumps(_to_sarif(result), indent=1))
    else:
        for v in result.violations:
            print(v.format())
        if result.grandfathered:
            print(f"-- {len(result.grandfathered)} grandfathered "
                  f"violation(s) in the baseline "
                  f"({os.path.basename(baseline_path or '')}); new code "
                  f"must not add to them")
        if result.stale_baseline:
            print(f"-- {len(result.stale_baseline)} stale baseline "
                  f"entr(y/ies) no longer match — shrink with "
                  f"--update-baseline:")
            for k in result.stale_baseline:
                print(f"   {k}")
        for e in result.parse_errors:
            print(f"parse error: {e}", file=sys.stderr)
        print(f"raylint: {result.files_scanned} files, "
              f"{len(result.violations)} failing, "
              f"{len(result.grandfathered)} grandfathered, "
              f"{result.suppressed_count} inline-disabled "
              f"({result.elapsed_s:.2f}s)")

    if result.violations or result.parse_errors:
        return 1
    if args.strict_baseline and result.stale_baseline:
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
