"""GKE REST client for TPU pod-slice node pools.

Production implementation of the ``GkeNodePoolClient`` interface in
``ray_tpu/autoscaler/gke.py`` (VERDICT r2 item 5): builds the actual
`container.googleapis.com` node-pool payloads — machine type, multi-host
``placementPolicy.tpuTopology``, reserved-affinity labels — the way the
reference's GCP provider builds compute payloads
(reference: python/ray/autoscaler/_private/gcp/node_provider.py:1-350,
config.py bootstrap_gcp).

Transport is injected (``request_fn(method, url, body) -> dict``) so the
request/response mapping is unit-testable offline, mirroring how the
reference tests cloud providers without clouds (reference:
python/ray/tests/test_autoscaler_yaml.py, gcp/test fixtures). The default
transport uses urllib with a bearer token from the GCE metadata server or
an injected token provider — no SDK dependency.
"""

from __future__ import annotations

import json
import time
import urllib.error
import urllib.request
from typing import Callable, Dict, List, Optional

from ray_tpu.autoscaler.gke import GkeNodePoolClient, slice_shape

CONTAINER_API = "https://container.googleapis.com/v1"

# topology name -> (GKE machine type, physical chip topology string).
# v5e (ct5lp) topologies are 2-D over 4-chip hosts; v4/v5p (ct4p/ct5p)
# are 3-D. Sources: GKE TPU docs' published machine-type/topology tables
# (mirrored in the reference's accelerator tables,
# python/ray/_private/accelerators/tpu.py pod-type handling).
GKE_TPU_SHAPES: Dict[str, tuple] = {
    "v5e-4": ("ct5lp-hightpu-4t", "2x2"),
    "v5e-8": ("ct5lp-hightpu-4t", "2x4"),
    "v5e-16": ("ct5lp-hightpu-4t", "4x4"),
    "v5e-32": ("ct5lp-hightpu-4t", "4x8"),
    "v5e-64": ("ct5lp-hightpu-4t", "8x8"),
    "v5e-128": ("ct5lp-hightpu-4t", "8x16"),
    "v5e-256": ("ct5lp-hightpu-4t", "16x16"),
    "v5p-8": ("ct5p-hightpu-4t", "2x2x1"),
    "v5p-16": ("ct5p-hightpu-4t", "2x2x2"),
    "v5p-32": ("ct5p-hightpu-4t", "2x2x4"),
    "v4-8": ("ct4p-hightpu-4t", "2x2x1"),
    "v4-16": ("ct4p-hightpu-4t", "2x2x2"),
    "v4-32": ("ct4p-hightpu-4t", "2x2x4"),
}

METADATA_TOKEN_URL = ("http://metadata.google.internal/computeMetadata/v1/"
                      "instance/service-accounts/default/token")


def _metadata_token() -> str:
    req = urllib.request.Request(
        METADATA_TOKEN_URL, headers={"Metadata-Flavor": "Google"})
    with urllib.request.urlopen(req, timeout=5) as resp:
        return json.loads(resp.read())["access_token"]


def default_request_fn(token_provider: Callable[[], str]):
    """urllib transport with bearer auth; raises GkeApiError on HTTP errors."""

    def request(method: str, url: str, body: Optional[Dict]) -> Dict:
        data = json.dumps(body).encode() if body is not None else None
        req = urllib.request.Request(
            url, data=data, method=method,
            headers={"Authorization": f"Bearer {token_provider()}",
                     "Content-Type": "application/json"})
        try:
            with urllib.request.urlopen(req, timeout=60) as resp:
                payload = resp.read()
        except urllib.error.HTTPError as e:
            raise GkeApiError(e.code, e.read().decode(errors="replace"))
        return json.loads(payload) if payload else {}

    return request


class GkeApiError(RuntimeError):
    # quota/stockout markers GKE/Compute surface in error bodies
    _RETRYABLE_MARKERS = ("QUOTA", "RESOURCE_EXHAUSTED", "STOCKOUT",
                          "RESOURCE_AVAILABILITY", "rateLimitExceeded",
                          "GCE_STOCKOUT", "ZONE_RESOURCE_POOL_EXHAUSTED")

    @property
    def retryable(self) -> bool:
        """True for capacity/rate failures that a LATER retry can fix
        (429, 5xx, quota/stockout bodies); False for permanent request
        errors (400 bad topology, 403 missing permission) where hot
        retries would just spam the API."""
        if self.status == 429 or self.status >= 500:
            return True
        return any(m in self.message for m in self._RETRYABLE_MARKERS)

    def __init__(self, status: int, message: str):
        super().__init__(f"GKE API {status}: {message}")
        self.status = status
        self.message = message


class GkeRestClient(GkeNodePoolClient):
    """Slice-atomic node pools against the real GKE API.

    One ray slice == one GKE node pool created with
    ``placementPolicy.tpuTopology`` (GKE then schedules the multi-host
    slice atomically on the physical mesh) and deleted as a unit —
    exactly the invariant ``GkeTpuPodSliceProvider`` needs.
    """

    def __init__(self, project: str, location: str, cluster: str, *,
                 request_fn: Optional[Callable] = None,
                 token_provider: Optional[Callable[[], str]] = None,
                 node_pool_overrides: Optional[Dict] = None,
                 poll_interval: float = 5.0):
        self.project = project
        self.location = location
        self.cluster = cluster
        self.request = request_fn or default_request_fn(
            token_provider or _metadata_token)
        self.node_pool_overrides = node_pool_overrides or {}
        self.poll_interval = poll_interval

    # ------------------------------------------------------------- urls
    @property
    def _cluster_path(self) -> str:
        return (f"projects/{self.project}/locations/{self.location}"
                f"/clusters/{self.cluster}")

    def _pools_url(self) -> str:
        return f"{CONTAINER_API}/{self._cluster_path}/nodePools"

    def _pool_url(self, pool_name: str) -> str:
        return f"{self._pools_url()}/{pool_name}"

    # ---------------------------------------------------------- payloads
    def build_create_request(self, pool_name: str, tpu_topology: str,
                             num_hosts: int, labels: Dict[str, str]) -> Dict:
        """The exact POST body for nodePools.create. Split out from the
        network call so tests can assert the shape offline."""
        if tpu_topology not in GKE_TPU_SHAPES:
            raise ValueError(
                f"no GKE machine shape for topology {tpu_topology!r}; "
                f"known: {sorted(GKE_TPU_SHAPES)}")
        machine_type, chip_topology = GKE_TPU_SHAPES[tpu_topology]
        expected_hosts, _ = slice_shape(tpu_topology)
        if num_hosts != expected_hosts:
            raise ValueError(
                f"{tpu_topology} is a {expected_hosts}-host slice; "
                f"got num_hosts={num_hosts}")
        config: Dict = {
            "machineType": machine_type,
            "labels": {
                # GKE label values: lowercase alphanumerics + -_ only
                k: str(v).lower().replace(":", "-") for k, v in
                labels.items()},
            # the per-pool service scope the kubelet needs to pull images
            "oauthScopes": [
                "https://www.googleapis.com/auth/cloud-platform"],
        }
        config.update(self.node_pool_overrides.get("config", {}))
        node_pool: Dict = {
            "name": pool_name,
            "initialNodeCount": num_hosts,
            "config": config,
            # slice-atomic placement: GKE provisions the hosts on one
            # physical TPU mesh or not at all
            "placementPolicy": {"type": "COMPACT",
                                "tpuTopology": chip_topology},
            "management": {"autoRepair": False, "autoUpgrade": False},
            # a lost host invalidates the slice ICI mesh: never let GKE
            # resize below/above the slice host count
            "autoscaling": {"enabled": False},
        }
        for k, v in self.node_pool_overrides.items():
            if k != "config":
                node_pool[k] = v
        return {"nodePool": node_pool, "parent": self._cluster_path}

    # ------------------------------------------------- GkeNodePoolClient
    def create_tpu_node_pool(self, pool_name: str, tpu_topology: str,
                             num_hosts: int, per_host_resources: Dict,
                             labels: Dict[str, str],
                             head_resources: Dict) -> None:
        body = self.build_create_request(
            pool_name, tpu_topology, num_hosts, labels)
        op = self.request("POST", self._pools_url(), body)
        self._wait_operation(op)

    def delete_node_pool(self, pool_name: str) -> None:
        try:
            op = self.request("DELETE", self._pool_url(pool_name), None)
        except GkeApiError as e:
            if e.status == 404:  # already gone — deletion is idempotent
                return
            raise
        self._wait_operation(op)

    def pool_runtime_node_ids(self, pool_name: str) -> List[str]:
        """GKE names slice nodes gke-<cluster>-<pool>-<hash>; the agents
        register those INSTANCE NAMES as runtime node ids via the
        downward API. The pool only exposes instanceGroupUrls (one
        managed group per zone), so membership comes from each group's
        compute listManagedInstances call — returning the URLs themselves
        would never match a registered node id and the autoscaler would
        boot-timeout every healthy slice."""
        try:
            pool = self.request("GET", self._pool_url(pool_name), None)
        except GkeApiError as e:
            if e.status == 404:
                return []
            raise
        if pool.get("status") not in ("RUNNING", "RECONCILING"):
            return []
        names: List[str] = []
        for ig_url in pool.get("instanceGroupUrls", []):
            # instanceGroupManagers/<name> URL -> listManagedInstances
            try:
                reply = self.request(
                    "POST", f"{ig_url}/listManagedInstances", None)
            except GkeApiError as e:
                # only a group that does not exist YET is benign; a
                # persistent failure (403 missing compute permission, …)
                # must surface, or the autoscaler boot-timeouts healthy
                # slices forever on an empty membership list
                if e.status in (404, 409, 503):
                    continue  # group still materializing
                raise
            for inst in reply.get("managedInstances", []):
                url = inst.get("instance", "")
                if url and inst.get("instanceStatus") in (
                        "RUNNING", None):
                    names.append(url.rsplit("/", 1)[-1])
        return names

    # ------------------------------------------------------- operations
    def _operation_url(self, op: Dict) -> Optional[str]:
        if "selfLink" in op:
            return op["selfLink"]
        name = op.get("name")
        if not name:
            return None
        return (f"{CONTAINER_API}/projects/{self.project}/locations/"
                f"{self.location}/operations/{name}")

    def _wait_operation(self, op: Dict, timeout: float = 1800.0) -> None:
        url = self._operation_url(op)
        if url is None:
            return
        deadline = time.monotonic() + timeout
        while op.get("status") not in ("DONE", None):
            if time.monotonic() > deadline:
                raise TimeoutError(
                    f"GKE operation {op.get('name')} not DONE in "
                    f"{timeout}s (status={op.get('status')})")
            time.sleep(self.poll_interval)
            op = self.request("GET", url, None)
        err = op.get("error")
        if err:
            raise GkeApiError(int(err.get("code", 500)),
                              json.dumps(err))
