"""Autoscaler tests (reference parity: python/ray/tests/test_autoscaler.py
and test_autoscaling_cluster — scale-up on demand, min_workers, idle
scale-down, bin-packing unit tests)."""

import time

import pytest

from ray_tpu._private.resources import ResourceSet
from ray_tpu.autoscaler.resource_demand_scheduler import get_nodes_to_launch


def _w(d):
    return ResourceSet(d).to_wire()


class TestBinPacking:
    NODE_TYPES = {
        "cpu4": {"resources": {"CPU": 4}, "max_workers": 10},
        "tpu_slice": {"resources": {"TPU": 4, "CPU": 8}, "max_workers": 4},
    }

    def test_no_demand_no_launch(self):
        assert get_nodes_to_launch(self.NODE_TYPES, [], [], {}, 8, 0) == {}

    def test_demand_fits_existing(self):
        out = get_nodes_to_launch(
            self.NODE_TYPES, [_w({"CPU": 2})], [_w({"CPU": 4})], {}, 8, 1)
        assert out == {}

    def test_launch_for_unfulfilled(self):
        out = get_nodes_to_launch(
            self.NODE_TYPES, [_w({"CPU": 2})], [], {}, 8, 0)
        assert out == {"cpu4": 1}

    def test_pack_multiple_onto_one_node(self):
        out = get_nodes_to_launch(
            self.NODE_TYPES, [_w({"CPU": 2})] * 2, [], {}, 8, 0)
        assert out == {"cpu4": 1}

    def test_tpu_demand_picks_tpu_type(self):
        out = get_nodes_to_launch(
            self.NODE_TYPES, [_w({"TPU": 4})], [_w({"CPU": 4})], {}, 8, 1)
        assert out == {"tpu_slice": 1}

    def test_max_workers_cap(self):
        out = get_nodes_to_launch(
            self.NODE_TYPES, [_w({"CPU": 4})] * 5, [], {}, 2, 0)
        assert sum(out.values()) <= 2

    def test_infeasible_demand_ignored(self):
        out = get_nodes_to_launch(
            self.NODE_TYPES, [_w({"GPU": 1})], [], {}, 8, 0)
        assert out == {}

    def test_per_type_max(self):
        types = {"cpu4": {"resources": {"CPU": 4}, "max_workers": 1}}
        out = get_nodes_to_launch(
            types, [_w({"CPU": 4})] * 3, [], {}, 8, 0)
        assert out == {"cpu4": 1}


class TestAutoscalingCluster:
    def test_scale_up_and_down(self):
        import ray_tpu
        from ray_tpu.cluster_utils import AutoscalingCluster

        cluster = AutoscalingCluster(
            head_resources={"CPU": 1},
            worker_node_types={
                "worker": {"resources": {"CPU": 2, "extra": 2},
                           "min_workers": 0, "max_workers": 2},
            },
            idle_timeout_minutes=0.03,  # ~2s
            update_interval_s=0.3,
        )
        cluster.start()
        try:
            ray_tpu.init(address=cluster.address)

            @ray_tpu.remote(resources={"extra": 1})
            def on_worker():
                return "scaled"

            # no worker node exists yet: this demand must trigger scale-up
            assert ray_tpu.get(on_worker.remote(), timeout=120) == "scaled"
            assert cluster.provider.non_terminated_nodes()

            # idle: the worker node should be terminated after the timeout
            deadline = time.monotonic() + 60
            while time.monotonic() < deadline:
                if not cluster.provider.non_terminated_nodes():
                    break
                time.sleep(0.5)
            assert not cluster.provider.non_terminated_nodes(), \
                "idle node was not scaled down"
        finally:
            ray_tpu.shutdown()
            cluster.shutdown()

    def test_min_workers_maintained(self):
        import ray_tpu
        from ray_tpu.cluster_utils import AutoscalingCluster

        cluster = AutoscalingCluster(
            head_resources={"CPU": 1},
            worker_node_types={
                "worker": {"resources": {"CPU": 2},
                           "min_workers": 1, "max_workers": 2},
            },
            idle_timeout_minutes=0.02,
            update_interval_s=0.3,
        )
        cluster.start()
        try:
            ray_tpu.init(address=cluster.address)
            deadline = time.monotonic() + 60
            while time.monotonic() < deadline:
                if len(cluster.provider.non_terminated_nodes()) >= 1:
                    break
                time.sleep(0.5)
            assert len(cluster.provider.non_terminated_nodes()) >= 1
            # idle min_workers node must NOT be reclaimed
            time.sleep(3)
            assert len(cluster.provider.non_terminated_nodes()) >= 1
        finally:
            ray_tpu.shutdown()
            cluster.shutdown()
