"""Grid/random variant generation (reference:
python/ray/tune/search/basic_variant.py + variant_generator.py).

Expands every ``grid_search`` marker exhaustively (cross product), samples
every ``Domain`` leaf, repeats the whole expansion ``num_samples`` times.
"""

from __future__ import annotations

import itertools
import random
from typing import Any, Dict, List, Optional, Tuple

from ray_tpu.tune.search.sample import Domain
from ray_tpu.tune.search.searcher import Searcher


def _is_grid(v) -> bool:
    return isinstance(v, dict) and set(v.keys()) == {"grid_search"}


def _walk(prefix: Tuple, spec: Any):
    """Yield (path, leaf) for grid/domain leaves; nested dicts recursed."""
    if _is_grid(spec) or isinstance(spec, Domain):
        yield prefix, spec
    elif isinstance(spec, dict):
        for k, v in spec.items():
            yield from _walk(prefix + (k,), v)


def _set_path(d: Dict, path: Tuple, value) -> None:
    for k in path[:-1]:
        d = d.setdefault(k, {})
    d[path[-1]] = value


def _deepcopy_spec(spec):
    if isinstance(spec, dict):
        return {k: _deepcopy_spec(v) for k, v in spec.items()}
    return spec


def generate_variants(space: Dict, num_samples: int,
                      rng: random.Random) -> List[Dict]:
    """All resolved configs for the space (grid × num_samples)."""
    grid_leaves = []
    domain_leaves = []
    for path, leaf in _walk((), space):
        if _is_grid(leaf):
            grid_leaves.append((path, leaf["grid_search"]))
        else:
            domain_leaves.append((path, leaf))

    grid_combos = (list(itertools.product(*[vals for _, vals in grid_leaves]))
                   if grid_leaves else [()])
    out = []
    for _ in range(num_samples):
        for combo in grid_combos:
            cfg = _deepcopy_spec(space)
            for (path, _), val in zip(grid_leaves, combo):
                _set_path(cfg, path, val)
            for path, dom in domain_leaves:
                _set_path(cfg, path, dom.sample(rng))
            out.append(cfg)
    return out


class BasicVariantGenerator(Searcher):
    """The default searcher: pre-expands the whole space
    (reference: basic_variant.py:43)."""

    def __init__(self, space: Optional[Dict] = None, num_samples: int = 1,
                 seed: Optional[int] = None,
                 points_to_evaluate: Optional[List[Dict]] = None):
        super().__init__()
        self._space = space or {}
        self._num_samples = num_samples
        self._rng = random.Random(seed)
        self._points = list(points_to_evaluate or [])
        self._queue: Optional[List[Dict]] = None

    def set_search_properties(self, metric, mode, config) -> bool:
        super().set_search_properties(metric, mode, config)
        if config and not self._space:
            self._space = config
        return True

    def _ensure_expanded(self) -> None:
        if self._queue is None:
            self._queue = self._points + generate_variants(
                self._space, self._num_samples, self._rng)

    def suggest(self, trial_id: str) -> Optional[Dict]:
        self._ensure_expanded()
        if not self._queue:
            return Searcher.FINISHED
        return self._queue.pop(0)
