// Shared-memory object store kernel (plasma analog, C++ native).
//
// Behavioral parity with the reference's plasma store
// (reference: src/ray/object_manager/plasma/store.h:55, dlmalloc.cc,
// object_lifecycle_manager.h, eviction_policy.h): one mmap'd shared-memory
// arena per node holding immutable sealed objects, an object table shared by
// every process on the node, LRU eviction of unpinned sealed objects, and
// create/seal/get/release/delete lifecycle.
//
// Where the reference runs a store *server* thread inside the raylet and
// clients talk to it over a unix socket with fd-passing (plasma/fling.cc),
// this design is TPU-first and kernel-bypass: the whole store state (object
// table + heap allocator + robust mutex) lives inside the shm segment itself,
// so every client attaches the segment once and then performs create / seal /
// lookup directly in shared memory with no per-operation IPC round trip.
// Readers get zero-copy pointers into the arena, which feed
// jax.device_put -> HBM with no intermediate host copy.
//
// Exposed as a plain C ABI consumed from Python via ctypes
// (ray_tpu/_native/__init__.py).

#include <atomic>
#include <cerrno>
#include <cstdint>
#include <cstring>

#include <fcntl.h>
#include <pthread.h>
#include <signal.h>
#include <sys/mman.h>
#include <sys/stat.h>
#include <unistd.h>

namespace {

constexpr uint64_t kMagic = 0x7261795f74707532ULL;  // "ray_tpu2" (v2: Slot.creator_pid)
constexpr uint32_t kIdSize = 20;                    // ObjectID width (ids.py: task id 16B + return index 4B)
constexpr uint64_t kAlign = 64;                     // cache-line alignment

// ---------------------------------------------------------------- layout

// Object table slot states.
enum SlotState : uint32_t {
  SLOT_EMPTY = 0,
  SLOT_CREATED = 1,   // allocated, writer filling it in
  SLOT_SEALED = 2,    // immutable, readable
  SLOT_TOMBSTONE = 3, // deleted, probe chain continues through it
};

struct Slot {
  uint8_t id[kIdSize];
  uint64_t offset;  // data offset from heap base
  uint64_t size;
  uint64_t lru;     // last-touch clock tick
  uint32_t state;
  int32_t pincount;
  int32_t creator_pid;  // writer filling a CREATED slot (robust-recovery
                        // sweep reclaims slots of dead creators)
};

// Free-list block header, lives in the heap itself (boundary-tag allocator).
struct Block {
  uint64_t size;       // payload bytes (excluding header)
  uint64_t prev_size;  // payload of physically-previous block (0 if first)
  uint32_t free_;      // 1 if on the free list
  uint32_t last;       // 1 if physically last block in heap
  // Free blocks thread a doubly-linked list through their payload:
  // payload[0..8) = next free offset, payload[8..16) = prev free offset
};

constexpr uint64_t kNoBlock = ~0ULL;

struct Header {
  uint64_t magic;
  uint64_t segment_size;
  uint64_t capacity;        // heap payload capacity
  uint64_t used;            // sealed+created payload bytes
  uint64_t table_slots;     // power of two
  uint64_t table_offset;    // from segment base
  uint64_t heap_offset;     // from segment base
  uint64_t free_head;       // offset of first free block header (kNoBlock if none)
  uint64_t lru_clock;
  uint64_t num_objects;
  uint64_t num_evictions;
  uint64_t num_created;
  pthread_mutex_t mutex;    // robust, process-shared
};

struct Store {
  uint8_t* base;
  uint64_t mapped_size;
  Header* hdr;
  Slot* table;
  uint8_t* heap;
};

// ---------------------------------------------------------------- helpers

inline uint64_t align_up(uint64_t v, uint64_t a) { return (v + a - 1) & ~(a - 1); }

inline uint64_t id_hash(const uint8_t* id) {
  // FNV-1a over the id bytes.
  uint64_t h = 1469598103934665603ULL;
  for (uint32_t i = 0; i < kIdSize; i++) {
    h ^= id[i];
    h *= 1099511628211ULL;
  }
  return h;
}

inline Block* block_at(Store* s, uint64_t off) {
  return reinterpret_cast<Block*>(s->heap + off);
}

inline uint64_t* free_next(Store* s, uint64_t off) {
  return reinterpret_cast<uint64_t*>(s->heap + off + sizeof(Block));
}
inline uint64_t* free_prev(Store* s, uint64_t off) {
  return reinterpret_cast<uint64_t*>(s->heap + off + sizeof(Block) + 8);
}

void freelist_remove(Store* s, uint64_t off) {
  uint64_t nxt = *free_next(s, off);
  uint64_t prv = *free_prev(s, off);
  if (prv == kNoBlock) {
    s->hdr->free_head = nxt;
  } else {
    *free_next(s, prv) = nxt;
  }
  if (nxt != kNoBlock) *free_prev(s, nxt) = prv;
  block_at(s, off)->free_ = 0;
}

void freelist_push(Store* s, uint64_t off) {
  Block* b = block_at(s, off);
  b->free_ = 1;
  *free_next(s, off) = s->hdr->free_head;
  *free_prev(s, off) = kNoBlock;
  if (s->hdr->free_head != kNoBlock) *free_prev(s, s->hdr->free_head) = off;
  s->hdr->free_head = off;
}

// Merge a just-freed block with free physical neighbours. `off` must not be on
// the free list yet; returns the offset of the coalesced block (also not on
// the free list).
uint64_t coalesce(Store* s, uint64_t off) {
  Block* b = block_at(s, off);
  // merge right
  if (!b->last) {
    uint64_t roff = off + sizeof(Block) + b->size;
    Block* r = block_at(s, roff);
    if (r->free_) {
      freelist_remove(s, roff);
      b->size += sizeof(Block) + r->size;
      b->last = r->last;
      if (!b->last) {
        uint64_t rr = off + sizeof(Block) + b->size;
        block_at(s, rr)->prev_size = b->size;
      }
    }
  }
  // merge left
  if (b->prev_size != 0 || off != 0) {
    if (off != 0) {
      uint64_t loff = off - sizeof(Block) - b->prev_size;
      Block* l = block_at(s, loff);
      if (l->free_) {
        freelist_remove(s, loff);
        l->size += sizeof(Block) + b->size;
        l->last = b->last;
        if (!l->last) {
          uint64_t rr = loff + sizeof(Block) + l->size;
          block_at(s, rr)->prev_size = l->size;
        }
        return loff;
      }
    }
  }
  return off;
}

// First-fit allocation; returns payload offset or kNoBlock.
uint64_t heap_alloc(Store* s, uint64_t want) {
  want = align_up(want ? want : 1, kAlign);
  uint64_t off = s->hdr->free_head;
  while (off != kNoBlock) {
    Block* b = block_at(s, off);
    uint64_t nxt = *free_next(s, off);
    if (b->size >= want) {
      freelist_remove(s, off);
      // split if the remainder can hold a useful block
      if (b->size >= want + sizeof(Block) + kAlign) {
        uint64_t rest_off = off + sizeof(Block) + want;
        Block* rest = block_at(s, rest_off);
        rest->size = b->size - want - sizeof(Block);
        rest->prev_size = want;
        rest->last = b->last;
        b->size = want;
        b->last = 0;
        if (!rest->last) {
          uint64_t rr = rest_off + sizeof(Block) + rest->size;
          block_at(s, rr)->prev_size = rest->size;
        }
        freelist_push(s, rest_off);
      }
      return off + sizeof(Block);
    }
    off = nxt;
  }
  return kNoBlock;
}

void heap_free(Store* s, uint64_t payload_off) {
  uint64_t off = payload_off - sizeof(Block);
  uint64_t merged = coalesce(s, off);
  freelist_push(s, merged);
}

// ------------------------------------------------------------ table ops

Slot* table_find(Store* s, const uint8_t* id) {
  uint64_t mask = s->hdr->table_slots - 1;
  uint64_t i = id_hash(id) & mask;
  for (uint64_t probes = 0; probes <= mask; probes++, i = (i + 1) & mask) {
    Slot* slot = &s->table[i];
    if (slot->state == SLOT_EMPTY) return nullptr;
    if (slot->state != SLOT_TOMBSTONE && memcmp(slot->id, id, kIdSize) == 0)
      return slot;
  }
  return nullptr;
}

Slot* table_insert(Store* s, const uint8_t* id) {
  uint64_t mask = s->hdr->table_slots - 1;
  uint64_t i = id_hash(id) & mask;
  Slot* first_tomb = nullptr;
  for (uint64_t probes = 0; probes <= mask; probes++, i = (i + 1) & mask) {
    Slot* slot = &s->table[i];
    if (slot->state == SLOT_EMPTY) return first_tomb ? first_tomb : slot;
    if (slot->state == SLOT_TOMBSTONE) {
      if (!first_tomb) first_tomb = slot;
      continue;
    }
    if (memcmp(slot->id, id, kIdSize) == 0) return nullptr;  // exists
  }
  return first_tomb;  // table full unless a tombstone was seen
}

void delete_slot(Store* s, Slot* slot) {
  heap_free(s, slot->offset);
  s->hdr->used -= slot->size;
  s->hdr->num_objects--;
  slot->state = SLOT_TOMBSTONE;
  slot->pincount = 0;
}

// Evict unpinned sealed objects, oldest LRU tick first, until `need` payload
// bytes could plausibly be allocated. Mirrors plasma's EvictionPolicy
// (reference: src/ray/object_manager/plasma/eviction_policy.h).
bool evict_for(Store* s, uint64_t need) {
  for (;;) {
    if (s->hdr->used + need <= s->hdr->capacity) {
      // logical capacity ok — probe whether the free list can satisfy it
      uint64_t off = heap_alloc(s, need);
      if (off != kNoBlock) {
        heap_free(s, off);  // probe only
        return true;
      }
    }
    Slot* victim = nullptr;
    for (uint64_t i = 0; i < s->hdr->table_slots; i++) {
      Slot* slot = &s->table[i];
      if (slot->state == SLOT_SEALED && slot->pincount == 0 &&
          (!victim || slot->lru < victim->lru))
        victim = slot;
    }
    if (!victim) return false;
    delete_slot(s, victim);
    s->hdr->num_evictions++;
  }
}

// Repair shared state after a writer died holding the lock. Must run with
// the (now-consistent) mutex held. Two hazards are repairable from the
// block/slot metadata: (a) CREATED slots whose writer is gone — their heap
// space would leak forever; (b) a free list left mid-splice — the links
// are rebuilt from the per-block `free_` boundary tags, which every path
// updates before touching links. (A death INSIDE the two-word link write
// itself can still lose a block to the list until the next rebuild —
// bounded leak, never corruption of sealed payloads.)
void repair_after_owner_death(Store* s) {
  // (a) rebuild the free list from boundary tags FIRST: the dead writer
  // may have left the link words mid-splice, and the sweep below walks
  // delete_slot -> heap_free -> coalesce -> freelist_remove THROUGH them
  s->hdr->free_head = kNoBlock;
  uint64_t off = 0;
  uint64_t prev_free = kNoBlock;
  for (;;) {
    Block* b = block_at(s, off);
    if (b->free_) {
      *free_prev(s, off) = prev_free;
      *free_next(s, off) = kNoBlock;
      if (prev_free == kNoBlock)
        s->hdr->free_head = off;
      else
        *free_next(s, prev_free) = off;
      prev_free = off;
    }
    if (b->last) break;
    off += sizeof(Block) + align_up(b->size, kAlign);
  }
  // (b) sweep CREATED slots of dead writers (their heap space would
  // otherwise leak forever); the free list is now consistent
  for (uint64_t i = 0; i < s->hdr->table_slots; i++) {
    Slot* slot = &s->table[i];
    if (slot->state == SLOT_CREATED && slot->creator_pid > 0 &&
        kill(slot->creator_pid, 0) != 0 && errno == ESRCH) {
      delete_slot(s, slot);
    }
  }
}

struct MutexGuard {
  Store* s;
  explicit MutexGuard(Store* st) : s(st) {
    int rc = pthread_mutex_lock(&s->hdr->mutex);
    if (rc == EOWNERDEAD) {
      pthread_mutex_consistent(&s->hdr->mutex);  // robust recovery
      repair_after_owner_death(s);
    }
  }
  ~MutexGuard() { pthread_mutex_unlock(&s->hdr->mutex); }
};

}  // namespace

// ================================================================= C ABI

extern "C" {

// Create a new store segment at `path` with `capacity` payload bytes.
// Returns an opaque handle or nullptr.
void* tpu_store_create(const char* path, uint64_t capacity) {
  uint64_t table_slots = 4096;
  while (table_slots < capacity / (64 * 1024) && table_slots < (1ULL << 22))
    table_slots <<= 1;

  uint64_t table_bytes = table_slots * sizeof(Slot);
  uint64_t table_offset = align_up(sizeof(Header), kAlign);
  uint64_t heap_offset = align_up(table_offset + table_bytes, kAlign);
  // heap needs room for block headers too; pad by 1/32 + fixed slack
  uint64_t heap_bytes = capacity + capacity / 32 + (1 << 20);
  uint64_t segment_size = heap_offset + heap_bytes;

  int fd = open(path, O_CREAT | O_RDWR | O_EXCL, 0600);
  if (fd < 0) return nullptr;
  if (ftruncate(fd, (off_t)segment_size) != 0) {
    close(fd);
    unlink(path);
    return nullptr;
  }
  void* base =
      mmap(nullptr, segment_size, PROT_READ | PROT_WRITE, MAP_SHARED, fd, 0);
  close(fd);
  if (base == MAP_FAILED) {
    unlink(path);
    return nullptr;
  }

  Store* s = new Store();
  s->base = static_cast<uint8_t*>(base);
  s->mapped_size = segment_size;
  s->hdr = reinterpret_cast<Header*>(s->base);
  s->table = reinterpret_cast<Slot*>(s->base + table_offset);
  s->heap = s->base + heap_offset;

  Header* h = s->hdr;
  memset(h, 0, sizeof(Header));
  h->segment_size = segment_size;
  h->capacity = capacity;
  h->table_slots = table_slots;
  h->table_offset = table_offset;
  h->heap_offset = heap_offset;
  memset(s->table, 0, table_bytes);

  // one giant free block spanning the heap
  Block* b0 = block_at(s, 0);
  b0->size = heap_bytes - sizeof(Block);
  b0->prev_size = 0;
  b0->last = 1;
  h->free_head = kNoBlock;
  freelist_push(s, 0);

  pthread_mutexattr_t attr;
  pthread_mutexattr_init(&attr);
  pthread_mutexattr_setpshared(&attr, PTHREAD_PROCESS_SHARED);
  pthread_mutexattr_setrobust(&attr, PTHREAD_MUTEX_ROBUST);
  pthread_mutex_init(&h->mutex, &attr);
  pthread_mutexattr_destroy(&attr);

  std::atomic_thread_fence(std::memory_order_seq_cst);
  h->magic = kMagic;  // publish: attachers spin on magic
  return s;
}

// Attach to an existing segment. Returns handle or nullptr.
void* tpu_store_attach(const char* path) {
  int fd = open(path, O_RDWR);
  if (fd < 0) return nullptr;
  struct stat st;
  if (fstat(fd, &st) != 0 || st.st_size < (off_t)sizeof(Header)) {
    close(fd);
    return nullptr;
  }
  void* base =
      mmap(nullptr, (size_t)st.st_size, PROT_READ | PROT_WRITE, MAP_SHARED, fd, 0);
  close(fd);
  if (base == MAP_FAILED) return nullptr;
  Header* h = reinterpret_cast<Header*>(base);
  if (h->magic != kMagic || h->segment_size != (uint64_t)st.st_size) {
    munmap(base, (size_t)st.st_size);
    return nullptr;
  }
  Store* s = new Store();
  s->base = static_cast<uint8_t*>(base);
  s->mapped_size = (uint64_t)st.st_size;
  s->hdr = h;
  s->table = reinterpret_cast<Slot*>(s->base + h->table_offset);
  s->heap = s->base + h->heap_offset;
  return s;
}

void tpu_store_detach(void* handle) {
  Store* s = static_cast<Store*>(handle);
  munmap(s->base, s->mapped_size);
  delete s;
}

// Base pointer of the mapping (python computes buffer offsets against it).
uint8_t* tpu_store_base(void* handle) {
  return static_cast<Store*>(handle)->base;
}

// Allocate an unsealed object. Returns absolute offset of the payload from
// the segment base, or 0 on failure (0 is never a valid payload offset).
uint64_t tpu_store_create_object(void* handle, const uint8_t* id, uint64_t size) {
  Store* s = static_cast<Store*>(handle);
  MutexGuard g(s);
  if (size > s->hdr->capacity) return 0;
  Slot* slot = table_insert(s, id);
  if (!slot) return 0;  // duplicate or table full
  if (!evict_for(s, size)) return 0;
  uint64_t off = heap_alloc(s, size);
  if (off == kNoBlock) return 0;
  memcpy(slot->id, id, kIdSize);
  slot->offset = off;
  slot->size = size;
  slot->lru = ++s->hdr->lru_clock;
  slot->state = SLOT_CREATED;
  slot->pincount = 0;
  slot->creator_pid = static_cast<int32_t>(getpid());
  s->hdr->used += size;
  s->hdr->num_objects++;
  s->hdr->num_created++;
  return s->hdr->heap_offset + off;
}

int tpu_store_seal(void* handle, const uint8_t* id) {
  Store* s = static_cast<Store*>(handle);
  MutexGuard g(s);
  Slot* slot = table_find(s, id);
  if (!slot || slot->state != SLOT_CREATED) return -1;
  std::atomic_thread_fence(std::memory_order_release);
  slot->state = SLOT_SEALED;
  return 0;
}

int tpu_store_abort(void* handle, const uint8_t* id) {
  Store* s = static_cast<Store*>(handle);
  MutexGuard g(s);
  Slot* slot = table_find(s, id);
  if (!slot || slot->state != SLOT_CREATED) return -1;
  delete_slot(s, slot);
  return 0;
}

// Look up a sealed object; pins it (caller must release). Writes the payload
// absolute offset and size. Returns 0 on hit, -1 on miss.
int tpu_store_get(void* handle, const uint8_t* id, uint64_t* offset_out,
                  uint64_t* size_out) {
  Store* s = static_cast<Store*>(handle);
  MutexGuard g(s);
  Slot* slot = table_find(s, id);
  if (!slot || slot->state != SLOT_SEALED) return -1;
  slot->lru = ++s->hdr->lru_clock;
  slot->pincount++;
  *offset_out = s->hdr->heap_offset + slot->offset;
  *size_out = slot->size;
  return 0;
}

int tpu_store_contains(void* handle, const uint8_t* id) {
  Store* s = static_cast<Store*>(handle);
  MutexGuard g(s);
  Slot* slot = table_find(s, id);
  return (slot && slot->state == SLOT_SEALED) ? 1 : 0;
}

int tpu_store_release(void* handle, const uint8_t* id) {
  Store* s = static_cast<Store*>(handle);
  MutexGuard g(s);
  Slot* slot = table_find(s, id);
  if (!slot) return -1;
  if (slot->pincount > 0) slot->pincount--;
  return 0;
}

int tpu_store_delete(void* handle, const uint8_t* id) {
  Store* s = static_cast<Store*>(handle);
  MutexGuard g(s);
  Slot* slot = table_find(s, id);
  if (!slot || slot->state == SLOT_TOMBSTONE) return -1;
  if (slot->pincount > 0) return -2;  // pinned: caller defers
  delete_slot(s, slot);
  return 0;
}

void tpu_store_stats(void* handle, uint64_t* out /* [6] */) {
  Store* s = static_cast<Store*>(handle);
  MutexGuard g(s);
  out[0] = s->hdr->used;
  out[1] = s->hdr->capacity;
  out[2] = s->hdr->num_objects;
  out[3] = s->hdr->num_evictions;
  out[4] = s->hdr->num_created;
  out[5] = s->hdr->lru_clock;
}

// List ids of sealed, unpinned objects (spill candidates), oldest first.
// Fills up to max ids into out (contiguous 16-byte records); returns count.
int tpu_store_lru_candidates(void* handle, uint8_t* out, int max) {
  Store* s = static_cast<Store*>(handle);
  MutexGuard g(s);
  // selection sort over at most `max` winners (table scan is the cost anyway)
  int n = 0;
  uint64_t last_lru = 0;
  while (n < max) {
    Slot* best = nullptr;
    for (uint64_t i = 0; i < s->hdr->table_slots; i++) {
      Slot* slot = &s->table[i];
      if (slot->state == SLOT_SEALED && slot->pincount == 0 &&
          slot->lru > last_lru && (!best || slot->lru < best->lru))
        best = slot;
    }
    if (!best) break;
    memcpy(out + n * kIdSize, best->id, kIdSize);
    last_lru = best->lru;
    n++;
  }
  return n;
}

// TEST-ONLY: acquire the segment mutex and return WITHOUT releasing, so a
// test child can _exit() while holding it — the only way to exercise the
// EOWNERDEAD robust-recovery path (repair_after_owner_death) for real
// (reference analog: plasma's unit-test fault hooks).
int tpu_store_test_lock_and_leak(void* handle) {
  Store* s = static_cast<Store*>(handle);
  return pthread_mutex_lock(&s->hdr->mutex);
}

}  // extern "C"
