"""ARS — augmented random search (reference: rllib/algorithms/ars/ars.py,
externalized to rllib_contrib in the snapshot; Mania 2018, the V2-t
variant: observation normalization, top-b direction selection, and
reward-std-scaled steps on top of ES's antithetic perturbation loop).

Shares ES's driver-side architecture (no learner group — runners only
evaluate candidates); the three ARS augmentations live here:

- a running observation filter (mean/var over every state the candidates
  visit) applied inside the policy module, so whitening travels with the
  weights to the env runners instead of needing stateful runners;
- only the ``top_directions`` best perturbation pairs (by max of the pair)
  contribute to the update;
- the step is divided by the stdev of the rewards actually used, making
  the step size scale-free across tasks.
"""

from __future__ import annotations

import dataclasses
from typing import Dict

import jax
import jax.numpy as jnp
import numpy as np

import ray_tpu
from ray_tpu.rllib.algorithms.es.es import ES, ESConfig


@dataclasses.dataclass
class ARSModuleSpec:
    """Wraps the catalog module with observation whitening. The filter
    stats ride the weights pytree (stop-gradient by construction: they are
    never part of the perturbed parameter vector)."""

    inner: object  # RLModuleSpec

    @property
    def discrete(self) -> bool:
        return self.inner.discrete

    @property
    def action_dim(self) -> int:
        return self.inner.action_dim

    def build(self) -> "ARSModule":
        return ARSModule(self)


class ARSModule:
    CLIP = 5.0  # whitened-obs clip (Mania 2018 uses the same guard)

    def __init__(self, spec: ARSModuleSpec):
        self.spec = spec
        self.inner = spec.inner.build()

    @property
    def dist(self):
        return self.inner.dist

    def init(self, rng):
        return self.inner.init(rng)

    def _whiten(self, weights, obs):
        f = weights["filter"]
        z = (obs - f["mu"]) / jnp.sqrt(f["var"] + 1e-8)
        return jnp.clip(z, -self.CLIP, self.CLIP)

    def forward(self, weights, obs):
        return self.inner.forward(weights["inner"],
                                  self._whiten(weights, obs))

    def explore_action(self, weights, obs, rng):
        return self.inner.explore_action(weights["inner"],
                                         self._whiten(weights, obs), rng)

    # no greedy_action: the runner's argmax-on-forward fallback handles
    # deterministic evaluation, and forward() already whitens


class ARSConfig(ESConfig):
    def __init__(self, algo_class=None):
        super().__init__(algo_class=algo_class or ARS)
        self.top_directions = 8     # b <= pop_size directions kept
        self.step_size = 0.02
        self.noise_stdev = 0.03
        self.observation_filter = "MeanStdFilter"  # or "NoFilter"

    def _training_keys(self):
        return super()._training_keys() | {"top_directions",
                                           "observation_filter"}


class ARS(ES):
    @classmethod
    def get_default_config(cls):
        return ARSConfig(algo_class=cls)

    def setup(self, _config) -> None:
        import jax.flatten_util

        cfg = self.config = self._algo_config
        inner_spec = cfg.module_spec()
        self._filter = {
            "mu": np.zeros(inner_spec.obs_dim, np.float32),
            "var": np.ones(inner_spec.obs_dim, np.float32),
        }
        self._filter_count = 0
        # theta covers the INNER policy only; the filter travels beside it
        # in the weights dict, outside the perturbed vector
        self._module_spec = ARSModuleSpec(inner=inner_spec)
        params = inner_spec.build().init(jax.random.key(cfg.seed))
        flat, self._unravel = jax.flatten_util.ravel_pytree(params)
        self._theta = np.asarray(flat, np.float32)
        self._np_rng = np.random.default_rng(cfg.seed)
        self.env_runners = [self._make_runner(i)
                            for i in range(cfg.num_env_runners)]
        self._total_env_steps = 0
        self._episode_returns = []

    def get_weights(self):
        return {"filter": {k: jnp.asarray(v)
                           for k, v in self._filter.items()},
                "inner": jax.device_get(self._unravel(self._theta))}

    def _candidate_weights(self, cand: np.ndarray):
        return {"filter": {k: jnp.asarray(v)
                           for k, v in self._filter.items()},
                "inner": jax.device_get(self._unravel(cand))}

    def _update_filter(self, obs_batches) -> None:
        if self._algo_config.observation_filter == "NoFilter":
            return
        flat = np.concatenate(
            [o.reshape(-1, o.shape[-1]) for o in obs_batches], axis=0)
        n_new = len(flat)
        if n_new == 0:
            return
        n_old = self._filter_count
        mu_new = flat.mean(0)
        var_new = flat.var(0)
        n = n_old + n_new
        delta = mu_new - self._filter["mu"]
        # Chan's parallel-variance merge of (old stats, batch stats);
        # n_old=0 contributes nothing (the init var is a placeholder,
        # not a sample)
        m_old = self._filter["var"] * n_old
        m_new = var_new * n_new
        self._filter["mu"] = (self._filter["mu"]
                              + delta * n_new / n).astype(np.float32)
        self._filter["var"] = ((m_old + m_new + delta ** 2
                                * n_old * n_new / n)
                               / max(n, 1)).astype(np.float32)
        self._filter_count = n

    def training_step(self) -> Dict:
        cfg = self.config
        dim = len(self._theta)
        noise = self._np_rng.standard_normal(
            (cfg.pop_size, dim)).astype(np.float32)
        candidates = np.concatenate([
            self._theta + cfg.noise_stdev * noise,
            self._theta - cfg.noise_stdev * noise])
        refs = {}
        for i, cand in enumerate(candidates):
            runner = self.env_runners[i % len(self.env_runners)]
            w_ref = ray_tpu.put(self._candidate_weights(cand))
            refs[runner.sample.remote(w_ref)] = i

        fitness = np.zeros(len(candidates), np.float32)
        obs_batches = []
        steps_this_iter = 0
        for ref, i in refs.items():
            sample = ray_tpu.get(ref, timeout=600)
            fitness[i] = self._fitness(sample)
            obs_batches.append(sample["obs"])
            steps_this_iter += sample["env_steps"]
            self._total_env_steps += sample["env_steps"]
            for ep in sample["episodes"]:
                self._episode_returns.append(ep["episode_return"])

        pos, neg = fitness[:cfg.pop_size], fitness[cfg.pop_size:]
        # top-b directions by the better arm of each antithetic pair
        b = min(cfg.top_directions, cfg.pop_size)
        order = np.argsort(-np.maximum(pos, neg))[:b]
        used = np.concatenate([pos[order], neg[order]])
        sigma_r = used.std() + 1e-8
        grad = (pos[order] - neg[order]) @ noise[order] / (b * sigma_r)
        self._theta = self._theta + cfg.step_size * grad

        self._update_filter(obs_batches)
        return {
            "env_steps_this_iter": steps_this_iter,
            "fitness_mean": float(fitness.mean()),
            "fitness_max": float(fitness.max()),
            "reward_std_used": float(sigma_r),
            "filter_count": self._filter_count,
            "theta_norm": float(np.linalg.norm(self._theta)),
        }

    def compute_single_action(self, obs, explore: bool = False):
        module = self._module_spec.build()
        out = module.forward(self.get_weights(), np.asarray(obs)[None])
        logits = np.asarray(out["logits"])[0]
        if module.spec.discrete:
            return int(np.argmax(logits))
        return np.tanh(logits[:module.spec.action_dim])

    # ----------------------------------------------------------- checkpoint
    def save_checkpoint(self, checkpoint_dir: str) -> None:
        import os
        import pickle

        super().save_checkpoint(checkpoint_dir)
        with open(os.path.join(checkpoint_dir, "ars_filter.pkl"),
                  "wb") as f:
            pickle.dump({"filter": self._filter,
                         "count": self._filter_count}, f)

    def load_checkpoint(self, checkpoint_dir: str) -> None:
        import os
        import pickle

        super().load_checkpoint(checkpoint_dir)
        with open(os.path.join(checkpoint_dir, "ars_filter.pkl"),
                  "rb") as f:
            state = pickle.load(f)
        self._filter = state["filter"]
        self._filter_count = state["count"]
