"""Warm-template forkserver: workers start by fork() from a pre-imported
template instead of a cold ``python -m`` launch.

The reference hides interpreter-startup latency behind prestarted idle
workers (reference: worker_pool.h StartWorkerProcess + prestart pools);
on a 1-core host a burst of 1000 actor creations still pays ~350ms of
imports per process. Forking from this template costs ~20-30ms: the
interpreter, ray_tpu._private.worker_process, msgpack and the protocol
stack are already imported; the child just fixes its env and enters
worker main.

Protocol (newline-delimited JSON over a unix stream socket):
  request:  {"env": {...}, "log_out": path, "log_err": path}
  response: {"pid": <child pid>}    (or {"error": "..."})

Fork safety: this process is SINGLE-THREADED by construction (blocking
socket loop, no asyncio); children reset inherited state — they setsid,
close the server fds, redirect stdio, and worker main builds every
socket/loop fresh. jax is deliberately NOT pre-imported (workers default
to JAX_PLATFORMS=cpu and import lazily). Zombies are reaped via SIGCHLD.
"""

from __future__ import annotations

import json
import os
import signal
import socket
import sys

# Pre-import the worker stack while still single-threaded (this is the
# whole point of the template). Must not start loops or sockets.
import ray_tpu._private.worker_process  # noqa: F401  (warm import)

# numpy too: the serialization fast path imports it lazily on the FIRST
# task result, which charged every fresh worker a ~250ms import on its
# first reply (measured dominating warm-pool actor starts, ISSUE 10).
# numpy touches no device state — jax stays deliberately unimported
# (workers must not pre-touch TPU runtime; the MULTICHIP dryrun gate
# asserts a parked warm worker has no `jax` in sys.modules).
import numpy  # noqa: F401  (warm import)

# Store-attach warmup: psutil (default_store_capacity) and the native
# arena's ctypes .so — dlopen'd ONCE here and inherited by every fork —
# were the next-largest slices of a worker's measured time-to-leasable
# (boot trace: the `store` phase). Best-effort: a missing toolchain just
# means children fall back exactly as they would have cold.
import psutil  # noqa: F401  (warm import)

try:
    from ray_tpu import _native as _native_warm

    _native_warm.get_native_lib()
except Exception:
    pass


# Death ledger: pids reaped by the SIGCHLD handler are appended here (one
# decimal pid per line) for the agent to consume. The agent cannot see
# these deaths itself: forked workers are children of THIS process, so
# after the zombie is reaped the pid may be recycled and the agent's
# kill(pid, 0) liveness probe would call a dead (or foreign!) process
# alive — a warm worker that died between fork and first lease could be
# leased. The ledger is the authoritative death signal for that window.
_death_ledger_path: str = ""


def _reap(_sig, _frm):
    try:
        while True:
            pid, _ = os.waitpid(-1, os.WNOHANG)
            if pid == 0:
                break
            if _death_ledger_path:
                # Python signal handlers run between bytecodes (not in
                # async-signal context), so buffered file I/O is safe;
                # O_APPEND keeps concurrent lines intact.
                try:
                    with open(_death_ledger_path, "a") as f:
                        f.write(f"{pid}\n")
                except OSError:
                    pass
    except ChildProcessError:
        pass


def _spawn(req: dict, server: socket.socket, conn: socket.socket) -> int:
    pid = os.fork()
    if pid != 0:
        return pid
    # ---- child ----
    try:
        os.setsid()
        # setsid detaches the worker into its own pgid — nothing reaps it
        # by group, so fate-share with this forkserver (whose own death is
        # tied to the agent): PDEATHSIG fires even if the agent is
        # SIGKILL'd before it can walk the registry
        from ray_tpu._private.lifecycle import _set_pdeathsig

        _set_pdeathsig(signal.SIGTERM)
        server.close()
        conn.close()
        signal.signal(signal.SIGCHLD, signal.SIG_DFL)
        out = os.open(req["log_out"], os.O_WRONLY | os.O_CREAT | os.O_APPEND)
        err = os.open(req["log_err"], os.O_WRONLY | os.O_CREAT | os.O_APPEND)
        os.dup2(out, 1)
        os.dup2(err, 2)
        os.close(out)
        os.close(err)
        env = req["env"]
        os.environ.clear()
        os.environ.update(env)
        from ray_tpu._private import worker_process

        worker_process.main()
        os._exit(0)
    except BaseException:
        import traceback

        traceback.print_exc()
        os._exit(1)


def main() -> None:
    global _death_ledger_path
    sock_path = sys.argv[1]
    try:
        os.unlink(sock_path)
    except FileNotFoundError:
        pass
    _death_ledger_path = sock_path + ".deaths"
    try:
        os.unlink(_death_ledger_path)
    except FileNotFoundError:
        pass
    signal.signal(signal.SIGCHLD, _reap)
    # register in the session pid registry + die with the agent even when
    # the ppid poll below never gets to run (wedged accept, SIGKILL races)
    from ray_tpu._private import lifecycle

    lifecycle.register_self("forkserver",
                            node_id=os.environ.get("RAY_TPU_NODE_ID", ""))
    lifecycle._set_pdeathsig(signal.SIGTERM)
    parent = os.getppid()
    server = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
    server.bind(sock_path)
    server.listen(16)
    server.settimeout(5.0)
    # tell the agent we're ready (it waits for this file)
    with open(sock_path + ".ready", "w") as f:
        f.write(str(os.getpid()))
    while True:
        # the template must not outlive its agent (it is setsid-detached,
        # so nothing else reaps it on session shutdown)
        if os.getppid() != parent:
            break
        try:
            conn, _ = server.accept()
        except socket.timeout:
            continue
        except InterruptedError:  # SIGCHLD during accept
            continue
        except OSError:
            break
        try:
            # per-connection recv deadline: the accept loop is serial, so
            # one client that connects and never sends a full request must
            # not block every subsequent warm-fork spawn
            conn.settimeout(5.0)
            buf = b""
            while not buf.endswith(b"\n"):
                try:
                    chunk = conn.recv(65536)
                except (socket.timeout, OSError):
                    buf = b""
                    break
                if not chunk:
                    buf = b""
                    break
                buf += chunk
            if not buf:
                continue
            try:
                req = json.loads(buf)
                pid = _spawn(req, server, conn)
                reply = {"pid": pid}
            except BaseException as e:  # noqa: BLE001
                reply = {"error": repr(e)}
            try:
                conn.sendall((json.dumps(reply) + "\n").encode())
            except OSError:
                # the client gave up (agent's 30s wait_for timed out and
                # closed): a dead peer must not kill the forkserver — the
                # node would silently lose warm forks forever
                pass
        finally:
            try:
                conn.close()
            except OSError:
                pass


if __name__ == "__main__":
    main()
