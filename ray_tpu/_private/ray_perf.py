"""Core-runtime microbenchmarks (reference: python/ray/_private/ray_perf.py:93
— the suite behind the release microbenchmark numbers in BASELINE.md:
single-client sync/async tasks, 1:1 and n:n actor calls, put/get).

Run: ``python -m ray_tpu._private.ray_perf [--filter substr]``
Prints one line per benchmark: ``name: N ops/s`` plus a JSON summary.

``--ab`` runs the alternating A/B mode (ISSUE 18): fast path vs legacy
path interleaved per pair in the SAME process, so the printed deltas obey
the same-day rule — never compare a number measured today against one
recorded on a different day or box; shared-core machines drift too much.
"""

from __future__ import annotations

import json
import os
import statistics
import time
from typing import Callable, Dict, List

import numpy as np


def timeit(name: str, fn: Callable[[], None], multiplier: int = 1,
           min_time_s: float = 2.0) -> float:
    """Run fn repeatedly for ~min_time_s; returns ops/s
    (reference: ray_perf.py timeit)."""
    # warmup
    fn()
    start = time.perf_counter()
    count = 0
    while time.perf_counter() - start < min_time_s:
        fn()
        count += 1
    took = time.perf_counter() - start
    rate = count * multiplier / took
    print(f"{name}: {rate:.1f} ops/s")
    return rate


def main(filter_substr: str = "") -> Dict[str, float]:
    import ray_tpu

    if not ray_tpu.is_initialized():
        ray_tpu.init(num_cpus=4)

    results: Dict[str, float] = {}

    def bench(name, fn, multiplier=1):
        if filter_substr and filter_substr not in name:
            return
        results[name] = timeit(name, fn, multiplier)

    # ---------------------------------------------------------------- tasks
    @ray_tpu.remote
    def noop():
        pass

    ray_tpu.get(noop.remote(), timeout=60)  # prime worker pool

    bench("single client tasks sync",
          lambda: ray_tpu.get(noop.remote()))

    # round sizes mirror the reference suite (reference ray_perf.py:204
    # submits 1000 per async round; :222-232 runs the n:n pattern through
    # m concurrent CLIENT worker processes) so the numbers are comparable
    # with BASELINE.md's
    N_ASYNC = 1000
    bench("single client tasks async",
          lambda: ray_tpu.get([noop.remote() for _ in range(N_ASYNC)]),
          multiplier=N_ASYNC)

    # vectorized submission (ISSUE 18): the same round submitted through
    # fn.map — one id block / registration batch / wire frame instead of
    # N_ASYNC driver round-trips. Also reports the driver-tax metric the
    # fast path is actually about: main-thread submit µs per call.
    @ray_tpu.remote
    def noop1(i):
        pass

    ray_tpu.get(noop1.remote(0), timeout=60)
    if not filter_substr or filter_substr in "single client tasks batched":
        submit_us: List[float] = []

        def batched_round():
            t0 = time.perf_counter()
            refs = noop1.map(range(N_ASYNC))
            submit_us.append((time.perf_counter() - t0) / N_ASYNC * 1e6)
            ray_tpu.get(refs)

        results["single client tasks batched"] = timeit(
            "single client tasks batched", batched_round,
            multiplier=N_ASYNC)
        med_submit = statistics.median(submit_us)
        print(f"single client tasks batched submit: "
              f"{med_submit:.1f} us/call (main thread)")
        results["single client tasks batched submit us"] = round(
            med_submit, 2)

    # ----------------------------------------------------------------- puts
    bench("single client put small",
          lambda: ray_tpu.put(b"x" * 100))

    arr = np.zeros((5 << 18,), np.float32)  # 5 MiB

    # hardware context for the put number: a put is bounded below by ONE
    # 5-MiB copy into the shm arena, so report this box's raw single-thread
    # copy bandwidth alongside (the reference's 19.45 GB/s figure came from
    # an m4.16xlarge with many memory channels)
    if not filter_substr or filter_substr in "raw memcpy gigabytes":
        dst = bytearray(arr.nbytes)
        src = memoryview(arr).cast("B")
        dst[:] = src
        t0 = time.perf_counter()
        reps = 0
        while time.perf_counter() - t0 < 1.0:
            dst[:] = src
            reps += 1
        mgbps = reps * arr.nbytes / (time.perf_counter() - t0) / 1e9
        print(f"raw memcpy gigabytes: {mgbps:.2f} GB/s")
        results["raw memcpy gigabytes"] = mgbps

    def put_large():
        for _ in range(10):
            ray_tpu.put(arr)

    t0 = time.perf_counter()
    if not filter_substr or filter_substr in "single client put gigabytes":
        n = 0
        while time.perf_counter() - t0 < 2.0:
            put_large()
            n += 1
        gbps = n * 10 * arr.nbytes / (time.perf_counter() - t0) / 1e9
        print(f"single client put gigabytes: {gbps:.2f} GB/s")
        results["single client put gigabytes"] = gbps

    ref = ray_tpu.put(arr)
    bench("single client get large",
          lambda: ray_tpu.get(ref))

    # multi client tasks async: m actor-clients each submit a batch of
    # noop TASKS from inside their own process (reference:
    # ray_perf.py:181-189 small_value_batch x4)
    N_MULTI, M_MULTI = 2500, 4

    @ray_tpu.remote
    class TaskClient:
        def submit_batch(self, n):
            ray_tpu.get([noop.remote() for _ in range(n)])

    # near-zero CPU: the clients must leave the pool's cores to the
    # tasks they submit (reference actors hold 0 CPU while alive)
    clients = [TaskClient.options(num_cpus=0.001).remote()
               for _ in range(M_MULTI)]
    for c in clients:
        ray_tpu.get(c.submit_batch.remote(2), timeout=120)
    bench("multi client tasks async",
          lambda: ray_tpu.get([c.submit_batch.remote(N_MULTI)
                               for c in clients], timeout=600),
          multiplier=N_MULTI * M_MULTI)
    for c in clients:
        ray_tpu.kill(c)

    # ---------------------------------------------------------------- actors
    @ray_tpu.remote
    class Actor:
        def noop(self):
            pass

    a = Actor.remote()
    ray_tpu.get(a.noop.remote(), timeout=60)
    bench("1:1 actor calls sync", lambda: ray_tpu.get(a.noop.remote()))
    bench("1:1 actor calls async",
          lambda: ray_tpu.get([a.noop.remote() for _ in range(N_ASYNC)]),
          multiplier=N_ASYNC)

    actors = [Actor.remote() for _ in range(4)]
    for act in actors:
        ray_tpu.get(act.noop.remote(), timeout=60)

    # n:n = n CLIENTS x n actors: m concurrent driver-side `work` tasks
    # each fan N_NN calls over the actor pool from their own worker
    # process (reference: ray_perf.py:222-232 — `work.remote(actors)` x m)
    N_NN, M_NN = 1000, 4

    @ray_tpu.remote
    def work(actor_handles):
        ray_tpu.get([actor_handles[i % len(actor_handles)].noop.remote()
                     for i in range(N_NN)])

    bench("n:n actor calls async",
          lambda: ray_tpu.get([work.remote(actors) for _ in range(M_NN)]),
          multiplier=N_NN * M_NN)
    for act in actors + [a]:
        ray_tpu.kill(act)

    # flight-recorder A/B (ISSUE 14): the same async-task bench with the
    # recorder OFF (the default this suite runs under) vs ON at sample
    # rate 1.0 — the honest cost of full span recording — plus the
    # measured disabled-guard cost, which is what the <2% hard
    # requirement is actually about (you cannot A/B the disabled path
    # against "no instrumentation at runtime"; the guard probe times the
    # exact branch every site pays)
    if not filter_substr or "events" in filter_substr:
        from ray_tpu._private import events as _ev

        @ray_tpu.remote
        def noop_ev():
            pass

        ray_tpu.get(noop_ev.remote(), timeout=60)

        def run_batch():
            ray_tpu.get([noop_ev.remote() for _ in range(N_ASYNC)])

        off_rate = timeit("tasks async (events off)", run_batch,
                          multiplier=N_ASYNC)
        w = ray_tpu._worker_mod.global_worker
        armed = _ev.configure(w.session_dir or "/tmp", w.mode,
                              sample_rate=1.0)
        on_rate = timeit("tasks async (events on)", run_batch,
                         multiplier=N_ASYNC)
        _ev.REC.enabled = False  # restore the suite's default
        results["events ab"] = {
            "off_tasks_per_s": round(off_rate, 1),
            "on_tasks_per_s": round(on_rate, 1),
            "on_overhead_pct": round(
                (off_rate - on_rate) / off_rate * 100, 2) if off_rate else 0,
            "recorder_armed": armed,
            "disabled_guard_ns": round(_ev.overhead_probe(100_000), 1),
        }
        print(json.dumps({"events ab": results["events ab"]}))

    # direct-call transport columns (ISSUE 11): which lane the actor
    # benches above actually rode — shm frame counts prove same-node
    # calls bypassed loopback TCP; fallback counters prove the ladder
    # engaged rather than dropping frames
    try:
        from ray_tpu._private.mux import MUX_STATS
        from ray_tpu._private.shm_rpc import stats_snapshot

        transport = {
            "mux_sessions_opened": MUX_STATS["sessions_opened"],
            "mux_streams_opened": MUX_STATS["streams_opened"],
            **{f"shm_{k}": v for k, v in stats_snapshot().items()},
        }
        print(json.dumps({"transport": transport}))
        results["transport"] = transport  # type: ignore[assignment]
    except Exception:
        pass

    print(json.dumps(results))
    return results


_AB_KNOBS = ("RAY_TPU_SUBMIT_FASTPATH_ENABLED",
             "RAY_TPU_COMPLETION_BATCH_ENABLED")


def run_ab(pairs: int = 3, n: int = 2000) -> Dict:
    """Alternating A/B mode (ISSUE 18): each pair runs arm A (submit
    fast path + batched completion ON) then arm B (both OFF) back to
    back in the same interpreter, and the delta is computed per pair —
    the codified same-day rule. CONFIG reads env per access, so
    flipping the env vars switches the live path with no restart.

    Three benches per arm:
      - many_tasks: n tasks through fn.map (A) vs the same fn.map call,
        which falls back to a per-call submit loop when the knob is off
        (B) — identical API, identical result, only the driver path
        differs. Reports e2e tasks/s AND main-thread submit µs/call.
      - 1:1 actor calls async: n handle.method.remote() + one get.
      - 1:1 actor calls sync: submit-get round trips (parity check —
        the fast path must not tax the latency path).
    """
    import ray_tpu

    if not ray_tpu.is_initialized():
        ray_tpu.init(num_cpus=4)

    @ray_tpu.remote
    def noop1(i):
        pass

    @ray_tpu.remote
    class Actor:
        def noop(self):
            pass

    a = Actor.remote()
    ray_tpu.get(a.noop.remote(), timeout=60)
    ray_tpu.get(noop1.map(range(4)), timeout=60)

    def set_arm(on: bool) -> None:
        for k in _AB_KNOBS:
            os.environ[k] = "1" if on else "0"
        # drain stragglers from the previous arm so its completion work
        # does not bleed into this arm's numbers (one shared core)
        ray_tpu.get(a.noop.remote(), timeout=60)
        time.sleep(0.2)

    def run_arm() -> Dict[str, float]:
        t0 = time.perf_counter()
        refs = noop1.map(range(n))
        t_submit = time.perf_counter()
        ray_tpu.get(refs, timeout=600)
        t_done = time.perf_counter()
        arm = {
            "many_tasks_submit_us": (t_submit - t0) / n * 1e6,
            "many_tasks_per_s": n / (t_done - t0),
        }
        t0 = time.perf_counter()
        ray_tpu.get([a.noop.remote() for _ in range(n)], timeout=600)
        arm["actor_async_per_s"] = n / (time.perf_counter() - t0)
        t0 = time.perf_counter()
        for _ in range(200):
            ray_tpu.get(a.noop.remote())
        arm["actor_sync_per_s"] = 200 / (time.perf_counter() - t0)
        return arm

    saved = {k: os.environ.get(k) for k in _AB_KNOBS}
    pair_rows: List[Dict] = []
    try:
        for i in range(pairs):
            set_arm(True)
            arm_a = run_arm()
            set_arm(False)
            arm_b = run_arm()
            row = {"pair": i, "A": {k: round(v, 2) for k, v in arm_a.items()},
                   "B": {k: round(v, 2) for k, v in arm_b.items()},
                   "delta": {k: round(arm_a[k] / arm_b[k], 3)
                             for k in arm_a if arm_b[k]}}
            pair_rows.append(row)
            print(json.dumps(row))
    finally:
        for k, v in saved.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v
        ray_tpu.kill(a)

    summary = {
        "pairs": pairs,
        "n": n,
        "median_delta": {
            k: round(statistics.median(r["delta"][k] for r in pair_rows), 3)
            for k in pair_rows[0]["delta"]
        } if pair_rows else {},
        "median_A": {
            k: round(statistics.median(r["A"][k] for r in pair_rows), 2)
            for k in pair_rows[0]["A"]
        } if pair_rows else {},
        "median_B": {
            k: round(statistics.median(r["B"][k] for r in pair_rows), 2)
            for k in pair_rows[0]["B"]
        } if pair_rows else {},
    }
    print(json.dumps({"ab_summary": summary}))
    return {"pairs": pair_rows, "summary": summary}


if __name__ == "__main__":
    import argparse

    parser = argparse.ArgumentParser()
    parser.add_argument("--filter", default="")
    parser.add_argument("--ab", action="store_true",
                        help="alternating fast-path A/B mode (ISSUE 18)")
    parser.add_argument("--pairs", type=int, default=3)
    parser.add_argument("--n", type=int, default=2000)
    args = parser.parse_args()
    if args.ab:
        run_ab(args.pairs, args.n)
    else:
        main(args.filter)
