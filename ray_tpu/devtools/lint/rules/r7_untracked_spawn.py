"""R7 — every spawned process must register with the pid registry.

Invariant: any ``subprocess.Popen`` (or forkserver spawn) must be
recorded in the session pid registry (``lifecycle.register_process`` /
``register_self``, or the CLI's ``_record_pid`` pidfile) by its spawner,
so the PR 1 teardown sweep (``node.stop()`` SIGTERM→SIGKILL walk, stale
session GC, conftest leak gate) can reap it. An unregistered child that
outlives its parent is exactly the daemon-leak class that starved the
round-5 MULTICHIP gate (leaked forkservers + workers oversubscribing the
box).

Detection: a ``Popen(...)`` call whose enclosing function does not also
call a registry function. Same-function registration is the contract
("called by the SPAWNER immediately after fork/Popen, so a crash of the
child can never leave it unregistered" — lifecycle.py); registering in
some *other* function leaves a crash window and is flagged.
"""

from __future__ import annotations

import ast
from typing import List

from ..callgraph import _call_name
from ..model import ModuleInfo, Violation

RULE_ID = "R7"
SUMMARY = ("subprocess.Popen without same-function pid-registry "
           "registration — the child escapes the teardown sweep and "
           "leaks as a daemon")

_REGISTRY_CALLS = {"register_process", "register_self", "_record_pid"}


def check_module(mod: ModuleInfo, index) -> List[Violation]:
    out: List[Violation] = []
    for node in ast.walk(mod.tree):
        if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        spawns: List[ast.Call] = []
        registers = False
        for sub in ast.walk(node):
            if isinstance(sub, ast.Call):
                base, attr = _call_name(sub.func)
                if attr == "Popen":
                    spawns.append(sub)
                elif attr in _REGISTRY_CALLS:
                    registers = True
        if spawns and not registers:
            for sp in spawns:
                out.append(mod.violation(
                    RULE_ID, sp,
                    f"Popen in '{mod.qualname(node)}' never registers the "
                    f"child with the session pid registry "
                    f"(lifecycle.register_process) in the same function: "
                    f"if this process dies the child escapes the "
                    f"teardown sweep and leaks as a daemon"))
    return out
