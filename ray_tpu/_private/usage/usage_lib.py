"""Usage stats (reference: python/ray/_private/usage/usage_lib.py — opt-out
telemetry pings).

This deployment has zero egress, so reports are only ever written to a local
JSON file under the session dir (same schema position as the reference's
payload); the collection/enable/disable surface matches so tooling that
checks ``usage_stats_enabled()`` behaves identically.
"""

from __future__ import annotations

import json
import os
import time
from typing import Any, Dict, Optional

_ENV = "RAY_TPU_USAGE_STATS_ENABLED"


def usage_stats_enabled() -> bool:
    """Opt-out semantics (reference: usage_lib enablement precedence)."""
    return os.environ.get(_ENV, "0") == "1"  # default OFF: zero-egress image


def set_usage_stats_enabled_via_env_var(enabled: bool) -> None:
    os.environ[_ENV] = "1" if enabled else "0"


def _collect(extra: Optional[Dict[str, Any]] = None) -> Dict[str, Any]:
    import platform

    data: Dict[str, Any] = {
        "schema_version": "0.1",
        "source": "ray_tpu",
        "python_version": platform.python_version(),
        "os": platform.system().lower(),
        "collect_timestamp_ms": int(time.time() * 1000),
    }
    try:
        import jax

        data["jax_version"] = jax.__version__
        data["num_devices"] = jax.device_count()
        data["device_kind"] = jax.devices()[0].device_kind
    except Exception:
        pass
    try:
        import ray_tpu

        if ray_tpu.is_initialized():
            data["cluster_resources"] = ray_tpu.cluster_resources()
            data["num_nodes"] = len(
                [n for n in ray_tpu.nodes() if n.get("alive")])
    except Exception:
        pass
    if extra:
        data.update(extra)
    return data


def record_usage(session_dir: Optional[str] = None,
                 extra: Optional[Dict[str, Any]] = None) -> Optional[str]:
    """Write the usage payload locally; returns the path (or None if
    disabled)."""
    if not usage_stats_enabled():
        return None
    import ray_tpu

    session_dir = session_dir or getattr(
        ray_tpu._global_node, "session_dir", None) or "/tmp"
    path = os.path.join(session_dir, "usage_stats.json")
    with open(path, "w") as f:
        json.dump(_collect(extra), f)
    return path
