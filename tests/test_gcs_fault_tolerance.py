"""GCS (head) fault tolerance (reference:
python/ray/tests/test_gcs_fault_tolerance.py — GCS restart with
redis-backed state; here a write-ahead-logged file store is the durable
backend and agents/drivers re-register through their watchdogs).

The WAL makes durability per-mutation: a mutating RPC is acked only
after its record is fsynced, so these tests ``kill -9`` the head
IMMEDIATELY after an acked put/actor-create — no "let the debounced
snapshot flush" sleep (the pre-WAL race these tests used to paper over).
"""

import os
import signal
import subprocess
import sys
import time

import pytest

import ray_tpu
from ray_tpu.exceptions import HeadUnavailableError


@pytest.fixture()
def persistent_cluster(tmp_path, monkeypatch):
    persist = str(tmp_path / "head_state.bin")
    monkeypatch.setenv("RAY_TPU_GCS_PERSIST", persist)
    # fast reconnects + a short claim window keep the recovery phases of
    # these tests in seconds (daemons inherit the env via Cluster())
    monkeypatch.setenv("RAY_TPU_HEAD_WATCHDOG_PERIOD_S", "0.5")
    monkeypatch.setenv("RAY_TPU_HEAD_PING_TIMEOUT_S", "2.0")
    monkeypatch.setenv("RAY_TPU_GCS_RECOVERY_GRACE_S", "6.0")
    from ray_tpu.cluster_utils import Cluster

    cluster = Cluster(initialize_head=True, head_node_args={"num_cpus": 2})
    ray_tpu.init(_node=cluster.head_node)
    yield cluster, persist
    ray_tpu.shutdown()
    cluster.shutdown()


def _restart_head(node, persist: str) -> None:
    from ray_tpu._private import lifecycle

    node.head_proc.kill()  # SIGKILL: no flush, no atexit, no mercy
    node.head_proc.wait()
    log = open(os.path.join(node.session_dir, "logs", "head2.log"), "ab")
    env = dict(os.environ, RAY_TPU_GCS_PERSIST=persist)
    node.head_proc = subprocess.Popen(
        [sys.executable, "-m", "ray_tpu._private.gcs",
         "--session-dir", node.session_dir,
         "--port", str(node.head_port)],
        stdout=log, stderr=log, env=env,
        start_new_session=True)  # node.stop() killpg must not hit us
    # spawner-side pid-registry entry: node.stop()'s sweep must reap the
    # replacement head even if it dies before its own register_self runs
    # (intermittent leaked-session teardown ERROR otherwise)
    lifecycle.register_process(node.session_dir, "gcs", node.head_proc.pid)


def _await_kv(key: bytes, value: bytes, timeout: float = 30) -> bool:
    from ray_tpu.experimental import internal_kv

    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        try:
            if internal_kv._internal_kv_get(key) == value:
                return True
        except Exception:
            pass
        time.sleep(0.5)
    return False


def test_head_restart_preserves_state_and_recovers(persistent_cluster):
    cluster, persist = persistent_cluster
    from ray_tpu.experimental import internal_kv

    @ray_tpu.remote
    class Keeper:
        def __init__(self):
            self.n = 0

        def bump(self):
            self.n += 1
            return self.n

    keeper = Keeper.options(name="keeper", lifetime="detached").remote()
    assert ray_tpu.get(keeper.bump.remote(), timeout=60) == 1

    # kill -9 IMMEDIATELY after the acked put: the WAL ack contract means
    # an acknowledged mutation is already durable — no flush sleep
    internal_kv._internal_kv_put(b"durable_key", b"durable_value")
    _restart_head(cluster.head_node, persist)
    assert _await_kv(b"durable_key", b"durable_value"), \
        "KV not readable after head restart"

    # named detached actor survives: the restored actor table still routes
    # to the live actor process once the agent's re-register claims it
    handle = None
    deadline = time.monotonic() + 30
    while time.monotonic() < deadline:
        try:
            handle = ray_tpu.get_actor("keeper")
            break
        except Exception:
            time.sleep(0.5)
    assert handle is not None, "named actor not resolvable after restart"
    assert ray_tpu.get(handle.bump.remote(), timeout=60) == 2  # state kept

    # normal tasks still run (agent re-registered under the same node id)
    @ray_tpu.remote
    def add(a, b):
        return a + b

    deadline = time.monotonic() + 60
    while True:
        try:
            assert ray_tpu.get(add.remote(2, 3), timeout=30) == 5
            break
        except Exception:
            if time.monotonic() > deadline:
                raise
            time.sleep(1.0)

    # SECOND immediate kill: an acked actor-create with no snapshot flush
    # in between must survive through the WAL alone
    @ray_tpu.remote
    class Second:
        def ping(self):
            return "pong"

    second = Second.options(name="second", lifetime="detached").remote()
    assert ray_tpu.get(second.ping.remote(), timeout=60) == "pong"
    internal_kv._internal_kv_put(b"second_key", b"second_value")
    _restart_head(cluster.head_node, persist)
    assert _await_kv(b"second_key", b"second_value")
    deadline = time.monotonic() + 30
    while time.monotonic() < deadline:
        try:
            h2 = ray_tpu.get_actor("second")
            assert ray_tpu.get(h2.ping.remote(), timeout=30) == "pong"
            break
        except Exception:
            time.sleep(0.5)
    else:
        raise AssertionError("actor created pre-kill lost by restart")

    # operator view: the head knows how many lives it has had and that
    # its WAL is alive (CLI `status` surfaces exactly this)
    from ray_tpu._private import worker as worker_mod

    w = worker_mod.global_worker
    status = w.head_call("GetHeadStatus", {})
    assert status["incarnation"] == 3  # boot + two recoveries
    assert status["wal"] is not None and status["wal"]["seq"] > 0
    assert status["last_recovery"]["restored_actors"] >= 1


def test_unclaimed_actor_reconciled_dead(persistent_cluster):
    """An actor whose worker dies DURING the head outage: the restored
    table says ALIVE, the re-registering agent's live set says otherwise
    — reconciliation must declare it dead with the exact outage reason,
    not leave a ghost routing to a dead pid."""
    cluster, persist = persistent_cluster

    @ray_tpu.remote
    class Doomed:
        def pid(self):
            return os.getpid()

    doomed = Doomed.options(name="doomed", lifetime="detached").remote()
    victim_pid = ray_tpu.get(doomed.pid.remote(), timeout=60)

    # head dies first (so it can never observe the worker death), then
    # the worker: the ONLY way the cluster can learn the truth is the
    # recovery reconciliation against the agent's reported live set
    cluster.head_node.head_proc.kill()
    cluster.head_node.head_proc.wait()
    os.kill(victim_pid, signal.SIGKILL)
    time.sleep(0.5)  # let the agent reap the worker before it re-registers
    _restart_head(cluster.head_node, persist)

    deadline = time.monotonic() + 60
    view = None
    while time.monotonic() < deadline:
        try:
            from ray_tpu._private import worker as worker_mod

            w = worker_mod.global_worker
            views = w.head_call("ListActors", {})
            view = next(v for v in views
                        if v["name"] == "doomed")
            if view["state"] == "DEAD":
                break
        except Exception:
            pass
        time.sleep(0.5)
    assert view is not None and view["state"] == "DEAD", view
    assert view["death_context"]["reason"] == "lost_during_head_outage", view
    with pytest.raises(Exception):
        ray_tpu.get_actor("doomed")  # the name is released, no ghost


def test_outage_queue_then_typed_error(persistent_cluster, monkeypatch):
    """Head-bound control calls during an outage: queue briefly (a head
    bounce is survivable), then fail FAST with the typed error — not a
    generic ConnectionLost, not a 60 s RPC deadline."""
    cluster, persist = persistent_cluster
    from ray_tpu.experimental import internal_kv

    internal_kv._internal_kv_put(b"pre", b"1")  # link warm + durable
    monkeypatch.setenv("RAY_TPU_GCS_OUTAGE_QUEUE_S", "2.0")
    cluster.head_node.head_proc.kill()
    cluster.head_node.head_proc.wait()
    t0 = time.monotonic()
    with pytest.raises(HeadUnavailableError) as err:
        # retried internally against the dead head until the 2 s budget
        # lapses; worst case adds one in-flight RPC timeout on top
        internal_kv._internal_kv_put(b"during_outage", b"x")
    took = time.monotonic() - t0
    assert took < 30, f"typed failure took {took:.1f}s"
    assert err.value.method == "KvPut"
    # the head comes back: the SAME call path works again, and nothing
    # acked before the outage was lost
    _restart_head(cluster.head_node, persist)
    assert _await_kv(b"pre", b"1")
    deadline = time.monotonic() + 30
    while time.monotonic() < deadline:
        try:
            internal_kv._internal_kv_put(b"after", b"2")
            break
        except Exception:
            time.sleep(0.5)
    assert _await_kv(b"after", b"2", timeout=10)


def test_duplicate_create_actor_is_idempotent(tmp_path):
    """An ambiguous CreateActor (mutation durable, reply lost to a head
    kill) is retried by the outage-tolerant head_call: the head must
    adopt the duplicate (actor ids are client-generated, same id ==
    same logical create) — not raise 'name already taken' for a create
    that succeeded, and not schedule a second copy."""
    import asyncio

    from ray_tpu._private.gcs import HeadServer

    class _Conn:
        closed = False

        def __init__(self):
            self.meta = {"job_id": "j"}

    async def main():
        head = HeadServer(str(tmp_path), 0, persist_path=None)
        conn = _Conn()
        p = {"actor_id": "abc", "spec": {"class_name": "C"},
             "name": "dupname", "namespace": "default", "max_restarts": 0}
        r1 = await head._create_actor(conn, p)
        r2 = await head._create_actor(conn, p)  # retry after lost ack
        assert r2["actor_id"] == r1["actor_id"] == "abc"
        assert len(head.actors) == 1
        assert head.named_actors[("default", "dupname")] == "abc"
        assert head.actors["abc"].owner_conn is conn

    asyncio.run(main())


# ---------------------------------------------------------------------------
# decorrelated-jitter backoff (unit): the reconnect pacing the agent and
# driver watchdogs use after a head bounce
# ---------------------------------------------------------------------------
def test_decorrelated_jitter_backoff_sequence():
    import random

    from ray_tpu._private.async_util import DecorrelatedJitterBackoff

    b = DecorrelatedJitterBackoff(base_s=0.2, cap_s=2.0,
                                  rng=random.Random(42))
    prev = 0.2
    seq = []
    for _ in range(50):
        d = b.next_delay()
        seq.append(d)
        assert 0.2 <= d <= 2.0
        assert d <= max(prev * 3, 0.2 * 3) + 1e-9  # decorrelated bound
        prev = d
    # jittered: not a fixed doubling grid, and not constant
    assert len({round(d, 6) for d in seq}) > 10
    assert max(seq) == 2.0  # reaches the cap under sustained outage
    b.reset()
    assert b.next_delay() <= 0.6  # back to base pacing after reconnect


def test_decorrelated_jitter_distinct_across_instances():
    """Two clients must not share a schedule — that IS the herd."""
    from ray_tpu._private.async_util import DecorrelatedJitterBackoff

    a = [DecorrelatedJitterBackoff(0.2, 2.0).next_delay() for _ in range(8)]
    b = [DecorrelatedJitterBackoff(0.2, 2.0).next_delay() for _ in range(8)]
    assert a != b
