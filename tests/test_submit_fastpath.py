"""Vectorized submission fast path (ISSUE 18; reference:
python/ray/_private/worker.py submit path + direct_task_transport.h).

Covers the contract of ``fn.map`` / ``Worker.submit_many`` /
``submit_actor_tasks_many``: ref identity and ordering, per-entry error
blast radius (one bad entry fails alone), spec-template cache
invalidation when a function is redefined (new function id — stale
templates can never serve the new body), cache cap eviction, knob-off
parity (the legacy per-call path produces identical results through the
same API), ownership/lineage bookkeeping parity with the single-call
path (PR 17), full lineage RECONSTRUCTION of batched submissions after
a node kill, kill -9 mid-batch (typed per-entry errors, no hang), and
the one-root-span-per-batch trace shape (satellite of ISSUE 18).

One module-scoped cluster head; the reconstruction test brings its own
side node keyed by a unique resource (idiom from test_lineage).
"""

import os
import signal
import time
from itertools import repeat

import numpy as np
import pytest

import ray_tpu
from ray_tpu._private import events as _ev
from ray_tpu._private import worker as worker_mod
from ray_tpu._private.task_spec import (NORMAL_TASK, SpecTemplate, TaskSpec)
from ray_tpu._private.worker import _replay_seed
from ray_tpu.cluster_utils import Cluster
from ray_tpu.exceptions import RayTaskError, WorkerCrashedError
from ray_tpu._private.object_ref import ObjectRef


# ---------------------------------------------------------------------------
# spec-template units (no cluster)
# ---------------------------------------------------------------------------
def test_spec_template_lazy_instantiate():
    """instantiate() splices per-call fields into a copy of the frozen
    base wire dict; slots fill lazily on first read and to_wire() hands
    back the spliced dict without rebuilding."""
    tpl = SpecTemplate(
        job_id=b"j" * 4, task_type=NORMAL_TASK, function_id=b"f" * 16,
        function_name="t", num_returns=2, resources={"CPU": 1.0},
        owner_addr={"h": 1}, max_retries=3)
    spec = tpl.instantiate(b"t1" * 8, [("v", b"a")], {}, trace_ctx=None,
                           replay_seed=7)
    assert spec.task_id == b"t1" * 8
    assert spec.function_name == "t"
    assert spec.num_returns == 2
    assert spec.max_retries == 3
    assert spec.replay_seed == 7
    # omitted invariants fall to wire defaults, not AttributeError
    assert spec.seq == 0 and spec.actor_method == ""
    w = spec.to_wire()
    assert w["task_id"] == b"t1" * 8 and w["args"] == [("v", b"a")]
    # the template's base never absorbs per-call fields
    assert tpl.base["task_id"] is None
    # sched_key precomputed once matches the spec's own
    assert tpl.sched_key == spec.scheduling_key()


def test_spec_template_seq_splice():
    tpl = SpecTemplate(
        job_id=b"j" * 4, task_type=NORMAL_TASK, function_id=b"f" * 16,
        function_name="t", num_returns=1, resources={}, owner_addr={})
    assert tpl.instantiate(b"a" * 16, [], {}, seq=5).seq == 5
    assert tpl.instantiate(b"b" * 16, [], {}).seq == 0


# ---------------------------------------------------------------------------
# cluster tests: one module-scoped head, per-test side nodes
# ---------------------------------------------------------------------------
@pytest.fixture(scope="module")
def fastpath_cluster():
    cluster = Cluster(initialize_head=True, head_node_args={"num_cpus": 4})
    ray_tpu.init(_node=cluster.head_node)
    yield cluster
    ray_tpu.shutdown()
    cluster.shutdown()


def test_map_ref_identity_and_ordering(fastpath_cluster):
    """One map call yields one distinct, immediately-usable ObjectRef
    per item, results land in argument order, and every return id is
    registered with the owner (parity with per-call submission)."""
    @ray_tpu.remote
    def square(i):
        return i * i

    refs = square.map(range(40))
    assert len(refs) == 40
    assert all(isinstance(r, ObjectRef) for r in refs)
    assert len({r.id().binary() for r in refs}) == 40
    w = worker_mod.global_worker
    for r in refs:
        assert r.id().binary() in w.reference_counter._owned
    assert ray_tpu.get(refs, timeout=120) == [i * i for i in range(40)]

    @ray_tpu.remote(num_returns=2)
    def pair(i):
        return i, -i

    batches = pair.map(range(5))
    assert all(len(b) == 2 for b in batches)
    assert ray_tpu.get([b[1] for b in batches], timeout=120) == [
        0, -1, -2, -3, -4]


def test_map_zip_and_repeat_semantics(fastpath_cluster):
    """builtins.map/zip semantics: pairwise over iterables, stops at
    the shortest, constants ride itertools.repeat."""
    @ray_tpu.remote
    def add(a, b):
        return a + b

    assert ray_tpu.get(add.map([1, 2, 3], [10, 20]), timeout=120) == [11, 22]
    assert ray_tpu.get(add.map(range(3), repeat(100)),
                       timeout=120) == [100, 101, 102]
    assert add.map() == []


def test_per_entry_error_blast_radius(fastpath_cluster):
    """A raising entry fails ONLY its own ref with the typed task
    error; every other entry in the same batch completes normally."""
    @ray_tpu.remote
    def picky(i):
        if i % 5 == 0:
            raise ValueError(f"bad {i}")
        return i

    refs = picky.map(range(20))
    ok, bad = [], []
    for i, r in enumerate(refs):
        try:
            ok.append((i, ray_tpu.get(r, timeout=120)))
        except (ValueError, RayTaskError):
            bad.append(i)
    assert bad == [0, 5, 10, 15]
    assert ok == [(i, i) for i in range(20) if i % 5]


def test_template_cache_invalidation_on_redefinition(fastpath_cluster):
    """Redefining a function produces a new function id, so the
    template cache keys the new body separately — stale templates can
    never serve it (the cache key embeds the fid)."""
    w = worker_mod.global_worker

    def make(bias):
        @ray_tpu.remote
        def biased(i):
            return i + bias

        return biased

    f1 = make(100)
    assert ray_tpu.get(f1.map(range(3)), timeout=120) == [100, 101, 102]
    n_templates = len(w._spec_templates)
    # same source, different closure constant => different blob/fid
    f2 = make(500)
    assert ray_tpu.get(f2.map(range(3)), timeout=120) == [500, 501, 502]
    assert len(w._spec_templates) > n_templates
    # the original is still live and still correct after the redefine
    assert ray_tpu.get(f1.map(range(3)), timeout=120) == [100, 101, 102]


def test_template_cache_cap_eviction(fastpath_cluster, monkeypatch):
    """The cache clears on hitting spec_template_cache_max instead of
    growing without bound (one dict per (fn, options) signature)."""
    monkeypatch.setenv("RAY_TPU_SPEC_TEMPLATE_CACHE_MAX", "4")
    w = worker_mod.global_worker

    @ray_tpu.remote
    def fid(i):
        return i

    # distinct options signatures => distinct template keys
    for k in range(10):
        assert ray_tpu.get(
            fid.options(name=f"sig{k}").map([k]), timeout=120) == [k]
        assert len(w._spec_templates) <= 4


def test_knob_off_parity(fastpath_cluster, monkeypatch):
    """With the fast path and batched completion disabled, the SAME
    map()/submit_many API runs the legacy per-call path and produces
    identical results — the knob changes the driver cost, never the
    answer."""
    @ray_tpu.remote
    def cube(i):
        return i ** 3

    want = [i ** 3 for i in range(12)]
    assert ray_tpu.get(cube.map(range(12)), timeout=120) == want
    monkeypatch.setenv("RAY_TPU_SUBMIT_FASTPATH_ENABLED", "0")
    monkeypatch.setenv("RAY_TPU_COMPLETION_BATCH_ENABLED", "0")
    assert ray_tpu.get(cube.map(range(12)), timeout=120) == want


def test_batched_ownership_and_lineage_bookkeeping(fastpath_cluster):
    """Batched submissions get the SAME owner-side bookkeeping as
    per-call ones (PR 17 parity): owned metadata with a task: creator,
    a replay_seed that is the pure function of the task id, and a
    lineage-ledger retention for retriable plasma-return tasks."""
    w = worker_mod.global_worker

    @ray_tpu.remote(max_retries=2)
    def big(i):
        return np.full(200_000, i, np.int64)  # plasma-sized

    refs = big.map(range(3))
    vals = ray_tpu.get(refs, timeout=120)
    assert [int(v[0]) for v in vals] == [0, 1, 2]
    for r in refs:
        meta = w.reference_counter._owned.get(r.id().binary())
        assert meta is not None
        assert meta.creator.startswith("task:")
        tid = r.id().task_id().binary()
        rec = w._tasks.get(tid)
        assert rec is not None, "retriable batched task must stay replayable"
        assert rec.spec.replay_seed == _replay_seed(tid)
        assert rec.spec.max_retries == 2
    del refs, vals


def _kill_and_replace(cluster, node, res_key):
    cluster.remove_node(node)
    replacement = cluster.add_node(num_cpus=2, resources={res_key: 2})
    cluster.wait_for_nodes()
    time.sleep(2.5)  # node-death detection lag (~2s health check)
    return replacement


@pytest.mark.slow
def test_lineage_reconstruction_of_batched_submissions(fastpath_cluster):
    """Kill the node holding every return of a BATCHED submission:
    the owner replays each lost task under its original id and seed,
    reconstructing byte-identical values (acceptance: lineage
    reconstruction works for batched submissions)."""
    cluster = fastpath_cluster
    node = cluster.add_node(num_cpus=2, resources={"fp_lin": 2})
    cluster.wait_for_nodes()

    @ray_tpu.remote(max_retries=2, resources={"fp_lin": 1})
    def noisy(i):
        import random

        arr = np.zeros(200_000)
        arr[:64] = [random.random() for _ in range(64)]
        return arr + i

    @ray_tpu.remote(max_retries=2, resources={"fp_lin": 1})
    def sha(x):
        import hashlib

        return hashlib.sha256(x.tobytes()).hexdigest()

    refs = noisy.map(range(3))
    # hash on the SAME node: a driver get() would pull head-side
    # replicas and the kill below would lose nothing (test_lineage idiom)
    before_hashes = ray_tpu.get(sha.map(refs), timeout=180)
    w = worker_mod.global_worker
    before = w._lineage.reconstructions
    _kill_and_replace(cluster, node, "fp_lin")
    import hashlib

    after_vals = ray_tpu.get(refs, timeout=180)
    after_hashes = [hashlib.sha256(v.tobytes()).hexdigest()
                    for v in after_vals]
    assert after_hashes == before_hashes  # replay_seed => exact RNG replay
    assert w._lineage.reconstructions >= before + 3
    del refs, after_vals


def test_kill9_mid_batch_typed_errors_no_hang(fastpath_cluster, tmp_path):
    """SIGKILL a worker while a batch is in flight: entries on the dead
    worker fail with the typed WorkerCrashedError, entries elsewhere
    complete, and every get returns promptly — no hung futures."""
    gate = str(tmp_path)

    @ray_tpu.remote(max_retries=0)
    def stall(i, d):
        with open(os.path.join(d, f"{os.getpid()}.{i}.pid"), "w") as f:
            f.write(str(i))
        while not os.path.exists(os.path.join(d, "go")):
            time.sleep(0.05)
        return i

    refs = stall.map(range(4), repeat(gate))
    deadline = time.monotonic() + 60
    pids = set()
    while time.monotonic() < deadline:
        pids = {int(p.split(".")[0]) for p in os.listdir(gate)
                if p.endswith(".pid")}
        if pids:
            break
        time.sleep(0.05)
    assert pids, "no batch entry started within 60s"
    os.kill(sorted(pids)[0], signal.SIGKILL)
    time.sleep(0.3)
    with open(os.path.join(gate, "go"), "w") as f:
        f.write("1")

    t0 = time.monotonic()
    outcomes = []
    for i, r in enumerate(refs):
        try:
            outcomes.append(("ok", ray_tpu.get(r, timeout=90)))
        except WorkerCrashedError:
            outcomes.append(("crash", i))
        except RayTaskError as e:  # wrapped crash riding the reply path
            assert "died" in str(e).lower() or "crash" in str(e).lower()
            outcomes.append(("crash", i))
    assert time.monotonic() - t0 < 95, "mid-batch kill must not hang gets"
    crashes = [o for o in outcomes if o[0] == "crash"]
    assert crashes, "killing an executing worker must fail its entries"
    for kind, val in outcomes:
        if kind == "ok":
            assert outcomes[val] is not None  # value equals its index
    oks = [val for kind, val in outcomes if kind == "ok"]
    assert oks == [i for i in range(4)
                   if ("crash", i) not in outcomes]


def test_one_root_span_per_batch(fastpath_cluster):
    """With tracing armed, a batch records ONE submit_batch:: root span
    carrying the entry count instead of N per-task roots (satellite of
    ISSUE 18: keep trace volume proportional to batches, not entries)."""
    w = worker_mod.global_worker

    @ray_tpu.remote
    def traced(i):
        return i

    armed = _ev.configure(w.session_dir or "/tmp", w.mode, sample_rate=1.0)
    assert armed
    try:
        assert ray_tpu.get(traced.map(range(16)), timeout=120) == list(
            range(16))
        # read_ring reads the driver's mmap ring directly; no head-side
        # flush needed (and _maybe_flush_spans is loop-thread-only).
        info = _ev.read_ring(_ev.REC.path)
    finally:
        _ev.REC.enabled = False
    batch_roots = [s for s in info["spans"]
                   if s["name"].startswith("submit_batch::")
                   and s["name"].endswith("traced")]
    assert len(batch_roots) == 1
    assert batch_roots[0]["extra"] == {"count": 16}
    per_task_roots = [s for s in info["spans"]
                      if s["name"].startswith("task::")
                      and s["name"].endswith("traced")]
    assert not per_task_roots
