"""Streaming multi-node shuffle on the device object plane (ISSUE 12).

Unit layer (no cluster): the packed-shard codec round-trips numeric /
Fortran-order / object columns and its output rides the ZeroCopyArray
fast path.

Integration: the streaming exchange produces byte-identical results
(sha256 over sorted rows) vs the legacy materializing path on the same
multi-node cluster; reduce admission overlaps map execution (no
map→reduce barrier); admitted-reducer shard bytes never exceed the
configured budget; the executor drive loop is event-paced (no
busy-poll); shuffle workers never import jax (MULTICHIP gate).

Chaos: kill -9 of a node holding unique map shards mid-shuffle — the
job completes byte-identical with map re-execution counters > 0, never
a hang.
"""

import dataclasses
import hashlib
import subprocess
import sys
import time

import numpy as np
import pytest

import ray_tpu
import ray_tpu.data as rd
from ray_tpu.cluster_utils import Cluster
from ray_tpu.data.context import DataContext
from ray_tpu.data._internal import shard_codec as sc

MB = 1024 * 1024


# ---------------------------------------------------------------------------
# packed-shard codec (no cluster)
# ---------------------------------------------------------------------------
class TestShardCodec:
    def test_round_trips_numeric_and_object_columns(self):
        rng = np.random.default_rng(0)
        block = {
            "id": np.arange(64, dtype=np.int64),
            "x": rng.random((64, 16)).astype(np.float32),
            "flag": rng.random(64) < 0.5,
            "tag": np.array([f"row-{i}" for i in range(64)], dtype=object),
        }
        packed = sc.encode_shard(block)
        assert sc.is_packed_shard(packed)
        out = sc.decode_shard(packed)
        assert set(out) == set(block)
        for k in ("id", "x", "flag"):
            assert out[k].dtype == block[k].dtype
            assert np.array_equal(out[k], block[k])
        assert list(out["tag"]) == list(block["tag"])

    def test_fortran_order_and_empty(self):
        f = {"m": np.asfortranarray(np.arange(24.).reshape(4, 6))}
        assert np.array_equal(sc.decode_shard(sc.encode_shard(f))["m"],
                              f["m"])
        assert sc.decode_shard(sc.encode_shard({})) == {}
        empty_col = {"id": np.empty(0, np.int64)}
        out = sc.decode_shard(sc.encode_shard(empty_col))
        assert out["id"].shape == (0,)

    def test_packed_shard_rides_zero_copy_path(self):
        from ray_tpu._private import serialization as ser

        packed = sc.encode_shard(
            {"x": np.random.default_rng(1).random((100, 32))})
        zc = ser.try_serialize_array(packed)
        assert zc is not None, \
            "packed shard must be a bare contiguous array (ZC eligible)"
        wire = memoryview(zc.to_bytes())
        assert ser.is_zero_copy(wire)
        # decode from the zero-copy (read-only) view, like a reducer does
        view = ser.SerializationContext().deserialize(wire)
        assert not view.flags.writeable
        out = sc.decode_shard(view)
        assert out["x"].shape == (100, 32)

    def test_arrow_block_input(self):
        pa = pytest.importorskip("pyarrow")
        t = pa.table({"id": list(range(10)), "v": [float(i) for i in range(10)]})
        out = sc.decode_shard(sc.encode_shard(t))
        assert list(out["id"]) == list(range(10))

    def test_decode_rejects_garbage(self):
        with pytest.raises(ValueError):
            sc.decode_shard(np.zeros(128, np.uint8))


def test_shuffle_modules_import_no_jax():
    """MULTICHIP gate: the shuffle/executor import graph must not pull
    jax into workers (same contract as the warm pool)."""
    code = (
        "import ray_tpu.data._internal.streaming_shuffle, "
        "ray_tpu.data._internal.shard_codec, "
        "ray_tpu.data._internal.executor, "
        "ray_tpu.data._internal.shuffle; "
        # the vectorized-submission fast path (ISSUE 18) now sits on the
        # shuffle dispatch graph: the spec-template machinery must stay
        # jax-free too, and actually building a template must not pull
        # anything heavier in
        "import ray_tpu.remote_function; "
        "from ray_tpu._private.task_spec import SpecTemplate, NORMAL_TASK; "
        "t = SpecTemplate(job_id=b'j'*4, task_type=NORMAL_TASK, "
        "function_id=b'f'*16, function_name='p', num_returns=1, "
        "resources={}, owner_addr={}); "
        "t.instantiate(b'i'*16, [], {}); "
        "import sys; assert 'jax' not in sys.modules, 'jax imported'"
    )
    subprocess.run([sys.executable, "-c", code], check=True, timeout=120)


# ---------------------------------------------------------------------------
# cluster fixtures
# ---------------------------------------------------------------------------
@pytest.fixture
def ctx():
    """Fresh DataContext per test; restore the original afterwards."""
    old = DataContext.get_current()
    fresh = dataclasses.replace(old)
    DataContext._set_current(fresh)
    yield fresh
    DataContext._set_current(old)


@pytest.fixture
def shuffle_cluster(monkeypatch):
    """Factory: boot a head + N worker nodes localhost cluster."""
    made = []

    def boot(n_nodes=2, head_cpus=2, node_cpus=2, env=None,
             node_resources=None, head_resources=None):
        for k, v in (env or {}).items():
            monkeypatch.setenv(k, v)
        head_args = {"num_cpus": head_cpus}
        if head_resources:
            head_args["resources"] = head_resources
        cluster = Cluster(initialize_head=True, head_node_args=head_args)
        made.append(cluster)
        ray_tpu.init(_node=cluster.head_node)
        nodes = []
        for i in range(n_nodes):
            res = (node_resources[i] if node_resources else None)
            nodes.append(cluster.add_node(num_cpus=node_cpus,
                                          resources=res))
        cluster.wait_for_nodes()
        return cluster, nodes

    yield boot
    try:
        ray_tpu.shutdown()
    finally:
        for cluster in made:
            cluster.shutdown()


def _payload_ds(rows=4096, parallelism=8, width=128):
    def payload(batch):
        n = len(batch["id"])
        rng = np.random.default_rng(int(batch["id"][0]) if n else 0)
        batch["x"] = rng.random((n, width)).astype(np.float32)
        return batch

    return rd.range(rows, parallelism=parallelism).map_batches(payload)


def _rows_sha(ds) -> str:
    """sha256 over sorted rows (id + payload checksum per row)."""
    acc = []
    for batch in ds.iter_batches(batch_size=None, prefetch_batches=0):
        ids = np.asarray(batch["id"])
        xs = np.ascontiguousarray(np.asarray(batch["x"]))
        for i in range(len(ids)):
            acc.append((int(ids[i]), hashlib.sha256(
                xs[i].tobytes()).hexdigest()))
    acc.sort()
    return hashlib.sha256(str(acc).encode()).hexdigest()


def _shuffle_extras(ds):
    for op in ds._last_stats.to_dict()["ops"]:
        if "shuffle_maps" in (op.get("extra") or {}):
            return op["extra"]
    raise AssertionError(
        f"no shuffle extras in stats: {ds._last_stats.to_dict()}")


# ---------------------------------------------------------------------------
# integration
# ---------------------------------------------------------------------------
def test_streaming_matches_legacy_byte_identical(shuffle_cluster, ctx):
    """Multi-node streaming shuffle == the single-path materializing
    exchange, row for row (sha256 over sorted rows)."""
    shuffle_cluster(n_nodes=2)
    ctx.streaming_shuffle = True
    ds1 = _payload_ds().random_shuffle(seed=7, num_blocks=8)
    sha_streaming = _rows_sha(ds1)
    extras = _shuffle_extras(ds1)
    assert extras["shuffle_maps"] == 8
    assert extras["shuffle_reducers"] == 8
    assert extras["shuffle_map_reexecs"] == 0

    ctx.streaming_shuffle = False
    ds2 = _payload_ds().random_shuffle(seed=7, num_blocks=8)
    sha_legacy = _rows_sha(ds2)
    assert sha_streaming == sha_legacy, \
        "streaming shuffle lost/duplicated/corrupted rows"


def test_reduce_overlaps_maps(shuffle_cluster, ctx):
    """No map→reduce barrier: the first reducer is admitted before the
    last map finishes, and the pipeline-stall fraction stays low."""
    shuffle_cluster(n_nodes=2)
    ctx.streaming_shuffle = True
    ds = _payload_ds(rows=8192, width=256).random_shuffle(
        seed=3, num_blocks=8)
    assert ds.count() == 8192
    extras = _shuffle_extras(ds)
    assert extras["shuffle_reduce_overlapped_maps"], extras
    assert extras["shuffle_stall_fraction"] < 0.9, extras


def test_sort_streaming_multi_node(shuffle_cluster, ctx):
    shuffle_cluster(n_nodes=2)
    ctx.streaming_shuffle = True
    ds = _payload_ds(rows=2000, parallelism=5, width=8)
    ids = [r["id"] for r in ds.sort("id").take_all()]
    assert ids == sorted(ids) and len(ids) == 2000
    desc = [r["id"] for r in ds.sort("id", descending=True).take(5)]
    assert desc == [1999, 1998, 1997, 1996, 1995]


def test_inflight_shard_bytes_bounded(shuffle_cluster, ctx):
    """Admitted-reducer input bytes never exceed the configured budget
    (a slow reducer backpressures admission, not memory)."""
    shuffle_cluster(n_nodes=2)
    ctx.streaming_shuffle = True
    ds0 = _payload_ds().random_shuffle(seed=5, num_blocks=8)
    assert ds0.count() == 4096
    total = _shuffle_extras(ds0)["shuffle_shard_bytes"]
    assert total > 0
    # budget: ~2 of 8 reducers' input bytes
    budget = max(1, total // 4)
    ctx.shuffle_max_inflight_shard_bytes = budget
    ds = _payload_ds().random_shuffle(seed=5, num_blocks=8)
    assert ds.count() == 4096
    extras = _shuffle_extras(ds)
    assert 0 < extras["shuffle_inflight_peak_bytes"] <= budget, extras


def test_executor_event_paced_and_prefetch_stats(ctx):
    """The drive loop parks on completions instead of busy-polling
    (~300 iters/s before): iterations stay O(task completions), and the
    consumer-side prefetch window reports its stall time."""
    ray_tpu.init(num_cpus=4)
    try:
        def slow(batch):
            time.sleep(0.25)
            return batch

        ds = rd.range(64, parallelism=8).map_batches(slow)
        rows = sum(1 for _ in ds.iter_rows())
        assert rows == 64
        st = ds._last_stats.to_dict()
        wall = st["wall_s"]
        assert wall > 0.4  # the sleeps actually serialized some work
        busy_poll_iters = wall / 0.003
        assert st["loop_iters"] < max(150, busy_poll_iters * 0.25), st
        assert st["idle_waits"] > 0, "loop never parked"
        assert st["blocks_consumed"] == 8
        assert st["consumer_stall_s"] >= 0.0
    finally:
        ray_tpu.shutdown()


def test_shuffle_task_bodies_never_import_jax(ctx):
    """Probe-asserted MULTICHIP contract: executing the map AND reduce
    bodies in a worker leaves jax unimported."""
    ray_tpu.init(num_cpus=2)
    try:
        @ray_tpu.remote
        def probe():
            import sys

            import numpy as _np

            import ray_tpu as rt
            from ray_tpu.data._internal.streaming_shuffle import (
                _shuffle_map_shards, _shuffle_reduce_shards)

            block = {"id": _np.arange(200),
                     "x": _np.random.default_rng(0).random((200, 8))}
            outs = _shuffle_map_shards(block, 4, seed=5, salt=0)
            refs = [rt.put(s) for s in outs[:-1]]
            blk, meta = _shuffle_reduce_shards([refs[0]], 0, seed=5)
            assert meta.num_rows == outs[-1][0][0]
            return "jax" in sys.modules

        assert ray_tpu.get(probe.remote(), timeout=120) is False
    finally:
        ray_tpu.shutdown()


# ---------------------------------------------------------------------------
# chaos: node death mid-shuffle
# ---------------------------------------------------------------------------
def test_node_death_mid_shuffle_recovers(shuffle_cluster, ctx):
    """kill -9 the agent of a node holding unique map shards while the
    reduce plane is mid-flight: the shuffle re-executes exactly the dead
    node's maps (same object ids via lineage) and completes
    byte-identical — no hang, re-execution counters > 0."""
    from ray_tpu.util.chaos import DaemonKiller

    cluster, nodes = shuffle_cluster(
        n_nodes=2, node_cpus=2,
        env={
            "RAY_TPU_PULL_DEAD_HOLDER_ROUNDS": "3",
            "RAY_TPU_OBJECT_PULL_DEADLINE_S": "90",
        },
        node_resources=[{"vic": 100}, {"vic": 100}],
        head_resources={"safe": 100})
    ctx.streaming_shuffle = True
    # maps pinned to the two "vic" nodes so every shard lives off-head;
    # reducers pinned to the head so REDUCE outputs survive the kill
    # (losing reduce outputs is driver-lineage territory — this test
    # exercises the operator-local slice: lost MAP shards); input blocks
    # are driver-owned (head store) and survive too
    ctx.shuffle_map_remote_args = {"resources": {"vic": 0.001}}
    ctx.shuffle_reduce_remote_args = {"resources": {"safe": 0.001}}

    rng = np.random.default_rng(42)
    # 2 KB rows -> ~130 KB shards: ABOVE the inline threshold, so every
    # shard is a plasma object on a vic node (losable by the kill)
    blocks = [{"id": np.arange(i * 512, (i + 1) * 512),
               "x": rng.random((512, 512)).astype(np.float32)}
              for i in range(8)]
    expected = []
    for b in blocks:
        for i in range(512):
            expected.append((int(b["id"][i]), hashlib.sha256(
                np.ascontiguousarray(b["x"][i]).tobytes()).hexdigest()))
    expected.sort()
    expected_sha = hashlib.sha256(str(expected).encode()).hexdigest()

    ds = rd.from_blocks(blocks).random_shuffle(seed=11, num_blocks=8)

    acc = []
    killed = False
    deadline = time.monotonic() + 240
    it = ds.iter_batches(batch_size=None, prefetch_batches=0)
    while True:
        assert time.monotonic() < deadline, "shuffle hung after the kill"
        try:
            batch = next(it)
        except StopIteration:
            break
        ids = np.asarray(batch["id"])
        xs = np.ascontiguousarray(np.asarray(batch["x"]))
        for i in range(len(ids)):
            acc.append((int(ids[i]), hashlib.sha256(
                xs[i].tobytes()).hexdigest()))
        if not killed:
            # first reduce output consumed -> the exchange is mid-flight;
            # SIGKILL one shard-holding node's agent now
            killed = True
            killer = DaemonKiller(cluster.session_dir, roles=("agent",),
                                  max_kills=1)
            record = killer.kill_target(
                {"role": "agent", "pid": nodes[0].agent_proc.pid})
            assert record is not None, "victim agent was not killed"

    assert killed
    acc.sort()
    got_sha = hashlib.sha256(str(acc).encode()).hexdigest()
    assert len(acc) == 8 * 512, f"lost rows: {len(acc)}"
    assert got_sha == expected_sha, "recovery corrupted or duplicated rows"
    extras = _shuffle_extras(ds)
    assert extras["shuffle_map_reexecs"] >= 1, extras
    # ISSUE 17 contract: a reducer pulling a lost shard triggers the
    # owner's lineage replay from inside its own get — the reduce task
    # recovers WITHOUT failing, so reduce retries stay 0 and the
    # driver-side reconstruction counter is the recovery signal
    from ray_tpu._private import worker as worker_mod

    assert worker_mod.global_worker._lineage.reconstructions >= 1
