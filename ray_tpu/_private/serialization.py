"""Object serialization.

Parity with the reference's serialization context (reference:
``python/ray/_private/serialization.py:110``): cloudpickle for arbitrary
Python, pickle protocol 5 out-of-band buffers for zero-copy of large arrays,
and custom reducers so ``ObjectRef`` / actor handles survive a trip through
task arguments with correct ownership bookkeeping.

TPU-first deviation: ``jax.Array`` values are serialized by pulling them to
host as numpy (device buffers cannot cross processes); on the read side the
numpy view aliases the shared-memory segment so ``jax.device_put`` can stream
straight from shm to HBM without an extra host copy.
"""

from __future__ import annotations

import io
import pickle
from typing import Any, Callable, List, Optional, Tuple

import cloudpickle

# Wire format of a sealed object:
#   [8-byte header][meta][payload buffers]
#   header = <u32 meta_len><u32 num_buffers>
#   meta   = pickled (protocol 5) bytes with out-of-band buffer placeholders
#   then for each buffer: <u64 length><raw bytes, 64-byte aligned>
import struct

_ALIGN = 64


def _align(n: int) -> int:
    return (n + _ALIGN - 1) & ~(_ALIGN - 1)


class SerializedObject:
    __slots__ = ("meta", "buffers")

    def __init__(self, meta: bytes, buffers: List[pickle.PickleBuffer]):
        self.meta = meta
        self.buffers = buffers

    def total_size(self) -> int:
        size = 8 + _align(len(self.meta))
        for b in self.buffers:
            size += 8 + _align(len(b.raw()))
        return size

    def write_into(self, view: memoryview) -> int:
        """Write the wire format into a writable memoryview; returns bytes used."""
        struct.pack_into("<II", view, 0, len(self.meta), len(self.buffers))
        off = 8
        view[off : off + len(self.meta)] = self.meta
        off += _align(len(self.meta))
        for b in self.buffers:
            raw = b.raw()
            struct.pack_into("<Q", view, off, len(raw))
            off += 8
            view[off : off + len(raw)] = raw
            off += _align(len(raw))
        return off

    def to_bytes(self) -> bytes:
        buf = bytearray(self.total_size())
        used = self.write_into(memoryview(buf))
        return bytes(buf[:used])


def _jax_array_reducer(arr):
    import numpy as np

    return (_restore_numpy, (np.asarray(arr),))


def _restore_numpy(np_arr):
    return np_arr


class _Pickler(cloudpickle.Pickler):
    def __init__(self, file, buffer_callback):
        super().__init__(file, protocol=5, buffer_callback=buffer_callback)

    def reducer_override(self, obj):
        # jax.Array must come to host before crossing a process boundary.
        tname = type(obj).__module__
        if tname.startswith("jaxlib") or tname.startswith("jax"):
            try:
                import jax

                if isinstance(obj, jax.Array):
                    return _jax_array_reducer(obj)
            except ImportError:
                pass
        # Delegate to cloudpickle's own override (functions/classes by value).
        return super().reducer_override(obj)


class SerializationContext:
    """Per-worker serialization context with pluggable reducers for refs."""

    def __init__(self):
        self._object_ref_reducer: Optional[Callable] = None
        self._actor_handle_reducer: Optional[Callable] = None
        self._out_of_band_threshold = 1024  # buffers below this are inlined

    def set_object_ref_reducer(self, reducer: Callable) -> None:
        self._object_ref_reducer = reducer

    def set_actor_handle_reducer(self, reducer: Callable) -> None:
        self._actor_handle_reducer = reducer

    def serialize(self, value: Any) -> SerializedObject:
        buffers: List[pickle.PickleBuffer] = []

        def buffer_cb(pb: pickle.PickleBuffer) -> bool:
            if len(pb.raw()) < self._out_of_band_threshold:
                return True  # inline small buffers into the pickle stream
            buffers.append(pb)
            return False

        file = io.BytesIO()
        pickler = _Pickler(file, buffer_cb)
        ctx = _reducer_context
        ctx.object_ref_reducer = self._object_ref_reducer
        ctx.actor_handle_reducer = self._actor_handle_reducer
        try:
            pickler.dump(value)
        finally:
            ctx.object_ref_reducer = None
            ctx.actor_handle_reducer = None
        return SerializedObject(file.getvalue(), buffers)

    def deserialize(self, data: memoryview) -> Any:
        meta_len, num_buffers = struct.unpack_from("<II", data, 0)
        off = 8
        meta = data[off : off + meta_len]
        off += _align(meta_len)
        buffers = []
        for _ in range(num_buffers):
            (blen,) = struct.unpack_from("<Q", data, off)
            off += 8
            buffers.append(data[off : off + blen])
            off += _align(blen)
        return pickle.loads(meta, buffers=buffers)


import threading


class _ReducerContext(threading.local):
    """Per-thread reducer state: concurrent serializations (actor threads,
    the IO loop, the driver thread) must not clobber each other's collected
    nested-ref lists."""

    def __init__(self):
        self.object_ref_reducer: Optional[Callable] = None
        self.actor_handle_reducer: Optional[Callable] = None
        self.collected_refs = None


_reducer_context = _ReducerContext()


def get_reducer_context() -> _ReducerContext:
    return _reducer_context


def dumps(value: Any) -> bytes:
    """Plain cloudpickle for control-plane payloads (functions, specs)."""
    return cloudpickle.dumps(value, protocol=5)


def loads(data: bytes) -> Any:
    return pickle.loads(data)
