"""Streaming multi-node shuffle on the device object plane (ISSUE 12).

Replaces the materialize-everything exchange for ``random_shuffle`` and
``sort``: the old ``AllToAllOperator`` bulk functions had every reducer
``ray_tpu.get`` EVERY map output and slice one shard — shuffle bytes
scaled O(M×R), reduce could not start until the barrier, and every block
crossed the wire as pickle.

Here the exchange is a single streaming ``PhysicalOperator``:

- **Per-shard map outputs.** Each map task returns R separate store
  objects (``num_returns=R+1``: R packed shards + one inline metadata
  list), each shard a contiguous uint8 array encoded by ``shard_codec``
  so it rides the ``ZeroCopyArray`` fast path. A reducer pulls only its
  own O(bytes/R) shards over the per-peer data channels.
- **Pipelined reduce.** Maps dispatch as input blocks arrive (sort first
  runs a pipelined sample pass, then fixes boundaries once). Reducers
  are admitted as soon as the first map's shards seal — no map→reduce
  barrier — with two admission gates: a CPU-reservation gate (blocked
  reducers must never occupy every cluster slot while maps still need
  one: that is a distributed deadlock) and a byte budget
  (``DataContext.shuffle_max_inflight_shard_bytes``) so a slow reducer
  backpressures admission instead of OOMing workers. The operator's
  held shard bytes also feed the executor's
  ``ResourceBudgetBackpressurePolicy`` via ``extra_usage_bytes``.
- **Recovery = thin client of ownership lineage (ISSUE 17).** Most
  losses never reach the operator any more: a reducer pulling a lost
  shard triggers the owner's chained lineage replay from inside its own
  ``get``. When a loss does surface here (reduce meta lost, or lineage
  evicted), the operator maps the hex back to the producing map record
  and calls ``Worker.recover_task_returns`` — the general machinery
  replays the map under its original task/object ids and recursively
  reconstructs a lost map INPUT too, so the operator keeps no recovery
  logic of its own beyond a fresh-dispatch fallback for lineage-less
  records; one node death degrades throughput instead of killing the
  job.

Map/reduce task bodies in this module run in shuffle workers and must
never import jax (MULTICHIP gate, probe-asserted in
tests/test_data_shuffle.py).
"""

from __future__ import annotations

import collections
import time
from typing import Any, Dict, List, Optional

import numpy as np

import ray_tpu
from ray_tpu._private import events as _ev
from ray_tpu.data.block import BlockAccessor
from ray_tpu.data._internal.physical import PhysicalOperator, RefBundle
from ray_tpu.data._internal.shard_codec import decode_shard, encode_shard
from ray_tpu.exceptions import ObjectLostError


# --------------------------------------------------------------------------
# map / reduce task bodies (run in workers; no jax, no driver state)
# --------------------------------------------------------------------------
def _shuffle_map_shards(block, n: int, seed: int, salt: int):
    """Partition one block into n packed shards + an inline size list."""
    acc = BlockAccessor(block)
    rows = acc.num_rows()
    # seed is ALWAYS concrete (the operator draws one for seedless
    # shuffles): re-execution after a node death must re-produce
    # byte-identical shards or recovery would corrupt the output
    rng = np.random.default_rng(seed + salt)
    assign = rng.integers(0, n, rows)
    perm = rng.permutation(rows)
    outs: List[Any] = []
    sizes: List[List[int]] = []
    for i in range(n):
        idx = perm[assign[perm] == i]
        packed = encode_shard(acc.take_indices(idx))
        sizes.append([int(len(idx)), int(packed.nbytes)])
        outs.append(packed)
    outs.append(sizes)
    return outs


def _sort_map_shards(block, key, boundaries, n: int):
    acc = BlockAccessor(block)
    first = key if isinstance(key, str) else key[0]
    col = acc.to_numpy_dict()[first]
    assign = np.searchsorted(boundaries, col, side="right")
    outs: List[Any] = []
    sizes: List[List[int]] = []
    for i in range(n):
        idx = np.nonzero(assign == i)[0]
        packed = encode_shard(acc.take_indices(idx))
        sizes.append([int(len(idx)), int(packed.nbytes)])
        outs.append(packed)
    outs.append(sizes)
    return outs


def _pull_shards(shard_refs):
    """Reducer-side shard fetch with a ``shard_pull`` flight-recorder
    slice: the single batched ``get`` resolves every borrow and starts
    every pull in one WaitObjects window, and when the enclosing task is
    sampled the pull time lands as its own nested slice (the "lease wait
    vs pull vs merge?" answer per reducer)."""
    rec = _ev.REC
    ctx = _ev.current_ctx() if rec.enabled else None
    if ctx is None:
        return ray_tpu.get(list(shard_refs))
    t0 = time.time()
    try:
        return ray_tpu.get(list(shard_refs))
    finally:
        rec.record("shard_pull", "data", t0, time.time() - t0,
                   ctx[0], rec.next_id(), ctx[1],
                   {"shards": len(shard_refs)})


def _shuffle_reduce_shards(shard_refs, i: int, seed: int):
    """Merge this reducer's M shards (see ``_pull_shards``)."""
    shards = [decode_shard(s) for s in _pull_shards(shard_refs)]
    out = BlockAccessor.concat(shards)
    acc = BlockAccessor(out)
    rng = np.random.default_rng(seed * 7919 + i)
    out = acc.take_indices(rng.permutation(acc.num_rows()))
    return out, BlockAccessor(out).metadata()


def _sort_reduce_shards(shard_refs, i: int, key, descending: bool):
    shards = [decode_shard(s) for s in _pull_shards(shard_refs)]
    out = BlockAccessor.concat(shards)
    acc = BlockAccessor(out)
    if acc.num_rows():
        out = acc.take_indices(acc.sort_indices(key, descending))
    return out, BlockAccessor(out).metadata()


def _sample_boundaries_task(block, key, k: int):
    acc = BlockAccessor(block)
    n = acc.num_rows()
    if n == 0:
        return np.asarray([])
    idx = np.linspace(0, n - 1, min(k, n)).astype(np.int64)
    col = acc.to_numpy_dict()[key if isinstance(key, str) else key[0]]
    return col[idx]


# --------------------------------------------------------------------------
# exchange strategies
# --------------------------------------------------------------------------
class _ShuffleAlgo:
    """How maps shard and reducers merge; the operator drives the rest."""

    needs_prepare = False

    def __init__(self, map_remote_args: Optional[Dict] = None,
                 reduce_remote_args: Optional[Dict] = None):
        self.map_remote_args = dict(map_remote_args or {})
        self.reduce_remote_args = dict(reduce_remote_args or {})

    def fixed_reducers(self) -> Optional[int]:
        return None  # None: R = number of input blocks, known at barrier

    # prepare stage (sort sampling); default: none
    def prepare_submit(self, block_ref):  # pragma: no cover - abstract
        raise NotImplementedError

    def finish_prepare(self, samples: List[Any]) -> None:
        pass

    def map_submit(self, block_ref, salt: int, n: int) -> List[Any]:
        raise NotImplementedError

    def map_submit_many(self, block_refs: List[Any], salts: List[int],
                        n: int) -> List[List[Any]]:
        """Vectorized map dispatch (ISSUE 18): one driver pass for a run
        of map tasks. Default falls back to per-call map_submit; algos
        override with ``fn.map`` so the whole run rides one id block /
        registration batch / wire frame. MUST be byte-identical to the
        sequential loop — same salts, same seed, same num_returns."""
        return [self.map_submit(b, s, n)
                for b, s in zip(block_refs, salts)]

    def reduce_submit(self, shard_refs, i: int):
        raise NotImplementedError

    def emit_order(self, n: int):
        return range(n)


class RandomShuffleAlgo(_ShuffleAlgo):
    def __init__(self, seed: Optional[int], num_blocks: Optional[int],
                 **kw):
        super().__init__(**kw)
        if seed is None:
            # draw once so map re-execution is deterministic
            import os as _os

            seed = int.from_bytes(_os.urandom(4), "little")
        self.seed = int(seed)
        self.num_blocks = num_blocks

    def fixed_reducers(self) -> Optional[int]:
        return self.num_blocks

    def map_submit(self, block_ref, salt: int, n: int):
        return ray_tpu.remote(_shuffle_map_shards).options(
            name="Data::ShuffleMap", num_returns=n + 1,
            **self.map_remote_args).remote(block_ref, n, self.seed, salt)

    def map_submit_many(self, block_refs, salts, n):
        from itertools import repeat

        return ray_tpu.remote(_shuffle_map_shards).options(
            name="Data::ShuffleMap", num_returns=n + 1,
            **self.map_remote_args).map(
                block_refs, repeat(n), repeat(self.seed), salts)

    def reduce_submit(self, shard_refs, i: int):
        return ray_tpu.remote(_shuffle_reduce_shards).options(
            name="Data::ShuffleReduce", num_returns=2,
            **self.reduce_remote_args).remote(
                list(shard_refs), i, self.seed)


class SortAlgo(_ShuffleAlgo):
    needs_prepare = True

    def __init__(self, key, descending: bool = False, **kw):
        super().__init__(**kw)
        self.key = key
        self.descending = descending
        self.boundaries: Optional[np.ndarray] = None

    def prepare_submit(self, block_ref):
        return ray_tpu.remote(_sample_boundaries_task).options(
            name="Data::SortSample", **self.map_remote_args).remote(
                block_ref, self.key, 20)

    def finish_prepare(self, samples: List[Any]) -> None:
        n = max(1, len(samples))
        allsamp = np.sort(np.concatenate(
            [s for s in samples if len(s)] or [np.asarray([])]))
        if len(allsamp) == 0:
            self.boundaries = np.asarray([])
            return
        q = np.linspace(0, len(allsamp) - 1, n + 1)[1:-1].astype(np.int64)
        self.boundaries = allsamp[q]

    def map_submit(self, block_ref, salt: int, n: int):
        return ray_tpu.remote(_sort_map_shards).options(
            name="Data::SortMap", num_returns=n + 1,
            **self.map_remote_args).remote(
                block_ref, self.key, self.boundaries, n)

    def map_submit_many(self, block_refs, salts, n):
        # salt does not enter the sort map; arg order matches map_submit
        from itertools import repeat

        return ray_tpu.remote(_sort_map_shards).options(
            name="Data::SortMap", num_returns=n + 1,
            **self.map_remote_args).map(
                block_refs, repeat(self.key), repeat(self.boundaries),
                repeat(n))

    def reduce_submit(self, shard_refs, i: int):
        return ray_tpu.remote(_sort_reduce_shards).options(
            name="Data::SortReduce", num_returns=2,
            **self.reduce_remote_args).remote(
                list(shard_refs), i, self.key, self.descending)

    def emit_order(self, n: int):
        return range(n - 1, -1, -1) if self.descending else range(n)


# --------------------------------------------------------------------------
# operator
# --------------------------------------------------------------------------
class _MapRec:
    __slots__ = ("bundle", "salt", "shard_refs", "meta_ref", "done",
                 "sizes", "reexecs", "reexec_inflight", "t0")

    def __init__(self, bundle: RefBundle, salt: int, refs):
        self.t0 = time.time()
        self.bundle = bundle
        self.salt = salt
        self.shard_refs = list(refs[:-1])
        self.meta_ref = refs[-1]
        self.done = False
        self.sizes: Optional[List[List[int]]] = None  # [rows, nbytes] per shard
        self.reexecs = 0
        self.reexec_inflight = False


class _ReduceRec:
    __slots__ = ("index", "block_ref", "meta_ref", "running", "done",
                 "bundle", "attempts", "bytes_in", "t0")

    def __init__(self, index: int):
        self.t0 = 0.0
        self.index = index
        self.block_ref = None
        self.meta_ref = None
        self.running = False
        self.done = False
        self.bundle: Optional[RefBundle] = None
        self.attempts = 0
        self.bytes_in = 0


class StreamingShuffleOperator(PhysicalOperator):
    """Pipelined map/shuffle/reduce exchange (see module docstring)."""

    def __init__(self, name: str, algo: _ShuffleAlgo):
        super().__init__(name)
        from ray_tpu.data.context import DataContext

        ctx = DataContext.get_current()
        self.algo = algo
        self.max_concurrency = ctx.shuffle_max_concurrency
        self._budget = ctx.shuffle_max_inflight_shard_bytes
        self._max_retries = ctx.shuffle_max_reduce_retries
        self._n: Optional[int] = algo.fixed_reducers()
        self._maps: List[_MapRec] = []
        self._map_ready: collections.deque = collections.deque()
        self._parked: List[RefBundle] = []  # awaiting R / boundaries
        self._prepare_pending: List[Any] = []  # outstanding sample refs
        self._prepare_results: List[Any] = []
        self._prepare_done = not algo.needs_prepare
        # shard ids retired by a fresh (non-lineage) map re-dispatch: a
        # reduce already in flight can still fail on one; its retry reads
        # the CURRENT refs, so the loss needs no further action
        self._retired_shards: set = set()
        self._reducers: Optional[List[_ReduceRec]] = None
        self._emit_order: Optional[List[int]] = None
        self._emit_pos = 0
        self._cluster_cpus = self._total_cpus()
        # counters surfaced through stats_extras() / ExecutorStats
        self.map_reexecs = 0
        self.reduce_retries = 0
        self.shard_bytes_total = 0
        self.shard_inflight_peak = 0
        # incremental store-held shard accounting: += full map output on
        # its FIRST completion, -= that map's shard for each reducer
        # that finishes. extra_usage_bytes() is consulted by the
        # backpressure chain once per dispatch — recomputing an O(M*R)
        # walk there would make the scheduling loop quadratic
        self._held_shard_bytes = 0
        self._t_map_first_done = 0.0
        self._t_map_last_done = 0.0
        self._t_reduce_first_admit = 0.0
        self._t_start = time.perf_counter()
        # flight recorder (ISSUE 14): one sampled trace per exchange;
        # every map/reduce task submitted under trace_parent joins it, so
        # `ray_tpu trace` shows map -> shard_pull -> reduce as one tree
        self._trace = (_ev.REC.new_trace()
                       if _ev.REC.enabled and _ev.REC.sample() else None)
        self._trace_t0 = time.time()
        self._trace_closed = False
        # Most shard losses resolve inside the owner's pull path now
        # (ISSUE 17) and never reach _recover_lost — subscribe to the
        # ledger's replay feed so a lineage re-execution of one of OUR
        # maps still shows up in map_reexecs. Weakly held: this exchange
        # dying IS the unsubscribe.
        from ray_tpu._private import worker as worker_mod
        w = worker_mod.global_worker
        if w is not None:
            w._lineage.add_listener(self._on_lineage_replay)

    # ------------------------------------------------------------ helpers
    @staticmethod
    def _total_cpus() -> float:
        try:
            return float(ray_tpu.cluster_resources().get("CPU") or 4.0)
        except Exception:
            return 4.0

    def _maps_all_dispatched(self) -> bool:
        return (self.inputs_complete and not self.input_queue
                and not self._map_ready and not self._parked
                and not self._prepare_pending and self._prepare_done)

    def _maps_done(self) -> int:
        return sum(1 for m in self._maps if m.done)

    def _maps_all_done(self) -> bool:
        return self._maps_all_dispatched() and all(
            m.done for m in self._maps)

    def _running_reducers(self) -> int:
        if not self._reducers:
            return 0
        return sum(1 for r in self._reducers if r.running and not r.done)

    def num_active_tasks(self) -> int:
        maps_running = sum(1 for m in self._maps if not m.done)
        return (maps_running + len(self._prepare_pending)
                + self._running_reducers())

    # ------------------------------------------------- admission decisions
    def _reduce_slots(self) -> int:
        """Concurrent-reducer cap. While maps are still executing,
        reserve CPU slots for them: an admitted reducer BLOCKS on shards
        the remaining maps have yet to produce, so reducers occupying
        every cluster slot would deadlock the exchange (reducers wait on
        maps, maps wait on CPUs)."""
        if self._maps_all_done():
            return self.max_concurrency
        reserve = max(1.0, min(
            float(len(self._maps) - self._maps_done()) or 1.0,
            self._cluster_cpus // 2))
        return int(min(self.max_concurrency,
                       max(0.0, self._cluster_cpus - reserve)))

    def _reducer_bytes_estimate(self, idx: int) -> int:
        """Input bytes of reducer ``idx``: exact for finished maps,
        mean-shard estimate for the rest."""
        known = 0
        known_maps = 0
        for m in self._maps:
            if m.sizes is not None:
                known += m.sizes[idx][1]
                known_maps += 1
        if known_maps and known_maps < len(self._maps):
            known += int(known / known_maps) * (len(self._maps) - known_maps)
        return known

    def _inflight_reduce_bytes(self) -> int:
        if not self._reducers:
            return 0
        return sum(r.bytes_in for r in self._reducers
                   if r.running and not r.done)

    def _admittable_reducer(self) -> Optional[_ReduceRec]:
        if self._reducers is None or not self._maps_all_dispatched():
            return None
        if self._maps and self._maps_done() == 0:
            return None  # admit as the first map's shards seal
        running = self._running_reducers()
        if running >= self._reduce_slots():
            return None
        # admit in EMIT order: a descending sort emits n-1..0, and
        # admitting 0..n-1 would make the first emittable output the
        # LAST admitted reducer — re-creating the barrier
        for idx in (self._emit_order or ()):
            r = self._reducers[idx]
            if r.running or r.done:
                continue
            est = self._reducer_bytes_estimate(r.index)
            if (self._budget > 0 and running > 0
                    and self._inflight_reduce_bytes() + est > self._budget):
                return None  # budget: backpressure admission, never stall
            return r
        return None

    # --------------------------------------------------------- scheduling
    def can_dispatch(self) -> bool:
        if self.input_queue:
            return True
        if self._map_ready:
            return True
        return self._admittable_reducer() is not None

    def dispatch(self) -> None:
        # Priority: drain (admit a reducer) over fill (launch a map) —
        # with the byte budget this is what makes a slow reducer
        # backpressure the map side instead of growing the store.
        red = self._admittable_reducer()
        if red is not None:
            self._admit_reduce(red)
            return
        if self._map_ready:
            # the plan is fixed by the time _map_ready fills (every map
            # must launch before any reducer is admitted), so the whole
            # run rides ONE vectorized submission (ISSUE 18)
            self._dispatch_map_batch()
            return
        if self.input_queue:
            bundle = self.input_queue.popleft()
            if self.algo.needs_prepare:
                self._prepare_pending.append(
                    self.algo.prepare_submit(bundle.block_ref))
                self._parked.append(bundle)
                self.tasks_launched += 1
            elif self._n is None:
                self._parked.append(bundle)
            else:
                self._dispatch_map(bundle)

    def _dispatch_map(self, bundle: RefBundle) -> None:
        salt = len(self._maps)
        with _ev.trace_parent(self._trace):
            refs = self.algo.map_submit(bundle.block_ref, salt, self._n)
        self.tasks_launched += 1
        self._maps.append(_MapRec(bundle, salt, refs))

    def _dispatch_map_batch(self) -> None:
        bundles = list(self._map_ready)
        self._map_ready.clear()
        if len(bundles) == 1:
            self._dispatch_map(bundles[0])
            return
        # sequential salts in list order — byte-identical to the popleft
        # loop this replaces (the sha256 asserts in scale_bench hold)
        base = len(self._maps)
        salts = [base + i for i in range(len(bundles))]
        with _ev.trace_parent(self._trace):
            refs_list = self.algo.map_submit_many(
                [b.block_ref for b in bundles], salts, self._n)
        self.tasks_launched += len(bundles)
        for bundle, salt, refs in zip(bundles, salts, refs_list):
            self._maps.append(_MapRec(bundle, salt, refs))

    def _admit_reduce(self, r: _ReduceRec) -> None:
        shard_refs = [m.shard_refs[r.index] for m in self._maps]
        with _ev.trace_parent(self._trace):
            r.block_ref, r.meta_ref = self.algo.reduce_submit(
                shard_refs, r.index)
        r.t0 = time.time()
        r.bytes_in = self._reducer_bytes_estimate(r.index)
        r.running = True
        self.tasks_launched += 1
        if not self._t_reduce_first_admit:
            self._t_reduce_first_admit = time.perf_counter()
        inflight = self._inflight_reduce_bytes()
        if inflight > self.shard_inflight_peak:
            self.shard_inflight_peak = inflight

    # -------------------------------------------------------------- poll
    def poll(self) -> None:
        self._poll_prepares()
        self._maybe_fix_plan()
        self._poll_maps()
        self._poll_reduces()
        self._emit_ready()

    def _poll_prepares(self) -> None:
        if not self._prepare_pending:
            return
        ready, not_ready = ray_tpu.wait(
            self._prepare_pending, num_returns=len(self._prepare_pending),
            timeout=0)
        if not ready:
            return
        # sample order is irrelevant (finish_prepare sorts the union)
        self._prepare_results.extend(ray_tpu.get(ready))
        self._prepare_pending = not_ready

    def _maybe_fix_plan(self) -> None:
        """Once every input has arrived (and, for sort, every sample has
        landed), fix R and release the parked bundles to the map stage."""
        if self._n is not None and self._prepare_done:
            if self._reducers is None and self._maps_all_dispatched() \
                    and not self._map_ready:
                self._make_reducers()
            return
        if not (self.inputs_complete and not self.input_queue):
            return
        if self.algo.needs_prepare and not self._prepare_done:
            if self._prepare_pending:
                return
            self.algo.finish_prepare(self._prepare_results)
            self._prepare_done = True
        if self._n is None:
            self._n = len(self._parked) + len(self._maps)
        self._map_ready.extend(self._parked)
        self._parked = []

    def _make_reducers(self) -> None:
        # zero input blocks -> zero outputs (the legacy exchange's `if
        # not bundles: return []`), even with a fixed num_blocks: R
        # no-op reducers would hand the consumer R empty batches
        n = self._n if self._maps else 0
        self._reducers = [_ReduceRec(i) for i in range(n)]
        self._emit_order = list(self.algo.emit_order(n)) if n else []

    def _poll_maps(self) -> None:
        pending = [m for m in self._maps if not m.done]
        if not pending:
            return
        metas = [m.meta_ref for m in pending]
        ready, _ = ray_tpu.wait(metas, num_returns=len(metas), timeout=0)
        if not ready:
            return
        ready_set = set(ready)
        done_maps = [m for m in pending if m.meta_ref in ready_set]
        try:
            sizes = ray_tpu.get([m.meta_ref for m in done_maps])
        except ObjectLostError as e:
            self._recover_lost(e.object_id_hex)
            return
        now = time.perf_counter()
        done_idx = {r.index for r in (self._reducers or []) if r.done}
        for m, sz in zip(done_maps, sizes):
            first_completion = m.sizes is None
            m.done = True
            m.reexec_inflight = False
            m.sizes = sz
            if first_completion:
                self.shard_bytes_total += sum(s[1] for s in sz)
                self._held_shard_bytes += sum(
                    s[1] for i, s in enumerate(sz) if i not in done_idx)
                if self._trace is not None:
                    _ev.REC.record(
                        "shuffle_map", "data", m.t0, time.time() - m.t0,
                        self._trace[0], _ev.REC.next_id(), self._trace[1],
                        {"salt": m.salt,
                         "bytes": int(sum(x[1] for x in sz))})
        if not self._t_map_first_done:
            self._t_map_first_done = now
        self._t_map_last_done = now

    def _poll_reduces(self) -> None:
        if not self._reducers:
            return
        running = [r for r in self._reducers if r.running and not r.done]
        if not running:
            return
        metas = [r.meta_ref for r in running]
        ready, _ = ray_tpu.wait(metas, num_returns=len(metas), timeout=0)
        if not ready:
            return
        ready_set = set(ready)
        for r in running:
            if r.meta_ref not in ready_set:
                continue
            try:
                meta = ray_tpu.get(r.meta_ref)
            except ObjectLostError as e:
                self._retry_reduce(r, e.object_id_hex)
                continue
            r.done = True
            r.running = False
            if self._trace is not None:
                _ev.REC.record(
                    "shuffle_reduce", "data", r.t0, time.time() - r.t0,
                    self._trace[0], _ev.REC.next_id(), self._trace[1],
                    {"index": r.index, "bytes": int(r.bytes_in)})
            r.bundle = RefBundle(r.block_ref, meta)
            for m in self._maps:
                if m.sizes is not None:
                    self._held_shard_bytes -= m.sizes[r.index][1]
            # NOTE: shard refs are kept until the operator dies (end of
            # execution), NOT freed per-reducer: a reduce OUTPUT block
            # lost after emission re-executes its reduce through normal
            # driver lineage, and that rerun must still find its input
            # shards owned. Store pressure is handled by tiered spill;
            # the refs die with the topology.

    # ---------------------------------------------------------- recovery
    def _retry_reduce(self, r: _ReduceRec, lost_hex: str) -> None:
        r.attempts += 1
        self.reduce_retries += 1
        if r.attempts > self._max_retries:
            raise ObjectLostError(
                lost_hex,
                f"lost and shuffle recovery exhausted after "
                f"{r.attempts - 1} map re-executions")
        self._recover_lost(lost_hex)
        shard_refs = [m.shard_refs[r.index] for m in self._maps]
        r.block_ref, r.meta_ref = self.algo.reduce_submit(
            shard_refs, r.index)
        self.tasks_launched += 1

    def _recover_lost(self, lost_hex: str) -> None:
        """Map a lost object id back to the map record that produced (or
        consumed) it and replay that map through the general lineage
        machinery. A lost map INPUT needs no special casing any more:
        ``Worker._recover_chain`` recursively reconstructs lost owned
        arguments before resubmitting, so one call covers the chain."""
        from ray_tpu._private import worker as worker_mod

        if lost_hex in self._retired_shards:
            return  # already re-dispatched fresh; retries read current refs
        w = worker_mod.global_worker
        for m in self._maps:
            if (m.bundle.block_ref.hex() == lost_hex
                    or any(ref is not None and ref.hex() == lost_hex
                           for ref in m.shard_refs)):
                self._reexec_map(w, m)
                return
        raise ObjectLostError(
            lost_hex, "lost and not produced by this shuffle")

    def _reexec_map(self, w, m: _MapRec) -> None:
        if m.reexec_inflight:
            return  # one re-execution covers every lost shard of this map
        m.reexecs += 1
        if m.reexecs > self._max_retries:
            raise ObjectLostError(
                m.shard_refs[0].hex(),
                f"lost; map re-executed {m.reexecs - 1} times without "
                "sticking")
        # general machinery (ISSUE 17): resubmits the map under its
        # original task/object ids, replay-seeded for byte-identical
        # shards, recursively reconstructing lost inputs; returns False
        # (never raises here) when the record is evicted or opted out
        recovered = False
        if w is not None:
            recovered = w.recover_task_returns(m.meta_ref)
        if not recovered:
            # lineage record gone (or retries opted out): fresh dispatch
            # under new object ids; reducers re-read current refs on
            # their own retry
            for ref in m.shard_refs:
                if ref is not None:
                    self._retired_shards.add(ref.hex())
            refs = self.algo.map_submit(m.bundle.block_ref, m.salt,
                                        self._n)
            m.shard_refs = list(refs[:-1])
            m.meta_ref = refs[-1]
            self.tasks_launched += 1
            # the lineage path is counted by _on_lineage_replay (the
            # ledger notifies on resubmit); only the fresh dispatch
            # needs a manual bump or map_reexecs would double-count
            self.map_reexecs += 1
        m.done = False
        m.reexec_inflight = True

    def _on_lineage_replay(self, task_binary: bytes) -> None:
        """Ledger callback: the owner resubmitted ``task_binary`` from
        lineage. When it is one of our maps the map genuinely ran again
        — whether we asked (_reexec_map) or a reducer's failed pull
        triggered it behind our back — so it belongs in map_reexecs."""
        for m in self._maps:
            if m.meta_ref is not None and \
                    m.meta_ref.id().task_id().binary() == task_binary:
                self.map_reexecs += 1
                return

    # -------------------------------------------------------------- emit
    def _emit_ready(self) -> None:
        if not self._reducers or self._emit_order is None:
            return
        while self._emit_pos < len(self._emit_order):
            r = self._reducers[self._emit_order[self._emit_pos]]
            if not r.done:
                return
            self._emit(r.bundle)
            r.bundle = None
            self._emit_pos += 1

    def completed(self) -> bool:
        if self._n == 0 and self.inputs_complete and not self.input_queue:
            done = True
        else:
            done = (self._reducers is not None
                    and self._emit_order is not None
                    and self._emit_pos >= len(self._emit_order))
        if done and self._trace is not None and not self._trace_closed:
            self._trace_closed = True
            _ev.REC.record(
                "shuffle::" + self.name, "data", self._trace_t0,
                time.time() - self._trace_t0, self._trace[0],
                self._trace[1], 0,
                {"maps": len(self._maps),
                 "reducers": len(self._reducers or [])})
        return done

    # ------------------------------------------------------------- stats
    def extra_usage_bytes(self) -> int:
        """Shard bytes this exchange currently holds in the store plane:
        sealed map outputs whose reducer has not finished (incremental
        counter — see __init__). Feeds the
        ResourceBudgetBackpressurePolicy's global accounting."""
        return max(0, self._held_shard_bytes)

    def stats_extras(self) -> Dict[str, Any]:
        wall = max(time.perf_counter() - self._t_start, 1e-9)
        if self._t_reduce_first_admit and self._t_map_first_done:
            stall = max(0.0, self._t_reduce_first_admit
                        - self._t_map_first_done) / wall
        else:
            stall = 1.0 if self._maps else 0.0
        return {
            "shuffle_maps": len(self._maps),
            "shuffle_reducers": self._n or 0,
            "shuffle_map_reexecs": self.map_reexecs,
            "shuffle_reduce_retries": self.reduce_retries,
            "shuffle_shard_bytes": self.shard_bytes_total,
            "shuffle_inflight_peak_bytes": self.shard_inflight_peak,
            "shuffle_stall_fraction": round(stall, 4),
            "shuffle_reduce_overlapped_maps": bool(
                self._t_reduce_first_admit and self._t_map_last_done
                and self._t_reduce_first_admit < self._t_map_last_done),
        }


def build_streaming_shuffle(op) -> StreamingShuffleOperator:
    """Planner entry: logical AbstractAllToAll -> streaming operator."""
    from ray_tpu.data.context import DataContext

    ctx = DataContext.get_current()
    kw = op.kwargs
    common = dict(map_remote_args=ctx.shuffle_map_remote_args,
                  reduce_remote_args=ctx.shuffle_reduce_remote_args)
    if op.kind == "random_shuffle":
        algo = RandomShuffleAlgo(kw.get("seed"), kw.get("num_blocks"),
                                 **common)
    elif op.kind == "sort":
        algo = SortAlgo(kw["key"], kw.get("descending", False), **common)
    else:  # pragma: no cover - planner routes only the two kinds here
        raise ValueError(f"no streaming exchange for {op.kind!r}")
    return StreamingShuffleOperator(op.name, algo)
