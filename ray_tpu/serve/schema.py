"""Serve config schema (reference: python/ray/serve/schema.py — pydantic
ServeDeploySchema / ServeApplicationSchema / DeploymentSchema; dataclasses
here, same shape on the wire).

The declarative path mirrors the reference's ``serve build`` →
``serve deploy``: an application is named by an ``import_path``
("module:attr" resolving to a bound ``Application``), with per-deployment
option overrides applied at deploy time. ``serve.build()`` emits this
schema from a live ``Application``; ``serve.run_config()`` (and the
dashboard's ``PUT /api/serve/applications``) consumes it.
"""

from __future__ import annotations

import dataclasses
import importlib
from typing import Any, Dict, List, Optional


@dataclasses.dataclass
class DeploymentSchema:
    """Option overrides for one deployment (reference: DeploymentSchema)."""

    name: str
    num_replicas: Optional[int] = None
    max_ongoing_requests: Optional[int] = None
    max_queued_requests: Optional[int] = None
    user_config: Optional[Dict] = None
    autoscaling_config: Optional[Dict] = None
    ray_actor_options: Optional[Dict] = None
    health_check_period_s: Optional[float] = None
    graceful_shutdown_timeout_s: Optional[float] = None

    def to_dict(self) -> Dict:
        return {k: v for k, v in dataclasses.asdict(self).items()
                if v is not None}

    @classmethod
    def from_dict(cls, d: Dict) -> "DeploymentSchema":
        known = {f.name for f in dataclasses.fields(cls)}
        return cls(**{k: v for k, v in d.items() if k in known})


@dataclasses.dataclass
class ServeApplicationSchema:
    """One application (reference: ServeApplicationSchema)."""

    import_path: str = ""
    name: str = "default"
    route_prefix: str = "/"
    args: Optional[Dict] = None
    runtime_env: Optional[Dict] = None
    deployments: List[DeploymentSchema] = dataclasses.field(
        default_factory=list)

    def to_dict(self) -> Dict:
        d = {"name": self.name, "route_prefix": self.route_prefix,
             "import_path": self.import_path,
             "deployments": [dp.to_dict() for dp in self.deployments]}
        if self.args:
            d["args"] = self.args
        if self.runtime_env:
            d["runtime_env"] = self.runtime_env
        return d

    @classmethod
    def from_dict(cls, d: Dict) -> "ServeApplicationSchema":
        return cls(
            import_path=d.get("import_path", ""),
            name=d.get("name", "default"),
            route_prefix=d.get("route_prefix", "/"),
            args=d.get("args"),
            runtime_env=d.get("runtime_env"),
            deployments=[DeploymentSchema.from_dict(x)
                         for x in d.get("deployments", [])],
        )

    def resolve(self):
        """Import and return the bound Application, applying overrides."""
        from ray_tpu.serve.deployment import Application

        if not self.import_path:
            raise ValueError(
                f"application {self.name!r} has no import_path; "
                "serve.build() output needs import_path=\"module:attr\" "
                "filled in before it can be deployed declaratively")
        if ":" in self.import_path:
            mod_name, attr = self.import_path.split(":", 1)
        else:
            mod_name, attr = self.import_path.rsplit(".", 1)
        target = getattr(importlib.import_module(mod_name), attr)
        if callable(target) and not isinstance(target, Application):
            target = target(**(self.args or {}))  # app builder function
        if not isinstance(target, Application):
            raise TypeError(
                f"{self.import_path} resolved to {type(target).__name__}, "
                "expected a bound Application (deployment.bind(...))")
        overrides = {d.name: d for d in self.deployments}
        for node in target.walk():
            ov = overrides.get(node.deployment.name)
            if ov is None:
                continue
            opts = {k: v for k, v in ov.to_dict().items() if k != "name"}
            if opts:
                node.deployment = node.deployment.options(**opts)
        return target


@dataclasses.dataclass
class HTTPOptionsSchema:
    host: str = "127.0.0.1"
    port: int = 8000

    def to_dict(self) -> Dict:
        return dataclasses.asdict(self)

    @classmethod
    def from_dict(cls, d: Dict) -> "HTTPOptionsSchema":
        return cls(host=d.get("host", "127.0.0.1"),
                   port=d.get("port", 8000))


@dataclasses.dataclass
class ServeDeploySchema:
    """Top-level multi-app config (reference: ServeDeploySchema — the
    ``serve deploy`` document)."""

    applications: List[ServeApplicationSchema] = dataclasses.field(
        default_factory=list)
    http_options: HTTPOptionsSchema = dataclasses.field(
        default_factory=HTTPOptionsSchema)

    def to_dict(self) -> Dict:
        return {"http_options": self.http_options.to_dict(),
                "applications": [a.to_dict() for a in self.applications]}

    @classmethod
    def from_dict(cls, d: Dict) -> "ServeDeploySchema":
        return cls(
            applications=[ServeApplicationSchema.from_dict(a)
                          for a in d.get("applications", [])],
            http_options=HTTPOptionsSchema.from_dict(
                d.get("http_options", {})),
        )


def build_app_schema(app, *, name: str = "default",
                     route_prefix: str = "/",
                     import_path: str = "") -> ServeApplicationSchema:
    """``serve.build`` analog: snapshot a bound Application's deployment
    options into a declarative schema (reference: serve build CLI)."""
    deployments = []
    for node in app.walk():
        d = node.deployment
        auto = d.autoscaling_config
        deployments.append(DeploymentSchema(
            name=d.name,
            num_replicas=d.num_replicas,
            max_ongoing_requests=d.max_ongoing_requests,
            max_queued_requests=d.max_queued_requests,
            user_config=d.user_config,
            autoscaling_config=dict(auto.__dict__) if auto else None,
            ray_actor_options=d.ray_actor_options or None,
            health_check_period_s=d.health_check_period_s,
            graceful_shutdown_timeout_s=d.graceful_shutdown_timeout_s,
        ))
    return ServeApplicationSchema(
        import_path=import_path, name=name, route_prefix=route_prefix,
        deployments=deployments)
