"""Remaining accelerator families (reference:
python/ray/_private/accelerators/{amd_gpu,intel_gpu,neuron,hpu,npu}.py) —
detection + visibility env vars so clusters mixing hardware advertise the
same custom resources the reference does. None of these devices exist in a
TPU deployment, so detection returns 0 unless the standard env overrides
say otherwise; the value is API parity for schedulers and tooling."""

from __future__ import annotations

import os
from typing import Dict, List, Optional

from ray_tpu._private.accelerators.accelerator import AcceleratorManager


def _env_count(var: str) -> int:
    try:
        return int(os.environ.get(var, "0"))
    except ValueError:
        return 0


class _SimpleManager(AcceleratorManager):
    RESOURCE = ""
    VISIBLE_ENV = ""
    COUNT_ENV = ""

    @classmethod
    def get_resource_name(cls) -> str:
        return cls.RESOURCE

    @classmethod
    def get_visible_accelerator_ids_env_var(cls) -> str:
        return cls.VISIBLE_ENV

    @classmethod
    def get_current_node_num_accelerators(cls) -> int:
        return _env_count(cls.COUNT_ENV)

    @classmethod
    def set_visible_accelerator_ids(cls, ids: List[int]) -> None:
        os.environ[cls.VISIBLE_ENV] = ",".join(str(i) for i in ids)

    @classmethod
    def get_current_node_additional_resources(cls) -> Dict[str, float]:
        return {}


class AMDGPUAcceleratorManager(_SimpleManager):
    """reference: accelerators/amd_gpu.py (HIP_VISIBLE_DEVICES)."""

    RESOURCE = "GPU"
    VISIBLE_ENV = "HIP_VISIBLE_DEVICES"
    COUNT_ENV = "RAY_TPU_NUM_AMD_GPUS"


class IntelGPUAcceleratorManager(_SimpleManager):
    """reference: accelerators/intel_gpu.py (ONEAPI_DEVICE_SELECTOR)."""

    RESOURCE = "GPU"
    VISIBLE_ENV = "ONEAPI_DEVICE_SELECTOR"
    COUNT_ENV = "RAY_TPU_NUM_INTEL_GPUS"


class NeuronAcceleratorManager(_SimpleManager):
    """reference: accelerators/neuron.py (NEURON_RT_VISIBLE_CORES)."""

    RESOURCE = "neuron_cores"
    VISIBLE_ENV = "NEURON_RT_VISIBLE_CORES"
    COUNT_ENV = "RAY_TPU_NUM_NEURON_CORES"


class HPUAcceleratorManager(_SimpleManager):
    """reference: accelerators/hpu.py (HABANA_VISIBLE_MODULES)."""

    RESOURCE = "HPU"
    VISIBLE_ENV = "HABANA_VISIBLE_MODULES"
    COUNT_ENV = "RAY_TPU_NUM_HPUS"


class NPUAcceleratorManager(_SimpleManager):
    """reference: accelerators/npu.py (ASCEND_RT_VISIBLE_DEVICES)."""

    RESOURCE = "NPU"
    VISIBLE_ENV = "ASCEND_RT_VISIBLE_DEVICES"
    COUNT_ENV = "RAY_TPU_NUM_NPUS"
