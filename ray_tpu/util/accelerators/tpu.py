"""TPU pod-slice scheduling helpers (reference: the slice-head fan-out
pattern documented at _private/accelerators/tpu.py:356-369 — schedule one
task on the ``TPU-{pod_type}-head`` resource, then one per host on the
``{slice_name}`` resource)."""

from __future__ import annotations

from typing import List, Optional


def pod_slice_head_resource(pod_type: str) -> str:
    """Custom resource advertised only on worker 0 of a slice."""
    return f"TPU-{pod_type}-head"


def pod_slice_resource(slice_name: str) -> str:
    """Custom resource advertised on every host of a slice."""
    return slice_name


def slice_hosts(pod_type: str) -> Optional[int]:
    """Host count of a slice type, e.g. 'v5e-64' with 4 chips/host -> 16."""
    from ray_tpu._private.accelerators.tpu import TPUAcceleratorManager

    chips_per_host = TPUAcceleratorManager.chips_per_host_for_topology(
        pod_type)
    if not chips_per_host or "-" not in pod_type:
        return None
    try:
        total = int(pod_type.rsplit("-", 1)[1])
    except ValueError:
        return None
    return max(1, total // chips_per_host)


def reserve_tpu_slice(pod_type: str, timeout_s: float = 300.0) -> List:
    """The multi-host SPMD launch pattern: run a probe task on the slice
    head to learn the slice name, then return one remote-options dict per
    host so the caller can fan one worker task out to every host:

        opts = reserve_tpu_slice("v5e-64")
        refs = [train_task.options(**o).remote(...) for o in opts]
    """
    import ray_tpu

    head_res = pod_slice_head_resource(pod_type)

    @ray_tpu.remote(resources={head_res: 1})
    def probe_slice():
        import os

        from ray_tpu._private.accelerators.tpu import ENV_SLICE_NAME

        return os.environ.get(ENV_SLICE_NAME, "")

    ref = probe_slice.remote()
    try:
        slice_name = ray_tpu.get(ref, timeout=timeout_s)
    except Exception:
        try:  # don't leave an infeasible probe queued forever
            ray_tpu.cancel(ref, force=True)
        except Exception:
            pass
        raise
    if not slice_name:
        raise RuntimeError(
            f"slice head for {pod_type} reachable but {pod_type} slice "
            "name is not set (TPU_NAME)")
    hosts = slice_hosts(pod_type) or 1
    return [{"resources": {pod_slice_resource(slice_name): 1}}
            for _ in range(hosts)]
