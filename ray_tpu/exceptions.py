"""Public exception hierarchy.

Parity with the reference's exception surface (reference:
``python/ray/exceptions.py``): task errors wrap the remote traceback and
re-raise at ``get``; actor death, object loss and store pressure each have a
distinct type so user retry logic can discriminate.
"""

from __future__ import annotations

import pickle
import time
import traceback
from typing import Dict, List, Optional, Tuple


class RayTpuError(Exception):
    """Base class for all framework errors."""


class DeathContext:
    """Structured failure provenance carried by death-class exceptions.

    Built once where a failure is *detected* (usually the GCS) and handed
    through every propagation hop unchanged, so the exception a driver
    finally catches answers "which node, which incarnation, why, and
    when" — not just a flattened message string. Plain-data only (str /
    int / float tuples) so it survives pickle, msgpack-adjacent wire
    dicts, and the framework serializer identically.
    """

    __slots__ = ("node_id", "incarnation", "reason", "timeline")

    def __init__(self, node_id: str = "", incarnation: int = 0,
                 reason: str = "",
                 timeline: Optional[List[Tuple[float, str]]] = None):
        self.node_id = node_id or ""
        self.incarnation = int(incarnation or 0)
        # normalize to plain (float, str) tuples: wire dicts arrive as lists
        self.reason = reason or ""
        self.timeline = [(float(t), str(ev)) for t, ev in (timeline or [])]

    def add_event(self, event: str, at: Optional[float] = None) -> None:
        self.timeline.append((float(at if at is not None else time.time()),
                              str(event)))

    def to_dict(self) -> Dict:
        return {"node_id": self.node_id, "incarnation": self.incarnation,
                "reason": self.reason,
                "timeline": [list(ev) for ev in self.timeline]}

    @classmethod
    def from_dict(cls, d: Optional[Dict]) -> "DeathContext":
        d = d or {}
        return cls(d.get("node_id", ""), d.get("incarnation", 0),
                   d.get("reason", ""), d.get("timeline") or [])

    def describe(self) -> str:
        parts = []
        if self.node_id:
            parts.append(f"node={self.node_id[:12]}")
        if self.incarnation:
            parts.append(f"incarnation={self.incarnation}")
        if self.reason:
            parts.append(f"reason={self.reason}")
        return ", ".join(parts)


class RayTaskError(RayTpuError):
    """A task raised an exception remotely; re-raised at ray_tpu.get().

    Carries the remote traceback string and, when picklable, the original
    cause (reference behavior: python/ray/exceptions.py RayTaskError).
    """

    # memoized "is self.cause picklable" verdict; None = not yet probed
    _cause_picklable: Optional[bool] = None

    def __init__(
        self,
        function_name: str = "",
        traceback_str: str = "",
        cause: Optional[BaseException] = None,
    ):
        self.function_name = function_name
        self.traceback_str = traceback_str
        self.cause = cause
        super().__init__(traceback_str or str(cause))

    @classmethod
    def from_exception(cls, e: BaseException, function_name: str = "") -> "RayTaskError":
        tb = "".join(traceback.format_exception(type(e), e, e.__traceback__))
        try:
            pickle.dumps(e)
            cause = e
        except Exception:
            cause = None
        err = cls(function_name, tb, cause)
        err._cause_picklable = cause is not None
        return err

    def __reduce__(self):
        # Default Exception pickling would rebuild as cls(traceback_str),
        # mis-assigning the message to function_name and dropping the
        # cause (raylint R5). A cause set directly (not via
        # from_exception's picklability probe) may be unpicklable; drop
        # it rather than fail the whole dump. The probe verdict is
        # memoized so repeated dumps don't pickle the cause twice each.
        cause = self.cause
        if cause is not None:
            if self._cause_picklable is None:
                try:
                    pickle.dumps(cause)
                    self._cause_picklable = True
                except Exception:
                    self._cause_picklable = False
            if not self._cause_picklable:
                cause = None
        return (_rebuild_task_error,
                (type(self), self.function_name, self.traceback_str, cause))

    def __str__(self):
        return (
            f"Task '{self.function_name}' failed remotely:\n{self.traceback_str}"
        )


def _rebuild_task_error(cls, function_name, traceback_str, cause):
    return cls(function_name, traceback_str, cause)


class RayActorError(RayTpuError):
    """The actor died before or during this method call.

    Carries a :class:`DeathContext` (node_id, incarnation, reason,
    timeline) so retry logic and postmortems can discriminate a worker
    crash from a node death from a fenced partition survivor. The
    context round-trips serialization via ``__reduce__``.
    """

    def __init__(self, actor_id: str = "", reason: str = "",
                 node_id: str = "", incarnation: int = 0,
                 timeline: Optional[List[Tuple[float, str]]] = None):
        self.actor_id = actor_id
        self.reason = reason
        self.context = DeathContext(node_id, incarnation, reason, timeline)
        msg = f"Actor {actor_id} died: {reason}"
        extra = self.context.describe()
        if node_id or incarnation:
            msg += f" ({extra})"
        super().__init__(msg)

    def __reduce__(self):
        return (_rebuild_actor_error,
                (type(self), self.actor_id, self.reason,
                 self.context.to_dict()))


def _rebuild_actor_error(cls, actor_id, reason, ctx_dict):
    ctx = DeathContext.from_dict(ctx_dict)
    return cls(actor_id, reason, node_id=ctx.node_id,
               incarnation=ctx.incarnation, timeline=ctx.timeline)


class ActorDiedError(RayActorError):
    pass


class ActorUnavailableError(RayActorError):
    """Actor temporarily unreachable (restarting); call may be retried."""


class ObjectLostError(RayTpuError):
    def __init__(self, object_id_hex: str = "", reason: str = "lost"):
        self.object_id_hex = object_id_hex
        self.reason = reason
        super().__init__(f"Object {object_id_hex} {reason}")

    def __reduce__(self):
        # Rebuild from the real fields, not the formatted message
        # (raylint R5): default pickling would hand the whole sentence to
        # object_id_hex. type(self) keeps subclasses
        # (ObjectFetchTimedOutError) intact; OwnerDiedError overrides.
        return (_rebuild_object_lost,
                (type(self), self.object_id_hex, self.reason))


def _rebuild_object_lost(cls, object_id_hex, reason):
    return cls(object_id_hex, reason)


class ObjectFetchTimedOutError(ObjectLostError):
    pass


class ObjectReconstructionFailedError(ObjectLostError):
    """Lineage reconstruction was attempted for a lost object and could
    not complete: the lineage is truly absent (actor state, ``put()``
    value with a dead owner, record evicted under ``lineage_max_bytes``)
    or a bound tripped (``lineage_max_reconstruction_depth`` /
    ``_attempts``). Subclasses :class:`ObjectLostError` so existing
    "object is gone" handlers keep firing; carries the attempted chain
    (outermost first) so postmortems can see how far replay got.
    """

    def __init__(self, object_id_hex: str = "", reason: str = "",
                 chain: Optional[List[Dict]] = None):
        # each chain entry: {"object_id", "task", "why"} — plain data only
        self.chain = [dict(c) for c in (chain or [])]
        detail = "could not be reconstructed"
        if reason:
            detail += f": {reason}"
        if self.chain:
            hops = " <- ".join(
                str(c.get("object_id", "?"))[:12] for c in self.chain)
            detail += f" (lineage chain: {hops})"
        super().__init__(object_id_hex, detail)

    def __reduce__(self):
        # rebuild from the real fields, not the formatted message
        # (raylint R5); the chain round-trips as plain dicts
        return (_rebuild_reconstruction_failed,
                (self.object_id_hex, self.reason, self.chain))


def _rebuild_reconstruction_failed(object_id_hex, reason, chain):
    err = ObjectReconstructionFailedError.__new__(ObjectReconstructionFailedError)
    # bypass __init__'s re-formatting: `reason` is already the formatted
    # detail ("could not be reconstructed: ...") stored by the base ctor
    ObjectLostError.__init__(err, object_id_hex, reason)
    err.chain = [dict(c) for c in (chain or [])]
    return err


class OwnerDiedError(ObjectLostError):
    def __init__(self, object_id_hex: str = "", node_id: str = "",
                 incarnation: int = 0, reason: str = "",
                 timeline: Optional[List[Tuple[float, str]]] = None):
        self.context = DeathContext(node_id, incarnation,
                                    reason or "owner died", timeline)
        detail = "lost because its owner died"
        extra = self.context.describe()
        if node_id or incarnation:
            detail += f" ({extra})"
        super().__init__(object_id_hex, detail)

    def __reduce__(self):
        return (_rebuild_owner_error,
                (self.object_id_hex, self.context.to_dict()))


def _rebuild_owner_error(object_id_hex, ctx_dict):
    ctx = DeathContext.from_dict(ctx_dict)
    return OwnerDiedError(object_id_hex, node_id=ctx.node_id,
                          incarnation=ctx.incarnation, reason=ctx.reason,
                          timeline=ctx.timeline)


class ObjectStoreFullError(RayTpuError):
    pass


class OutOfMemoryError(RayTpuError):
    """Raised when the node memory monitor kills a task to relieve pressure."""


class GetTimeoutError(RayTpuError, TimeoutError):
    pass


class TaskCancelledError(RayTpuError):
    def __init__(self, task_id_hex: str = ""):
        self.task_id_hex = task_id_hex
        super().__init__(f"Task {task_id_hex} was cancelled")

    def __reduce__(self):
        # default pickling would double-wrap: cls("Task <id> was
        # cancelled") re-formats the already-formatted message (raylint R5)
        return (type(self), (self.task_id_hex,))


class WorkerCrashedError(RayTpuError):
    """The worker process executing the task died (e.g. OOM-killed, segfault)."""


class NodeDiedError(RayTpuError):
    """A node left the cluster (crash, kill, or partition fencing) while
    work targeting it was in flight. Pending leases, actor calls and
    pulls aimed at the node resolve to this instead of hanging out a
    network deadline that a partition (no TCP RST) would never trip."""

    def __init__(self, message: str = "", node_id: str = "",
                 incarnation: int = 0, reason: str = "",
                 timeline: Optional[List[Tuple[float, str]]] = None):
        self.context = DeathContext(node_id, incarnation, reason, timeline)
        if not message:
            message = f"Node {node_id[:12] if node_id else '?'} died"
            extra = self.context.describe()
            if extra:
                message += f" ({extra})"
        super().__init__(message)
        self.message = message

    @property
    def node_id(self) -> str:
        return self.context.node_id

    def __reduce__(self):
        return (_rebuild_node_error, (self.message, self.context.to_dict()))


def _rebuild_node_error(message, ctx_dict):
    ctx = DeathContext.from_dict(ctx_dict)
    return NodeDiedError(message, node_id=ctx.node_id,
                         incarnation=ctx.incarnation, reason=ctx.reason,
                         timeline=ctx.timeline)


class HeadUnavailableError(RayTpuError):
    """The head (GCS) stayed unreachable past the outage-queue budget.

    Head-bound control calls (KV, actor resolution, job registration)
    queue behind the watchdog's reconnect for up to
    ``gcs_outage_queue_s`` during a head outage instead of failing on
    the first lost connection; when the budget runs out they fail fast
    with this typed error so callers can tell "the head is down" from a
    task/actor failure and apply their own retry policy.
    """

    def __init__(self, message: str = "", method: str = "",
                 outage_s: float = 0.0):
        self.method = method
        self.outage_s = float(outage_s)
        if not message:
            message = "head unreachable"
            if method:
                message += f" for control call {method!r}"
            if self.outage_s:
                message += f" after queueing {self.outage_s:.1f}s"
        super().__init__(message)
        self.message = message

    def __reduce__(self):
        # rebuild from the real fields (raylint R5): default pickling
        # would hand the formatted message to `message` AND lose
        # method/outage_s
        return (_rebuild_head_unavailable,
                (self.message, self.method, self.outage_s))


def _rebuild_head_unavailable(message, method, outage_s):
    return HeadUnavailableError(message, method, outage_s)


class BackPressureError(RayTpuError):
    """The serving plane shed this request: every candidate replica's
    admission queue was full (``max_queued_requests``), or a batching
    engine's pending cap was hit. Typed so clients can tell overload
    (retry later, with backoff, against a load-shedding system that
    stays responsive) from failure — the replacement for the old
    reject-and-spin retry loop (reference: serve's
    ``BackPressureError`` on ``max_queued_requests``)."""

    def __init__(self, message: str = "",
                 deployment: str = "",
                 queue_depths: Optional[Dict[str, int]] = None):
        self.deployment = deployment
        self.queue_depths = dict(queue_depths or {})
        if not message:
            message = (f"request to {deployment or 'deployment'} shed under "
                       f"backpressure")
            if self.queue_depths:
                depths = ", ".join(
                    f"{n[-18:]}={d}" for n, d in self.queue_depths.items())
                message += f" (queue depths: {depths})"
        super().__init__(message)
        self.message = message

    def __reduce__(self):
        return (_rebuild_backpressure_error,
                (self.message, self.deployment, self.queue_depths))


def _rebuild_backpressure_error(message, deployment, queue_depths):
    return BackPressureError(message, deployment, queue_depths)


class TrainingWorkerError(RayTpuError):
    """A training worker died (or its user loop raised) mid-round.

    Raised by ``BackendExecutor.get_next_results`` instead of wedging the
    result barrier behind survivors stuck in a collective. Carries the
    failed world ranks and a :class:`DeathContext` so the trainer's
    recovery loop can decide between an in-place restart (user-loop
    error) and an elastic shrink (host/actor death), and so postmortems
    see which rank took the group down.
    """

    def __init__(self, message: str = "",
                 failed_ranks: Optional[List[int]] = None,
                 node_id: str = "", incarnation: int = 0,
                 reason: str = "",
                 timeline: Optional[List[Tuple[float, str]]] = None):
        self.failed_ranks = sorted(int(r) for r in (failed_ranks or []))
        self.context = DeathContext(node_id, incarnation, reason, timeline)
        if not message:
            ranks = ",".join(str(r) for r in self.failed_ranks) or "?"
            message = f"training worker(s) rank [{ranks}] failed"
            extra = self.context.describe()
            if extra:
                message += f" ({extra})"
        super().__init__(message)
        self.message = message

    @property
    def is_user_error(self) -> bool:
        """True when the user train loop raised (the worker process itself
        is fine) — recovery must not shrink the world for these."""
        return self.context.reason == "train_fn_error"

    def __reduce__(self):
        return (_rebuild_training_worker_error,
                (type(self), self.message, self.failed_ranks,
                 self.context.to_dict()))


def _rebuild_training_worker_error(cls, message, failed_ranks, ctx_dict):
    ctx = DeathContext.from_dict(ctx_dict)
    return cls(message, failed_ranks=failed_ranks, node_id=ctx.node_id,
               incarnation=ctx.incarnation, reason=ctx.reason,
               timeline=ctx.timeline)


class TrainRendezvousError(RayTpuError):
    """Collective/backend rendezvous could not form within its budget.

    The bounded replacement for the rc-124 hang class: a peer dying (or a
    coordinator port being rebound) mid-``jax.distributed.initialize``
    used to wedge ``on_start`` forever. Carries the coordinator address
    and how many bounded attempts were burned so the caller can tell an
    exhausted retry loop from a first-try failure.
    """

    def __init__(self, message: str = "", coordinator: str = "",
                 attempts: int = 0, reason: str = ""):
        self.coordinator = coordinator
        self.attempts = int(attempts)
        self.reason = reason
        if not message:
            message = "training rendezvous failed"
            if coordinator:
                message += f" at {coordinator}"
            if self.attempts:
                message += f" after {self.attempts} attempt(s)"
            if reason:
                message += f": {reason}"
        super().__init__(message)
        self.message = message

    def __reduce__(self):
        return (_rebuild_rendezvous_error,
                (type(self), self.message, self.coordinator, self.attempts,
                 self.reason))


def _rebuild_rendezvous_error(cls, message, coordinator, attempts, reason):
    return cls(message, coordinator, attempts, reason)


class RuntimeEnvSetupError(RayTpuError):
    pass


class PendingCallsLimitExceeded(RayTpuError):
    pass


class CrossLanguageError(RayTpuError):
    pass
