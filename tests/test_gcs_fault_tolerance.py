"""GCS (head) fault tolerance (reference:
python/ray/tests/test_gcs_fault_tolerance.py — GCS restart with
redis-backed state; here a file snapshot is the durable store and agents/
drivers re-register through their watchdogs)."""

import os
import subprocess
import sys
import time

import pytest

import ray_tpu


@pytest.fixture()
def persistent_cluster(tmp_path, monkeypatch):
    persist = str(tmp_path / "head_state.bin")
    monkeypatch.setenv("RAY_TPU_GCS_PERSIST", persist)
    from ray_tpu.cluster_utils import Cluster

    cluster = Cluster(initialize_head=True, head_node_args={"num_cpus": 2})
    ray_tpu.init(_node=cluster.head_node)
    yield cluster, persist
    ray_tpu.shutdown()
    cluster.shutdown()


def _restart_head(node, persist: str) -> None:
    node.head_proc.kill()
    node.head_proc.wait()
    log = open(os.path.join(node.session_dir, "logs", "head2.log"), "ab")
    env = dict(os.environ, RAY_TPU_GCS_PERSIST=persist)
    node.head_proc = subprocess.Popen(
        [sys.executable, "-m", "ray_tpu._private.gcs",
         "--session-dir", node.session_dir,
         "--port", str(node.head_port)],
        stdout=log, stderr=log, env=env,
        start_new_session=True)  # node.stop() killpg must not hit us


def test_head_restart_preserves_state_and_recovers(persistent_cluster):
    cluster, persist = persistent_cluster
    from ray_tpu.experimental import internal_kv

    internal_kv._internal_kv_put(b"durable_key", b"durable_value")

    @ray_tpu.remote
    class Keeper:
        def __init__(self):
            self.n = 0

        def bump(self):
            self.n += 1
            return self.n

    keeper = Keeper.options(name="keeper", lifetime="detached").remote()
    assert ray_tpu.get(keeper.bump.remote(), timeout=60) == 1
    time.sleep(0.3)  # let the debounced snapshot flush

    _restart_head(cluster.head_node, persist)
    # wait for agent + driver watchdogs to reconnect to the new head
    deadline = time.monotonic() + 30
    recovered = False
    while time.monotonic() < deadline:
        try:
            if internal_kv._internal_kv_get(b"durable_key") == \
                    b"durable_value":
                recovered = True
                break
        except Exception:
            pass
        time.sleep(0.5)
    assert recovered, "KV not readable after head restart"

    # named detached actor survives: the restored actor table still routes
    # to the live actor process
    handle = None
    deadline = time.monotonic() + 30
    while time.monotonic() < deadline:
        try:
            handle = ray_tpu.get_actor("keeper")
            break
        except Exception:
            time.sleep(0.5)
    assert handle is not None, "named actor not resolvable after restart"
    assert ray_tpu.get(handle.bump.remote(), timeout=60) == 2  # state kept

    # normal tasks still run (agent re-registered under the same node id)
    @ray_tpu.remote
    def add(a, b):
        return a + b

    deadline = time.monotonic() + 60
    while True:
        try:
            assert ray_tpu.get(add.remote(2, 3), timeout=30) == 5
            break
        except Exception:
            if time.monotonic() > deadline:
                raise
            time.sleep(1.0)
