"""ray_tpu.air — shared configs for Train/Tune (reference:
python/ray/air/__init__.py)."""

from ray_tpu.air.config import (
    CheckpointConfig, FailureConfig, RunConfig, ScalingConfig)

from ray_tpu.air import integrations

__all__ = ["CheckpointConfig", "FailureConfig", "RunConfig",
           "ScalingConfig", "integrations"]
