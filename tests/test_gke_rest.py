"""Offline request/response-mapping tests for the GKE REST client
(VERDICT r2 item 5) — the reference tests cloud providers without clouds
(reference: python/ray/tests/test_autoscaler_yaml.py pattern); here the
transport is an injected fake that records requests and scripts replies."""

import json

import pytest

from ray_tpu.autoscaler.gke_rest import (
    GKE_TPU_SHAPES, GkeApiError, GkeRestClient)


class FakeTransport:
    def __init__(self, replies=None):
        self.calls = []
        self.replies = list(replies or [])

    def __call__(self, method, url, body):
        self.calls.append((method, url, body))
        if self.replies:
            reply = self.replies.pop(0)
            if isinstance(reply, Exception):
                raise reply
            return reply
        return {}


def make_client(replies=None, **kw):
    t = FakeTransport(replies)
    c = GkeRestClient("proj-1", "us-central2-b", "ray-cluster",
                      request_fn=t, poll_interval=0.0, **kw)
    return c, t


class TestCreateRequestShape:
    def test_v5e16_payload(self):
        c, _ = make_client()
        body = c.build_create_request(
            "ray-v5e16-1", "v5e-16", 4, {"tpu-slice": "ray-v5e16-1"})
        np_ = body["nodePool"]
        assert body["parent"] == (
            "projects/proj-1/locations/us-central2-b/clusters/ray-cluster")
        assert np_["name"] == "ray-v5e16-1"
        assert np_["initialNodeCount"] == 4
        assert np_["config"]["machineType"] == "ct5lp-hightpu-4t"
        assert np_["placementPolicy"] == {"type": "COMPACT",
                                          "tpuTopology": "4x4"}
        assert np_["autoscaling"] == {"enabled": False}
        assert np_["management"] == {"autoRepair": False,
                                     "autoUpgrade": False}
        assert np_["config"]["labels"]["tpu-slice"] == "ray-v5e16-1"

    def test_v4_3d_topology(self):
        c, _ = make_client()
        body = c.build_create_request("p", "v4-32", 4, {})
        assert body["nodePool"]["config"]["machineType"] == "ct4p-hightpu-4t"
        assert body["nodePool"]["placementPolicy"]["tpuTopology"] == "2x2x4"

    def test_host_count_must_match_slice(self):
        c, _ = make_client()
        with pytest.raises(ValueError, match="4-host slice"):
            c.build_create_request("p", "v5e-16", 2, {})

    def test_unknown_topology(self):
        c, _ = make_client()
        with pytest.raises(ValueError, match="no GKE machine shape"):
            c.build_create_request("p", "v9e-999", 1, {})

    def test_label_values_sanitized(self):
        c, _ = make_client()
        body = c.build_create_request("p", "v5e-4", 1,
                                      {"ray": "Head:Node"})
        assert body["nodePool"]["config"]["labels"]["ray"] == "head-node"

    def test_overrides_merge(self):
        c, _ = make_client(node_pool_overrides={
            "config": {"diskSizeGb": 200},
            "locations": ["us-central2-b"]})
        body = c.build_create_request("p", "v5e-4", 1, {})
        assert body["nodePool"]["config"]["diskSizeGb"] == 200
        assert body["nodePool"]["locations"] == ["us-central2-b"]
        # base fields survive the merge
        assert body["nodePool"]["config"]["machineType"] == "ct5lp-hightpu-4t"

    def test_every_topology_maps_and_serializes(self):
        from ray_tpu.autoscaler.gke import slice_shape

        c, _ = make_client()
        for topo in GKE_TPU_SHAPES:
            hosts, _ = slice_shape(topo)
            body = c.build_create_request("p", topo, hosts, {})
            json.dumps(body)  # REST-serializable


class TestLifecycle:
    def test_create_posts_then_polls_operation(self):
        c, t = make_client(replies=[
            {"name": "op-123", "status": "RUNNING"},
            {"name": "op-123", "status": "DONE"},
        ])
        c.create_tpu_node_pool("pool-a", "v5e-16", 4, {}, {}, {})
        assert t.calls[0][0] == "POST"
        assert t.calls[0][1].endswith(
            "/clusters/ray-cluster/nodePools")
        assert t.calls[1][0] == "GET"
        assert t.calls[1][1].endswith("/operations/op-123")

    def test_operation_error_raises(self):
        c, t = make_client(replies=[
            {"name": "op-9", "status": "DONE",
             "error": {"code": 8, "message": "quota"}}])
        with pytest.raises(GkeApiError, match="quota"):
            c.create_tpu_node_pool("pool-a", "v5e-16", 4, {}, {}, {})

    def test_delete_idempotent_on_404(self):
        c, t = make_client(replies=[GkeApiError(404, "not found")])
        c.delete_node_pool("gone-pool")  # no raise
        assert t.calls[0][0] == "DELETE"
        assert t.calls[0][1].endswith("/nodePools/gone-pool")

    def test_delete_other_errors_propagate(self):
        c, _ = make_client(replies=[GkeApiError(403, "forbidden")])
        with pytest.raises(GkeApiError, match="403"):
            c.delete_node_pool("p")

    def test_runtime_ids_empty_until_running(self):
        c, _ = make_client(replies=[
            {"status": "PROVISIONING", "instanceGroupUrls": ["ig-1"]}])
        assert c.pool_runtime_node_ids("pool-a") == []

    def test_runtime_ids_resolve_instance_names(self):
        """The autoscaler matches runtime ids against agent-registered
        node ids (INSTANCE names), so the client must walk each group's
        listManagedInstances — not echo the group URLs."""
        ig = ("https://www.googleapis.com/compute/v1/projects/p/zones/z/"
              "instanceGroupManagers/gke-ray-pool-grp")
        c, t = make_client(replies=[
            {"status": "RUNNING", "instanceGroupUrls": [ig]},
            {"managedInstances": [
                {"instance": ".../instances/gke-ray-pool-abcd",
                 "instanceStatus": "RUNNING"},
                {"instance": ".../instances/gke-ray-pool-efgh",
                 "instanceStatus": "RUNNING"},
                {"instance": ".../instances/gke-ray-pool-dead",
                 "instanceStatus": "STOPPING"},
            ]},
        ])
        assert c.pool_runtime_node_ids("pool-a") == [
            "gke-ray-pool-abcd", "gke-ray-pool-efgh"]
        assert t.calls[1][0] == "POST"
        assert t.calls[1][1] == f"{ig}/listManagedInstances"

    def test_runtime_ids_group_still_materializing(self):
        c, _ = make_client(replies=[
            {"status": "RUNNING", "instanceGroupUrls": ["ig-1"]},
            GkeApiError(503, "not ready")])
        assert c.pool_runtime_node_ids("pool-a") == []

    def test_runtime_ids_404_is_empty(self):
        c, _ = make_client(replies=[GkeApiError(404, "no pool")])
        assert c.pool_runtime_node_ids("pool-a") == []


class TestProviderIntegration:
    def test_provider_uses_rest_client(self):
        """GkeTpuPodSliceProvider drives the REST client end-to-end with a
        scripted transport: create → ids → slice-atomic delete."""
        from ray_tpu.autoscaler.gke import GkeTpuPodSliceProvider

        c, t = make_client(replies=[
            {"name": "op-1", "status": "DONE"},           # create
            {"status": "RUNNING",
             "instanceGroupUrls": ["ig-url"]},            # get pool
            {"managedInstances": [                        # listManaged...
                {"instance": f".../instances/host-{i}",
                 "instanceStatus": "RUNNING"} for i in range(4)]},
            {"name": "op-2", "status": "DONE"},           # delete
        ])
        provider = GkeTpuPodSliceProvider({
            "node_types": {"v5e16": {"tpu_topology": "v5e-16",
                                     "cpus_per_host": 4}},
            "gke_client": c,
        }, cluster_name="ray")
        [sid] = provider.create_node("v5e16", 1)
        assert provider.expected_runtime_nodes(sid) == 4
        assert len(provider.runtime_node_ids(sid)) == 4
        provider.terminate_node(sid)
        methods = [m for m, _, _ in t.calls]
        assert methods == ["POST", "GET", "POST", "DELETE"]
        # the created pool carries the slice placement policy
        assert t.calls[0][2]["nodePool"]["placementPolicy"][
            "tpuTopology"] == "4x4"


class TestErrorPaths:
    """VERDICT r3 weak #4: the provider's behavior under real API
    failures (quota 429, stockout mid-operation, permission 403) was
    speculative — these drive each class end to end against a failing
    client and assert no ghost slices, backoff, and rollback."""

    class FailingClient:
        def __init__(self, err):
            self.err = err
            self.create_calls = 0
            self.deleted = []

        def create_tpu_node_pool(self, pool_name, **kw):
            self.create_calls += 1
            raise self.err

        def delete_node_pool(self, pool_name):
            self.deleted.append(pool_name)

        def pool_runtime_node_ids(self, pool_name):
            return []

    def _provider(self, client):
        from ray_tpu.autoscaler.gke import GkeTpuPodSliceProvider

        return GkeTpuPodSliceProvider(
            {"node_types": {
                "v5e-8": {"tpu_topology": "v5e-8",
                          "resources": {"TPU": 8.0}}},
             "gke_client": client}, "t")

    def test_quota_429_rolls_back_and_backs_off(self):
        from ray_tpu.autoscaler.gke_rest import GkeApiError

        client = self.FailingClient(
            GkeApiError(429, "rateLimitExceeded: quota"))
        p = self._provider(client)
        created = p.create_node("v5e-8", 2)
        assert created == []            # nothing pretended into existence
        assert p.num_slices() == 0      # no ghost slice
        assert client.create_calls == 1  # stopped after the first failure
        assert client.deleted           # best-effort cleanup issued
        assert 0 < p.create_failure_backoff("v5e-8") <= 60
        # within the backoff window the API is NOT hit again
        assert p.create_node("v5e-8", 1) == []
        assert client.create_calls == 1

    def test_stockout_operation_error_is_retryable(self):
        from ray_tpu.autoscaler.gke_rest import GkeApiError

        err = GkeApiError(200, '{"code": 8, "message": '
                               '"ZONE_RESOURCE_POOL_EXHAUSTED"}')
        assert err.retryable
        client = self.FailingClient(err)
        p = self._provider(client)
        assert p.create_node("v5e-8", 1) == []
        assert 0 < p.create_failure_backoff("v5e-8") <= 60

    def test_permission_403_backs_off_long(self):
        from ray_tpu.autoscaler.gke_rest import GkeApiError

        err = GkeApiError(403, "PERMISSION_DENIED: container.nodePools")
        assert not err.retryable
        client = self.FailingClient(err)
        p = self._provider(client)
        assert p.create_node("v5e-8", 1) == []
        assert p.create_failure_backoff("v5e-8") > 60  # permanent-class

    def test_backoff_expires_and_retries(self, monkeypatch):
        from ray_tpu.autoscaler.gke_rest import GkeApiError

        client = self.FailingClient(GkeApiError(429, "quota"))
        p = self._provider(client)
        p.create_node("v5e-8", 1)
        assert client.create_calls == 1
        # jump past the window: the next create hits the API again
        with p._lock:
            p._create_backoff["v5e-8"] = 0.0
        p.create_node("v5e-8", 1)
        assert client.create_calls == 2

    def test_retryable_classification(self):
        from ray_tpu.autoscaler.gke_rest import GkeApiError

        assert GkeApiError(500, "boom").retryable
        assert GkeApiError(429, "x").retryable
        assert GkeApiError(400, "RESOURCE_EXHAUSTED in zone").retryable
        assert not GkeApiError(400, "invalid topology").retryable
        assert not GkeApiError(404, "no such cluster").retryable
