"""Reporter + profiling surfaces (reference:
dashboard/modules/reporter/reporter_agent.py:277 psutil stats,
profile_manager.py:61-97 on-demand profiling; SURVEY §5 jax.profiler
integration; VERDICT r1 item 7)."""

import json
import os
import time
import urllib.request

import pytest

import ray_tpu
from ray_tpu.util import state


@pytest.fixture(scope="module")
def obs_cluster():
    os.environ["RAY_TPU_FAKE_TPU_DUTY"] = "37.5"
    if not ray_tpu.is_initialized():
        ray_tpu.init(num_cpus=4)
    yield
    ray_tpu.shutdown()
    os.environ.pop("RAY_TPU_FAKE_TPU_DUTY", None)


def test_node_stats_reported(obs_cluster):
    deadline = time.time() + 30
    stats = []
    while time.time() < deadline:
        stats = state.get_node_stats()
        if stats and "cpu_percent" in stats[0]:
            break
        time.sleep(0.5)
    assert len(stats) == 1
    st = stats[0]
    assert isinstance(st["cpu_percent"], (int, float))
    assert st["mem_total_bytes"] > 0
    assert st["mem_used_bytes"] > 0
    assert st["num_workers"] >= 0
    assert "object_store" in st
    assert st["tpu"].get("duty_cycle_percent") == 37.5


def test_system_metrics_in_prometheus(obs_cluster):
    from ray_tpu.util.metrics import prometheus_text

    deadline = time.time() + 30
    text = ""
    while time.time() < deadline:
        text = prometheus_text()
        if "ray_tpu_node_cpu_percent" in text:
            break
        time.sleep(0.5)
    assert "ray_tpu_node_cpu_percent" in text
    assert "ray_tpu_node_mem_used_bytes" in text
    assert "ray_tpu_tpu_duty_cycle_percent" in text
    assert 'node_id="' in text


def test_dashboard_node_stats_endpoint(obs_cluster):
    from ray_tpu.dashboard import start_dashboard

    port = start_dashboard(port=0)
    deadline = time.time() + 30
    rows = []
    while time.time() < deadline:
        with urllib.request.urlopen(
                f"http://127.0.0.1:{port}/api/node_stats", timeout=30) as r:
            rows = json.loads(r.read())
        if rows and rows[0].get("tpu"):
            break
        time.sleep(0.5)
    assert rows and rows[0]["tpu"]["duty_cycle_percent"] == 37.5


def _wait_registered_worker(actor_id, timeout=90):
    deadline = time.time() + timeout
    while time.time() < deadline:
        rows = state.list_workers(filters=[("actor_id", "=", actor_id)])
        if rows and rows[0].get("direct_addr"):
            return rows[0]
        time.sleep(0.5)
    raise AssertionError(f"actor worker {actor_id} never registered")


def test_profile_worker_folded_stacks(obs_cluster):
    @ray_tpu.remote
    class Busy:
        def spin_forever_name_marker(self, t):
            deadline = time.time() + t
            total = 0
            while time.time() < deadline:
                total += sum(range(200))
            return total

    b = Busy.remote()
    row = _wait_registered_worker(b._actor_id.hex())
    ref = b.spin_forever_name_marker.remote(8)
    time.sleep(0.5)
    prof = state.profile_worker(row["worker_id"], duration_s=2.0)
    assert prof["pid"] == row["pid"]
    folded_text = "\n".join(prof["folded"])
    assert "spin_forever_name_marker" in folded_text
    ray_tpu.get(ref, timeout=60)
    ray_tpu.kill(b)


def test_capture_jax_trace_produces_files(obs_cluster, tmp_path):
    @ray_tpu.remote
    class JaxWork:
        def crunch(self, t):
            import jax.numpy as jnp

            deadline = time.time() + t
            x = jnp.ones((128, 128))
            while time.time() < deadline:
                x = (x @ x) / 128.0
            return float(x[0, 0])

    j = JaxWork.remote()
    row = _wait_registered_worker(j._actor_id.hex())
    ref = j.crunch.remote(8)
    time.sleep(0.5)
    out = state.capture_jax_trace(row["worker_id"], duration_s=2.0,
                                  out_dir=str(tmp_path / "trace"))
    assert "error" not in out, out
    assert out["files"], f"empty trace dir: {out}"
    # loadable trace: the xplane protobuf TensorBoard/Perfetto consume
    assert any("xplane" in f or f.endswith((".json.gz", ".trace.json.gz"))
               for f in out["files"]), out["files"]
    ray_tpu.get(ref, timeout=60)
    ray_tpu.kill(j)
