"""Multi-agent PPO (reference: RLlib's multi-agent support —
AlgorithmConfig.multi_agent(policies, policy_mapping_fn) and the
multi-agent train batch split in algorithm.py/rollout_worker.py; each
policy gets its own module + optimizer and learns only from the agents
mapped to it).

Per-policy updates are independent jitted PPO steps; shared-policy
self-play is the policies={'shared'} + constant mapping special case.
"""

from __future__ import annotations

from typing import Callable, Dict, List, Optional

import numpy as np

import ray_tpu
from ray_tpu.rllib.algorithms.algorithm import Algorithm
from ray_tpu.rllib.algorithms.ppo.ppo import PPOConfig
from ray_tpu.rllib.core.learner import PPOLearner
from ray_tpu.rllib.core.rl_module import RLModuleSpec
from ray_tpu.rllib.env.multi_agent_env import MultiAgentEnvRunner


def _stream_gae(rewards, vf, dones, gamma, lam):
    """GAE over a single row stream; fragment end bootstraps with 0 (the
    stream is cut mid-episode at worst — small, standard bias)."""
    T = len(rewards)
    adv = np.zeros(T, np.float32)
    last = 0.0
    next_vf = 0.0
    for t in range(T - 1, -1, -1):
        nonterminal = 1.0 - dones[t]
        delta = rewards[t] + gamma * next_vf * nonterminal - vf[t]
        last = delta + gamma * lam * nonterminal * last
        adv[t] = last
        next_vf = vf[t]
    return adv, adv + vf


class MultiAgentPPOConfig(PPOConfig):
    def __init__(self, algo_class=None):
        super().__init__(algo_class=algo_class or MultiAgentPPO)
        self.policies: List[str] = []
        self.policy_mapping_fn: Callable[[str], str] = lambda aid: "default"
        self.num_env_runners = 2
        self.train_batch_size = 512

    def multi_agent(self, *, policies: List[str],
                    policy_mapping_fn: Callable[[str], str]
                    ) -> "MultiAgentPPOConfig":
        self.policies = list(policies)
        self.policy_mapping_fn = policy_mapping_fn
        return self

    def _training_keys(self):
        return super()._training_keys() | {"policies", "policy_mapping_fn"}

    def multi_module_specs(self) -> Dict[str, RLModuleSpec]:
        """One spec per policy, derived from a mapped agent's spaces."""
        import gymnasium as gym

        probe = self.make_env()()
        try:
            specs: Dict[str, RLModuleSpec] = {}
            for agent_id in probe.possible_agents:
                pid = self.policy_mapping_fn(agent_id)
                if pid in specs:
                    continue
                obs_space = probe.observation_spaces[agent_id]
                act_space = probe.action_spaces[agent_id]
                discrete = isinstance(act_space, gym.spaces.Discrete)
                specs[pid] = RLModuleSpec(
                    obs_dim=int(obs_space.shape[0]),
                    action_dim=(int(act_space.n) if discrete
                                else int(act_space.shape[0])),
                    discrete=discrete,
                    hiddens=tuple(self.model.get("hiddens", (64, 64))),
                    activation=self.model.get("activation", "tanh"))
            missing = set(self.policies) - set(specs)
            if missing:
                raise ValueError(
                    f"policies {sorted(missing)} not reachable by "
                    "policy_mapping_fn from any possible agent")
            return specs
        finally:
            probe.close()


class MultiAgentPPO(Algorithm):
    @classmethod
    def get_default_config(cls):
        return MultiAgentPPOConfig(algo_class=cls)

    def setup(self, _config) -> None:
        cfg = self.config = self._algo_config
        if not cfg.policies:
            raise ValueError(
                "MultiAgentPPO requires config.multi_agent(policies=...)")
        self._module_specs = cfg.multi_module_specs()
        lcfg = cfg.learner_config_dict()
        self.learners: Dict[str, PPOLearner] = {
            pid: PPOLearner(spec, lcfg)
            for pid, spec in self._module_specs.items()}
        self.env_runners = [self._make_runner(i)
                            for i in range(cfg.num_env_runners)]
        self._total_env_steps = 0
        self._episode_returns: List[float] = []

    def _make_runner(self, idx: int):
        cfg = self.config
        return ray_tpu.remote(MultiAgentEnvRunner).options(
            resources={"CPU": 1}).remote(
                cfg.make_env(), cfg.rollout_fragment_length,
                self._module_specs, cfg.policy_mapping_fn,
                seed=cfg.seed + idx * 1000 + 1, gamma=cfg.gamma)

    def get_weights(self) -> Dict[str, Dict]:
        return {pid: ln.get_weights() for pid, ln in self.learners.items()}

    def training_step(self) -> Dict:
        cfg = self.config
        weights_ref = ray_tpu.put(self.get_weights())
        merged: Dict[str, Dict[str, List[np.ndarray]]] = {
            pid: {"obs": [], "actions": [], "logp": [], "advantages": [],
                  "value_targets": []}
            for pid in self.learners}
        env_steps = 0
        while env_steps < cfg.train_batch_size:
            parts = self._sample_from_runners(weights_ref)
            if not parts:
                break
            for s in parts:
                env_steps += s["env_steps"]
                for pid, per_agent in s["agent_batches"].items():
                    # GAE per agent stream (time recursion must never
                    # cross agents), then rows pool per policy
                    for b in per_agent.values():
                        adv, vt = _stream_gae(
                            b["rewards"], b["vf"], b["dones"],
                            cfg.gamma, cfg.lambda_)
                        merged[pid]["obs"].append(b["obs"])
                        merged[pid]["actions"].append(b["actions"])
                        merged[pid]["logp"].append(b["logp"])
                        merged[pid]["advantages"].append(adv)
                        merged[pid]["value_targets"].append(vt)

        metrics: Dict = {"env_steps_this_iter": env_steps}
        for pid, cols in merged.items():
            if not cols["obs"]:
                continue
            batch = {k: np.concatenate(v) for k, v in cols.items()}
            pm = self.learners[pid].update(batch)
            metrics.update({f"{pid}/{k}": v for k, v in pm.items()})
        return metrics

    def compute_single_action(self, obs, policy_id: str = "default",
                              explore: bool = False):
        module = self._module_specs[policy_id].build()
        out = module.forward(self.learners[policy_id].get_weights(),
                             np.asarray(obs, np.float32)[None])
        logits = np.asarray(out["logits"])[0]
        if module.spec.discrete:
            return int(np.argmax(logits))
        return np.tanh(logits[:module.spec.action_dim])

    # ----------------------------------------------------------- checkpoint
    def save_checkpoint(self, checkpoint_dir: str) -> None:
        import os
        import pickle

        state = {pid: ln.get_state() for pid, ln in self.learners.items()}
        with open(os.path.join(checkpoint_dir, "ma_learners.pkl"),
                  "wb") as f:
            pickle.dump(state, f)

    def load_checkpoint(self, checkpoint_dir: str) -> None:
        import os
        import pickle

        with open(os.path.join(checkpoint_dir, "ma_learners.pkl"),
                  "rb") as f:
            state = pickle.load(f)
        for pid, st in state.items():
            self.learners[pid].set_state(st)

    def cleanup(self) -> None:
        for r in self.env_runners:
            try:
                ray_tpu.get(r.stop.remote(), timeout=10)
            except Exception:
                pass
            try:
                ray_tpu.kill(r)
            except Exception:
                pass
