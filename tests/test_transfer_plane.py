"""Pipelined multi-stream object transfer plane (reference:
``object_manager.h:117`` windowed Push/Pull chunking + ``pull_manager.h``
admission control).

Two-node (localhost) integration: a large pull lands byte-identical under
the windowed pipeline; a holder killed mid-transfer yields failover or a
clean lost verdict (never a hung ``get``); the pull byte budget queues a
burst of concurrent large gets. Plus event-loop unit tests for the raw
chunk framing, the FIFO budget, and the streaming spill restore.
"""

import asyncio
import os
import threading
import time

import numpy as np
import pytest

import ray_tpu
from ray_tpu._private.ids import ObjectID
from ray_tpu._private.object_store import StoreDirectory
from ray_tpu._private.protocol import AsyncRpcClient, RawData, RpcServer
from ray_tpu._private.pull_manager import PullBudget
from ray_tpu.cluster_utils import Cluster

MB = 1024 * 1024


def _pull_stats():
    """Pull-plane counters of the agent THIS driver is attached to (the
    pulling side of every cross-node get below)."""
    from ray_tpu._private import worker as worker_mod

    w = worker_mod.global_worker
    return w._acall(w.agent.call("GetPullStats", {}))


@pytest.fixture
def two_node(monkeypatch):
    """Factory: env knobs -> (cluster, far_node). Env must be set before
    the cluster boots — agents read RAY_TPU_* from their inherited env."""
    made = []

    def boot(env=None):
        for k, v in (env or {}).items():
            monkeypatch.setenv(k, v)
        cluster = Cluster(initialize_head=True,
                          head_node_args={"num_cpus": 2})
        made.append(cluster)
        ray_tpu.init(_node=cluster.head_node)
        node = cluster.add_node(num_cpus=2, resources={"far": 4})
        cluster.wait_for_nodes()
        return cluster, node

    yield boot
    try:
        ray_tpu.shutdown()
    finally:
        for cluster in made:
            cluster.shutdown()


def test_large_pull_byte_identical(two_node):
    """64 MB produced on the far node arrives byte-identical through the
    windowed, striped, raw-framed pipeline (out-of-order chunk completion
    must not scramble offsets)."""
    two_node()

    @ray_tpu.remote(resources={"far": 1})
    def produce():
        rng = np.random.default_rng(1234)
        return rng.integers(0, 255, 64 * MB, dtype=np.uint8)

    ref = produce.remote()
    value = ray_tpu.get(ref, timeout=300)
    expected = np.random.default_rng(1234).integers(
        0, 255, 64 * MB, dtype=np.uint8)
    assert value.dtype == np.uint8 and value.nbytes == 64 * MB
    assert np.array_equal(value, expected)
    stats = _pull_stats()
    assert stats["transfers_ok"] >= 1
    # a real multi-chunk pipeline ran (64 chunks at the 1 MB default;
    # still >= 13 for any chunk size up to ~4.9 MB)
    assert stats["chunks_fetched"] >= 13
    assert stats["bytes_fetched"] >= 64 * MB
    assert stats["inflight_bytes"] == 0  # budget fully retired


def test_batched_get_pulls_concurrently(two_node):
    """One `get` of 8 cross-node refs issues ONE WaitObjects frame, so the
    agent overlaps the transfers. Asserted on the pull manager's
    occupancy counters — `transfers_concurrent_peak` can only exceed 1
    if two transfers were genuinely inside `_transfer` at once — instead
    of wall-clock overlap, which flaked on slow boxes where scheduler
    jitter dwarfed the transfer time."""
    two_node()

    @ray_tpu.remote(resources={"far": 0.25})
    def produce(i):
        return np.full(4 * MB, i, dtype=np.uint8)

    refs = [produce.remote(i) for i in range(8)]
    ready, _ = ray_tpu.wait(refs, num_returns=len(refs), timeout=120)
    assert len(ready) == len(refs)

    one = ray_tpu.get(refs[0], timeout=120)
    assert one[0] == 0
    base = _pull_stats()
    assert base["transfers_ok"] >= 1

    refs2 = [produce.remote(i) for i in range(8)]  # fresh object ids
    ready, _ = ray_tpu.wait(refs2, num_returns=len(refs2), timeout=120)
    assert len(ready) == len(refs2)
    values = ray_tpu.get(refs2, timeout=120)
    for i, v in enumerate(values):
        assert v[0] == i and v.nbytes == 4 * MB

    stats = _pull_stats()
    assert stats["transfers_ok"] >= base["transfers_ok"] + 8
    # the batched get overlapped transfers (a sequential agent would
    # never have two pulls inside _transfer simultaneously)
    assert stats["transfers_concurrent_peak"] >= 2, stats
    # and the per-holder chunk window pipelined within a transfer
    assert stats["window_occupancy_peak"] >= 2, stats
    # everything retired cleanly
    assert stats["transfers_concurrent"] == 0
    assert stats["inflight_bytes"] == 0


def test_holder_killed_mid_transfer_no_hang(two_node):
    """SIGKILL the only holder's agent while chunks stream (tiny chunks +
    narrow window stretch the transfer). The get must end — value (raced
    the kill) or clean lost verdict — never hang."""
    from ray_tpu.util.chaos import DaemonKiller

    cluster, node = two_node(env={
        "RAY_TPU_OBJECT_CHUNK_SIZE_BYTES": str(128 * 1024),
        "RAY_TPU_OBJECT_PULL_WINDOW": "2",
        "RAY_TPU_PULL_DEAD_HOLDER_ROUNDS": "2",
        "RAY_TPU_OBJECT_PULL_DEADLINE_S": "45",
    })

    @ray_tpu.remote(resources={"far": 1}, max_retries=0)
    def produce():
        return np.ones(48 * MB, dtype=np.uint8)

    ref = produce.remote()
    ready, _ = ray_tpu.wait([ref], num_returns=1, timeout=120)
    assert ready, "produce() did not finish"

    outcome = {}

    def getter():
        try:
            outcome["value"] = ray_tpu.get(ref, timeout=90)
        except Exception as e:  # noqa: BLE001 — the verdict IS the test
            outcome["error"] = e

    t = threading.Thread(target=getter)
    t.start()
    time.sleep(0.05)  # let the transfer start
    killer = DaemonKiller(cluster.session_dir, roles=("agent",), max_kills=1)
    record = killer.kill_target(
        {"role": "agent", "pid": node.agent_proc.pid})
    assert record is not None, "holder agent was not killed"
    t.join(timeout=120)
    assert not t.is_alive(), "get() hung after the holder died"
    assert outcome, "getter finished without a verdict"
    if "value" in outcome:  # transfer raced the kill and won
        assert outcome["value"].nbytes == 48 * MB
        assert int(outcome["value"][0]) == 1
    else:
        # clean lost/timeout verdict — never a partial object, never a hang
        assert isinstance(outcome["error"], Exception)


def test_pull_budget_queues_burst(two_node):
    """A burst of concurrent large gets must queue on the admission budget
    (cap unsealed pull bytes), admit FIFO as bytes retire, and still land
    every object intact."""
    two_node(env={
        # one ~8 MB transfer in flight at a time; the other three queue
        "RAY_TPU_OBJECT_PULL_MAX_INFLIGHT_BYTES": str(9 * MB),
        "RAY_TPU_OBJECT_CHUNK_SIZE_BYTES": str(1 * MB),
    })

    @ray_tpu.remote(resources={"far": 0.25})
    def produce(i):
        return np.full(8 * MB, i, dtype=np.uint8)

    refs = [produce.remote(i) for i in range(4)]
    ready, _ = ray_tpu.wait(refs, num_returns=len(refs), timeout=120)
    assert len(ready) == len(refs)
    values = ray_tpu.get(refs, timeout=300)  # batched -> concurrent pulls
    for i, v in enumerate(values):
        assert v.nbytes == 8 * MB and int(v[0]) == i
    stats = _pull_stats()
    assert stats["transfers_ok"] >= 4
    assert stats["pulls_queued_total"] >= 1, (
        f"budget never queued a transfer: {stats}")
    assert stats["inflight_bytes"] == 0
    assert stats["pulls_queued"] == 0


# ---------------------------------------------------------------------------
# event-loop / store unit tests (no cluster)
# ---------------------------------------------------------------------------


def test_raw_chunk_framing_roundtrip():
    """RawData replies (header + raw bytes on the wire) resolve to the
    exact payload and interleave safely with normal msgpack replies on one
    connection."""
    payload = os.urandom(MB)

    async def scenario():
        server = RpcServer("raw-test")

        async def fetch(conn, p):
            off, length = p["offset"], p["length"]
            return RawData(memoryview(payload)[off:off + length])

        async def ping(conn, p):
            return {"pong": True}

        server.add_handler("Fetch", fetch)
        server.add_handler("Ping", ping)
        port = await server.start_tcp("127.0.0.1", 0)
        client = AsyncRpcClient()
        await client.connect_tcp("127.0.0.1", port)
        try:
            out = await client.call("Fetch", {"offset": 100, "length": 1000})
            assert out == payload[100:1100]
            empty = await client.call("Fetch", {"offset": 0, "length": 0})
            assert empty == b""
            results = await asyncio.gather(
                client.call("Fetch", {"offset": 0, "length": MB}),
                client.call("Ping", {}),
                client.call("Fetch", {"offset": 5, "length": 7}),
            )
            assert results[0] == payload
            assert results[1] == {"pong": True}
            assert results[2] == payload[5:12]
        finally:
            await client.aclose()
            await server.close()

    asyncio.run(scenario())


def test_pull_budget_fifo():
    """FIFO admission: a waiter admits only when bytes retire, in arrival
    order; an oversized transfer admits alone once the pipe is empty; a
    cancelled waiter neither admits nor wedges the queue."""

    async def scenario():
        b = PullBudget(10)
        await b.acquire(6)
        assert b.inflight == 6
        second = asyncio.ensure_future(b.acquire(6))
        third = asyncio.ensure_future(b.acquire(2))
        await asyncio.sleep(0)
        # 2 would fit, but FIFO order holds it behind the queued 6
        assert b.queued == 2 and b.inflight == 6
        b.release(6)
        await second
        await third
        assert b.inflight == 8 and b.queued == 0
        assert b.queued_total == 2
        b.release(6)
        b.release(2)
        # oversized admits alone on an empty pipe
        await b.acquire(100)
        assert b.inflight == 100
        follower = asyncio.ensure_future(b.acquire(1))
        await asyncio.sleep(0)
        assert b.queued == 1
        follower.cancel()
        await asyncio.gather(follower, return_exceptions=True)
        b.release(100)
        # the cancelled waiter must not have admitted or blocked anyone
        assert b.inflight == 0 and b.queued == 0
        await b.acquire(5)
        assert b.inflight == 5

    asyncio.run(scenario())


def test_restore_streams_spilled_object(tmp_path, monkeypatch):
    """restore() streams the spilled file through create()/seal() in
    chunks — byte-identical round trip without a whole-file bytes blob."""
    monkeypatch.setenv("RAY_TPU_STORE_BACKEND", "tmpfs")
    monkeypatch.setenv("RAY_TPU_OBJECT_CHUNK_SIZE_BYTES", str(64 * 1024))
    store = StoreDirectory(str(tmp_path / "store"), capacity=64 * MB)
    oid = ObjectID(os.urandom(20))
    data = os.urandom(3 * MB + 12345)  # not chunk-aligned on purpose
    store.client.put_bytes(oid, data)
    store.on_sealed(oid.hex(), len(data))

    assert store._spill(oid.hex())
    assert store.is_spilled(oid.hex())
    assert store.client.get_view(oid) is None

    assert store.restore(oid.hex())
    view = store.client.get_view(oid)
    assert view is not None
    assert bytes(view[:len(data)]) == data
    assert not store.is_spilled(oid.hex())
    assert store.used == len(data)
