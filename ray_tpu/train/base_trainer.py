"""BaseTrainer + Result (reference: python/ray/train/base_trainer.py —
fit :581; in the reference, fit wraps the trainer as a Tune Trainable
:700,844).

Here ``fit()`` sets up experiment/trial dirs and calls the subclass's
``training_loop()`` directly; ``ray_tpu.tune`` reuses trainers through the
same ``training_loop()`` entry point when sweeping (Trainable wrapping
lives on the Tune side).
"""

from __future__ import annotations

import dataclasses
import os
import time
from typing import Any, Dict, Optional

from ray_tpu.air.config import (
    CheckpointConfig, FailureConfig, RunConfig, ScalingConfig)
from ray_tpu.train._checkpoint import Checkpoint


class TrainingFailedError(RuntimeError):
    pass


@dataclasses.dataclass
class Result:
    metrics: Optional[Dict[str, Any]]
    checkpoint: Optional[Checkpoint]
    path: str
    error: Optional[Exception] = None
    metrics_dataframe: Optional[Any] = None
    best_checkpoints: Optional[list] = None
    # the trial's hyperparameter config (reference: Result.config)
    config: Optional[Dict[str, Any]] = None
    # worker-group restarts the elastic recovery loop performed; 0 on a
    # clean run (mirrors ray_tpu_train_restarts_total for this trial)
    restarts: int = 0


class BaseTrainer:
    def __init__(
        self,
        *,
        scaling_config: Optional[ScalingConfig] = None,
        run_config: Optional[RunConfig] = None,
        resume_from_checkpoint: Optional[Checkpoint] = None,
        datasets: Optional[Dict[str, Any]] = None,
    ):
        self.scaling_config = scaling_config or ScalingConfig()
        self.run_config = run_config or RunConfig()
        self.resume_from_checkpoint = resume_from_checkpoint
        self.datasets = datasets or {}

    # Subclasses implement the actual training drive loop.
    def training_loop(self) -> Result:
        raise NotImplementedError

    def fit(self) -> Result:
        from ray_tpu._private.storage import (
            get_storage_backend, is_remote_uri, join_uri, local_path)

        name = self.run_config.name or f"train_{int(time.time())}"
        storage = self.run_config.resolved_storage_path()
        if is_remote_uri(storage):
            trial_dir = join_uri(storage, name)
            get_storage_backend(trial_dir).makedirs(trial_dir)
        else:
            trial_dir = os.path.join(local_path(storage), name)
            os.makedirs(trial_dir, exist_ok=True)
        self._experiment_name = name
        self._storage_path = storage
        self._trial_dir = trial_dir
        return self.training_loop()
