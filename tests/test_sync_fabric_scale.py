"""Resource-gossip scale behavior (reference: src/ray/common/ray_syncer/
ray_syncer.h:88 versioned RESOURCE_VIEW deltas; VERDICT r1 item 10).

Boots a 50-node cluster (1 agent process per node, no prestarted workers)
and checks that steady-state head ingress is heartbeat-only — full snapshots
flow only when a node's view actually changes."""

import time

import pytest

import ray_tpu
from ray_tpu.cluster_utils import Cluster

N_EXTRA_NODES = 49


@pytest.fixture(scope="module")
def big_cluster():
    cluster = Cluster(initialize_head=True, head_node_args={"num_cpus": 1})
    try:
        ray_tpu.init(_node=cluster.head_node)
        for i in range(N_EXTRA_NODES):
            # num_cpus=0: no prestarted worker processes — 50 agents alone
            # is the point, not 50 worker pools
            cluster.add_node(num_cpus=0, resources={f"n{i}": 1})
        cluster.wait_for_nodes(timeout=600)
    except BaseException:
        # a setup failure fires BEFORE yield — without this, the teardown
        # below never runs and ~50 agent processes leak onto the box,
        # poisoning every later test (observed: the full-suite run's
        # wait_for_nodes timeout left 50+ agents running)
        ray_tpu.shutdown()
        cluster.shutdown()
        raise
    yield cluster
    ray_tpu.shutdown()
    cluster.shutdown()


def _report_stats():
    from ray_tpu._private import worker as wm

    w = wm.global_worker
    return w._acall(w.head.call("GetReportStats", {}))


def test_50_nodes_alive(big_cluster):
    nodes = [n for n in ray_tpu.nodes() if n["alive"]]
    assert len(nodes) == N_EXTRA_NODES + 1


def test_idle_traffic_is_heartbeat_only(big_cluster):
    time.sleep(3)  # settle: initial full snapshots all delivered
    s1 = _report_stats()
    window = 5.0
    time.sleep(window)
    s2 = _report_stats()
    hb = s2.get("heartbeats", 0) - s1.get("heartbeats", 0)
    full = s2.get("full_reports", 0) - s1.get("full_reports", 0)
    # 50 nodes x ~10 ticks/s: thousands of ticks; full snapshots must be
    # O(changed nodes) = ~0, not O(n) per tick
    assert hb > 50, f"heartbeats not flowing at scale: {hb}"
    assert full <= N_EXTRA_NODES + 1, \
        f"idle 50-node cluster sent {full} full snapshots in {window}s"


def test_change_propagates_as_single_delta(big_cluster):
    time.sleep(1)
    s1 = _report_stats()

    @ray_tpu.remote(num_cpus=1)
    def touch():
        return 1

    assert ray_tpu.get(touch.remote(), timeout=120) == 1
    time.sleep(1.5)
    s2 = _report_stats()
    full = s2.get("full_reports", 0) - s1.get("full_reports", 0)
    # only the head node's view changed (lease grant/return + worker spawn):
    # a handful of snapshots from one node, not 50
    assert 1 <= full <= 20, f"expected O(1-node) delta traffic, got {full}"
