"""MADDPG — multi-agent DDPG with centralized critics (reference:
rllib/algorithms/maddpg (legacy rllib_contrib/maddpg); Lowe et al. 2017
"Multi-Agent Actor-Critic for Mixed Cooperative-Competitive Environments").

Centralized training, decentralized execution: each agent has a
deterministic actor μ_i(o_i) over its OWN observation, but its critic
Q_i(s, a_1..a_n) sees the global state and EVERY agent's action — the
fix for non-stationarity that independent DDPG learners suffer. Targets
use target actors+critics (polyak).

TPU-first shape: all agents' actors/critics are stacked into one pytree
with a leading agent dim and updated in ONE jitted function via vmap over
agents — n_agents small networks become one batched MXU-friendly update,
not a Python loop of tiny matmuls.
"""

from __future__ import annotations

from typing import Any, Dict, List

import jax
import jax.numpy as jnp
import numpy as np
import optax

from ray_tpu.rllib.algorithms.algorithm import Algorithm
from ray_tpu.rllib.algorithms.algorithm_config import AlgorithmConfig
from ray_tpu.rllib.models.catalog import _mlp_forward, _mlp_params
from ray_tpu.rllib.utils.replay_buffer import ReplayBuffer


def _stacked_mlp_params(key, n: int, sizes, final_scale=1.0):
    keys = jax.random.split(key, n)
    return jax.vmap(
        lambda k: _mlp_params(k, sizes, final_scale=final_scale))(keys)


class MADDPGModel:
    """Per-agent actor + centralized critic, agent-stacked (leading dim)."""

    def __init__(self, obs_dim: int, act_dim: int, n_agents: int,
                 hidden: int = 64, act_low: float = -1.0,
                 act_high: float = 1.0):
        self.obs_dim = obs_dim
        self.act_dim = act_dim
        self.n_agents = n_agents
        self.hidden = hidden
        self.act_low = act_low
        self.act_high = act_high
        self.state_dim = obs_dim * n_agents
        self.joint_act = act_dim * n_agents

    def init(self, rng) -> Dict:
        k1, k2 = jax.random.split(rng)
        return {
            "actor": _stacked_mlp_params(
                k1, self.n_agents,
                (self.obs_dim, self.hidden, self.hidden, self.act_dim),
                final_scale=0.01),
            "critic": _stacked_mlp_params(
                k2, self.n_agents,
                (self.state_dim + self.joint_act, self.hidden,
                 self.hidden, 1)),
        }

    def _squash(self, raw):
        mid = (self.act_high + self.act_low) / 2.0
        half = (self.act_high - self.act_low) / 2.0
        return mid + half * jnp.tanh(raw)

    def actions(self, params, obs_all):
        """obs_all [B, n_agents, obs_dim] -> [B, n_agents, act_dim]."""
        def one(actor_i, obs_i):   # obs_i [B, obs_dim]
            return self._squash(_mlp_forward(actor_i, obs_i, jax.nn.relu))

        out = jax.vmap(one, in_axes=(0, 1), out_axes=1)(
            params["actor"], obs_all)
        return out

    def q_values(self, params, state, joint_actions):
        """state [B, state_dim], joint_actions [B, joint_act] ->
        [B, n_agents]."""
        x = jnp.concatenate([state, joint_actions], axis=-1)

        def one(critic_i):
            return _mlp_forward(critic_i, x, jax.nn.relu)[..., 0]

        return jax.vmap(one)(params["critic"]).swapaxes(0, 1)


class MADDPGConfig(AlgorithmConfig):
    def __init__(self, algo_class=None):
        super().__init__(algo_class=algo_class or MADDPG)
        self.lr = 1e-3
        self.critic_lr = 1e-3
        self.gamma = 0.95
        self.tau = 0.01                     # polyak
        self.train_batch_size = 128
        self.replay_buffer_capacity = 50_000
        self.num_steps_sampled_before_learning_starts = 300
        self.exploration_noise = 0.3        # gaussian on actions
        self.hidden_dim = 64
        self.num_env_steps_per_iter = 128

    def _training_keys(self):
        return {"critic_lr", "tau", "train_batch_size",
                "replay_buffer_capacity", "exploration_noise",
                "hidden_dim", "num_env_steps_per_iter",
                "num_steps_sampled_before_learning_starts"}


class MADDPG(Algorithm):
    """Self-contained trainer over a MultiAgentEnv with continuous
    per-agent action spaces (the QMIX in-process collection pattern;
    distributed rollout rides MultiAgentEnvRunner when envs are costly)."""

    @classmethod
    def get_default_config(cls):
        return MADDPGConfig(algo_class=cls)

    def __init__(self, config):
        # bypass Algorithm.__init__'s env-runner/learner-group setup:
        # MADDPG owns its own in-process loop (the QMIX pattern)
        self.config = config
        self.setup(config)

    def setup(self, _config) -> None:
        cfg = self.config
        self._env = cfg.make_env()()
        self.agents = list(self._env.possible_agents)
        obs_space = self._env.observation_spaces[self.agents[0]]
        act_space = self._env.action_spaces[self.agents[0]]
        self.obs_dim = int(np.prod(obs_space.shape))
        self.act_dim = int(np.prod(act_space.shape))
        self.model = MADDPGModel(
            self.obs_dim, self.act_dim, len(self.agents),
            hidden=cfg.hidden_dim,
            act_low=float(np.min(act_space.low)),
            act_high=float(np.max(act_space.high)))
        self.params = self.model.init(jax.random.key(cfg.seed))
        self.target_params = jax.tree.map(jnp.copy, self.params)
        self.tx_actor = optax.adam(cfg.lr)
        self.tx_critic = optax.adam(cfg.critic_lr)
        self.opt_actor = self.tx_actor.init(self.params["actor"])
        self.opt_critic = self.tx_critic.init(self.params["critic"])
        self.replay = ReplayBuffer(cfg.replay_buffer_capacity,
                                   seed=cfg.seed)
        self._rng = np.random.default_rng(cfg.seed)
        self._obs: Any = None
        self._ep_return = 0.0
        self._total_env_steps = 0
        self._episode_returns: List[float] = []
        self._iteration = 0
        self._update_fn = self._build_update()

    # ------------------------------------------------------------ updates
    def _build_update(self):
        gamma, tau = self.config.gamma, self.config.tau
        model = self.model
        B_agents = len(self.agents)

        def critic_loss(critic, actor_tgt, critic_tgt, batch):
            state = batch["state"]
            next_state = batch["next_state"]
            next_obs = batch["next_obs"]
            next_act = model.actions({"actor": actor_tgt}, next_obs)
            next_q = model.q_values(
                {"critic": critic_tgt}, next_state,
                next_act.reshape(next_act.shape[0], -1))   # [B, n]
            y = batch["rewards"] + gamma * \
                (1.0 - batch["dones"][:, None]) * \
                jax.lax.stop_gradient(next_q)
            q = model.q_values({"critic": critic}, state,
                               batch["joint_actions"])
            return jnp.mean((q - y) ** 2)

        def actor_loss(actor, critic, batch):
            obs = batch["obs"]                           # [B, n, obs]
            acts = model.actions({"actor": actor}, obs)  # [B, n, act]
            # each agent's critic scores the joint action where ONLY its
            # own slot comes from its live actor; other slots use the
            # replayed actions (Lowe 2017 eq. 6)
            replay_acts = batch["joint_actions"].reshape(acts.shape)
            losses = []
            for i in range(B_agents):
                joint = replay_acts.at[:, i].set(acts[:, i])
                qi = model.q_values({"critic": critic}, batch["state"],
                                    joint.reshape(joint.shape[0], -1))
                losses.append(-jnp.mean(qi[:, i]))
            return sum(losses) / B_agents

        def update(params, target, opt_a, opt_c, batch):
            cl, cg = jax.value_and_grad(critic_loss)(
                params["critic"], target["actor"], target["critic"], batch)
            cu, opt_c = self.tx_critic.update(cg, opt_c, params["critic"])
            critic = optax.apply_updates(params["critic"], cu)
            al, ag = jax.value_and_grad(actor_loss)(
                params["actor"], critic, batch)
            au, opt_a = self.tx_actor.update(ag, opt_a, params["actor"])
            actor = optax.apply_updates(params["actor"], au)
            new_params = {"actor": actor, "critic": critic}
            new_target = jax.tree.map(
                lambda t, o: (1 - tau) * t + tau * o, target, new_params)
            return new_params, new_target, opt_a, opt_c, cl, al

        return jax.jit(update)

    # ---------------------------------------------------------- collection
    def _obs_matrix(self, obs_dict) -> np.ndarray:
        return np.stack([np.asarray(obs_dict[a], np.float32).reshape(-1)
                         for a in self.agents])

    def _collect(self, n_steps: int) -> int:
        cfg = self.config
        if self._obs is None:
            obs_dict, _ = self._env.reset(seed=int(self._rng.integers(1e9)))
            self._obs = self._obs_matrix(obs_dict)
            self._ep_return = 0.0
        for _ in range(n_steps):
            obs = self._obs
            acts = np.asarray(self.model.actions(
                self.params, obs[None]))[0]           # [n, act_dim]
            acts = acts + self._rng.normal(
                0.0, cfg.exploration_noise, acts.shape)
            acts = np.clip(acts, self.model.act_low, self.model.act_high)
            action_dict = {a: acts[i].astype(np.float32)
                           for i, a in enumerate(self.agents)}
            nxt, rewards, terms, truncs, _ = self._env.step(action_dict)
            done_all = bool(terms.get("__all__"))
            trunc_all = bool(truncs.get("__all__"))
            nxt_m = self._obs_matrix(nxt)
            r_vec = np.asarray([float(rewards.get(a, 0.0))
                                for a in self.agents], np.float32)
            self._ep_return += float(r_vec.sum())
            self.replay.add_batch({
                "obs": obs[None],
                "joint_actions": acts.reshape(1, -1).astype(np.float32),
                "rewards": r_vec[None],
                "next_obs": nxt_m[None],
                "state": obs.reshape(1, -1),
                "next_state": nxt_m.reshape(1, -1),
                "dones": np.asarray([float(done_all)], np.float32),
            })
            self._total_env_steps += 1
            if done_all or trunc_all:
                self._episode_returns.append(self._ep_return)
                obs_dict, _ = self._env.reset(
                    seed=int(self._rng.integers(1e9)))
                self._obs = self._obs_matrix(obs_dict)
                self._ep_return = 0.0
            else:
                self._obs = nxt_m
        return n_steps

    def training_step(self) -> Dict:
        cfg = self.config
        new = self._collect(cfg.num_env_steps_per_iter)
        metrics: Dict[str, Any] = {"env_steps_this_iter": new}
        if len(self.replay) >= cfg.num_steps_sampled_before_learning_starts:
            for _ in range(max(1, new // 32)):
                batch = self.replay.sample(cfg.train_batch_size)
                (self.params, self.target_params, self.opt_actor,
                 self.opt_critic, cl, al) = self._update_fn(
                    self.params, self.target_params, self.opt_actor,
                    self.opt_critic, batch)
            metrics["critic_loss"] = float(cl)
            metrics["actor_loss"] = float(al)
        if self._episode_returns:
            metrics["episode_return_mean"] = float(
                np.mean(self._episode_returns[-100:]))
        return metrics

    def train(self) -> Dict:
        self._iteration += 1
        out = self.training_step()
        out["training_iteration"] = self._iteration
        return out

    def stop(self) -> None:
        try:
            self._env.close()
        except Exception:
            pass
