"""Multi-agent RLlib tests (reference: rllib multi-agent test suite —
policy mapping, per-policy learning, shared-policy self-play)."""

import numpy as np
import pytest

import ray_tpu
from ray_tpu.rllib import MultiAgentEnv, MultiAgentPPOConfig


@pytest.fixture(scope="module")
def ray4():
    if not ray_tpu.is_initialized():
        ray_tpu.init(num_cpus=4)
    yield
    ray_tpu.shutdown()


class SignGame(MultiAgentEnv):
    """Each agent sees a 2-dim obs; action 1 is rewarded iff obs[0] > 0.
    Agents are independent — a clean probe that each policy learns from
    exactly its own agents' experience."""

    possible_agents = ["a0", "a1"]

    def __init__(self, episode_len=10, seed=0):
        import gymnasium as gym

        self._spaces = {
            a: gym.spaces.Box(-1, 1, (2,), np.float32)
            for a in self.possible_agents}
        self._aspaces = {a: gym.spaces.Discrete(2)
                         for a in self.possible_agents}
        self._rng = np.random.default_rng(seed)
        self._len = episode_len
        self._t = 0

    @property
    def observation_spaces(self):
        return self._spaces

    @property
    def action_spaces(self):
        return self._aspaces

    def _obs(self):
        return {a: self._rng.uniform(-1, 1, 2).astype(np.float32)
                for a in self.possible_agents}

    def reset(self, *, seed=None):
        self._t = 0
        self._cur = self._obs()
        return dict(self._cur), {}

    def step(self, action_dict):
        rewards = {}
        for a, act in action_dict.items():
            correct = int(self._cur[a][0] > 0)
            rewards[a] = 1.0 if int(act) == correct else 0.0
        self._t += 1
        done = self._t >= self._len
        self._cur = self._obs()
        obs = dict(self._cur)
        terms = {a: done for a in action_dict}
        terms["__all__"] = done
        truncs = {"__all__": False}
        return obs, rewards, terms, truncs, {}


def test_multi_agent_ppo_learns_per_policy(ray4):
    cfg = (MultiAgentPPOConfig()
           .environment(lambda cfg=None: SignGame())
           .multi_agent(policies=["p0", "p1"],
                        policy_mapping_fn=lambda aid: "p" + aid[-1])
           .env_runners(num_env_runners=2, rollout_fragment_length=64)
           .training(lr=5e-3, train_batch_size=256, minibatch_size=128,
                     num_epochs=6, entropy_coeff=0.0))
    algo = cfg.build()
    try:
        for i in range(7):
            r = algo.step()
        # both policies must act correctly on held-out observations
        for pid in ("p0", "p1"):
            correct = 0
            rng = np.random.default_rng(7)
            for _ in range(40):
                obs = rng.uniform(-1, 1, 2).astype(np.float32)
                act = algo.compute_single_action(obs, policy_id=pid)
                correct += int(act == int(obs[0] > 0))
            assert correct >= 30, f"{pid}: {correct}/40"
        assert any(k.startswith("p0/") for k in r)
        assert any(k.startswith("p1/") for k in r)
    finally:
        algo.stop()


def test_shared_policy_self_play(ray4):
    cfg = (MultiAgentPPOConfig()
           .environment(lambda cfg=None: SignGame())
           .multi_agent(policies=["shared"],
                        policy_mapping_fn=lambda aid: "shared")
           .env_runners(num_env_runners=1, rollout_fragment_length=64)
           .training(lr=3e-3, train_batch_size=128, minibatch_size=128,
                     num_epochs=4))
    algo = cfg.build()
    try:
        r = algo.step()
        assert r["env_steps_this_iter"] >= 128
        assert any(k.startswith("shared/") for k in r)
        # the shared policy saw BOTH agents' rows: 2 rows per env step
        ckpt_metrics = r["shared/total_loss"]
        assert np.isfinite(ckpt_metrics)
    finally:
        algo.stop()


def test_policy_mapping_validation(ray4):
    cfg = (MultiAgentPPOConfig()
           .environment(lambda cfg=None: SignGame())
           .multi_agent(policies=["p0", "orphan"],
                        policy_mapping_fn=lambda aid: "p0"))
    with pytest.raises(ValueError, match="orphan"):
        cfg.build()
