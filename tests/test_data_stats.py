"""Dataset.stats(): per-operator rows/bytes/wall/task-count collected by
the streaming executor (VERDICT r3 missing #5 / next #6; reference:
python/ray/data/_internal/stats.py rendered via ds.stats())."""

import numpy as np
import pytest

import ray_tpu
from ray_tpu import data as rdata


@pytest.fixture(scope="module")
def cluster():
    ray_tpu.init(num_cpus=4, ignore_reinit_error=True)
    yield ray_tpu
    ray_tpu.shutdown()


def test_stats_read_map_shuffle(cluster):
    ds = (rdata.range(1000, parallelism=8)
          .map_batches(lambda b: {"id": b["id"] * 2})
          .random_shuffle(seed=7))
    rows = sum(len(b["id"]) for b in ds.iter_batches(batch_size=None))
    assert rows == 1000

    report = ds.stats()
    d = ds._last_stats.to_dict()
    assert d["wall_s"] > 0
    ops = d["ops"]
    assert len(ops) >= 2  # read+map fused, shuffle stage(s)

    # the fused read->map operator produced all 1000 rows with real bytes
    first = ops[0]
    assert first["rows_out"] == 1000
    assert first["bytes_out"] > 0
    assert first["tasks"] == 8  # one task per block
    assert first["blocks_out"] == 8
    assert first["wall_s"] >= 0

    # the terminal operator emitted all rows, consumed what upstream made
    last = ops[-1]
    assert last["rows_out"] == 1000
    assert last["rows_in"] == 1000
    assert last["bytes_in"] > 0

    # the rendered report carries the reference-style lines
    assert "Operator 0" in report
    assert "tasks executed" in report
    assert "Rows: " in report
    assert "Dataset: " in report


def test_stats_published_to_kv_for_dashboard(cluster):
    ds = rdata.range(100, parallelism=2).map_batches(
        lambda b: {"id": b["id"] + 1})
    ds.materialize()
    from ray_tpu.experimental.internal_kv import _internal_kv_list

    keys = _internal_kv_list(b"__data_stats__:")
    assert keys, "driver did not publish dataset stats"
    # dashboard route consumes the same keys
    from ray_tpu.dashboard import DashboardActor

    api = DashboardActor.__new__(DashboardActor)
    out = api._api("/api/data_stats")
    assert out and out[-1]["ops"]


def test_stats_empty_before_execution(cluster):
    ds = rdata.range(10)
    assert ds.stats() == ""
