"""GBDT trainers (reference: python/ray/train/gbdt_trainer.py:98 —
XGBoostTrainer / LightGBMTrainer running on xgboost-ray/lightgbm-ray
actors).

Gated: neither ``xgboost`` nor ``lightgbm`` is in this image's baked
package set. When the library IS importable, training runs single-process
on the worker group's rank-0 actor (distributed tree building needs the
library's own rabit/network layer, out of scope here); otherwise
construction raises a clear ImportError naming the missing dependency.
"""

from __future__ import annotations

from typing import Any, Dict, Optional

from ray_tpu.air import RunConfig, ScalingConfig
from ray_tpu.train._checkpoint import Checkpoint
from ray_tpu.train.base_trainer import BaseTrainer, Result


class _GBDTTrainer(BaseTrainer):
    _lib_name = ""
    _lib_hint = ""

    def __init__(
        self,
        *,
        datasets: Dict[str, Any],
        label_column: str,
        params: Optional[Dict] = None,
        num_boost_round: int = 10,
        scaling_config: Optional[ScalingConfig] = None,
        run_config: Optional[RunConfig] = None,
        resume_from_checkpoint: Optional[Checkpoint] = None,
    ):
        self._require_lib()
        super().__init__(scaling_config=scaling_config,
                         run_config=run_config,
                         resume_from_checkpoint=resume_from_checkpoint,
                         datasets=datasets)
        self.label_column = label_column
        self.params = params or {}
        self.num_boost_round = num_boost_round

    @classmethod
    def _require_lib(cls):
        import importlib

        try:
            importlib.import_module(cls._lib_name)
        except ImportError as e:
            raise ImportError(
                f"{cls.__name__} requires `{cls._lib_name}`, which is not "
                f"installed in this environment. {cls._lib_hint}") from e

    def _to_matrix(self, ds):
        df = ds.to_pandas()
        y = df[self.label_column]
        X = df.drop(columns=[self.label_column])
        return X, y


class XGBoostTrainer(_GBDTTrainer):
    _lib_name = "xgboost"
    _lib_hint = ("Use JaxTrainer/TorchTrainer for neural models, or "
                 "install xgboost for tree models.")

    def training_loop(self) -> Result:
        import os
        import tempfile

        import xgboost as xgb

        X, y = self._to_matrix(self.datasets["train"])
        dtrain = xgb.DMatrix(X, label=y)
        evals = []
        if "valid" in self.datasets:
            Xv, yv = self._to_matrix(self.datasets["valid"])
            evals = [(xgb.DMatrix(Xv, label=yv), "valid")]
        results: Dict = {}
        booster = xgb.train(self.params, dtrain,
                            num_boost_round=self.num_boost_round,
                            evals=evals, evals_result=results)
        d = tempfile.mkdtemp(prefix="xgb_ckpt_")
        booster.save_model(os.path.join(d, "model.json"))
        metrics = {"num_boost_round": self.num_boost_round}
        for name, hist in results.items():
            for metric, vals in hist.items():
                metrics[f"{name}-{metric}"] = vals[-1]
        return Result(metrics=metrics, checkpoint=Checkpoint(d), path=d)


class LightGBMTrainer(_GBDTTrainer):
    _lib_name = "lightgbm"
    _lib_hint = ("Use JaxTrainer/TorchTrainer for neural models, or "
                 "install lightgbm for tree models.")

    def training_loop(self) -> Result:
        import os
        import tempfile

        import lightgbm as lgb

        X, y = self._to_matrix(self.datasets["train"])
        train_set = lgb.Dataset(X, label=y)
        valid_sets = []
        if "valid" in self.datasets:
            Xv, yv = self._to_matrix(self.datasets["valid"])
            valid_sets = [lgb.Dataset(Xv, label=yv)]
        booster = lgb.train(self.params, train_set,
                            num_boost_round=self.num_boost_round,
                            valid_sets=valid_sets)
        d = tempfile.mkdtemp(prefix="lgbm_ckpt_")
        booster.save_model(os.path.join(d, "model.txt"))
        return Result(metrics={"num_boost_round": self.num_boost_round},
                      checkpoint=Checkpoint(d), path=d)
