"""Replica actor (reference: python/ray/serve/_private/replica.py —
ReplicaActor :233, handle_request :391, rejection-based backpressure :487
``max_ongoing_requests``).

Hosts one instance of the user's deployment class/function. Requests above
``max_ongoing_requests`` are rejected with a sentinel so the router retries
elsewhere — backpressure flows to the caller instead of queueing here.
"""

from __future__ import annotations

import asyncio
import inspect
from typing import Any, Dict, Optional, Tuple

REJECTED = "__serve_rejected__"


class _HandlePlaceholder:
    """Marks a bound sub-deployment in init args; resolved to a
    DeploymentHandle inside the replica."""

    def __init__(self, app_name: str, dep_name: str):
        self.app_name = app_name
        self.dep_name = dep_name


class Replica:
    def __init__(self, blob: bytes, init_blob: bytes, app_name: str,
                 dep_name: str, max_ongoing_requests: int,
                 user_config: Any):
        import cloudpickle

        self._app_name = app_name
        self._dep_name = dep_name
        self._max_ongoing = max_ongoing_requests
        self._ongoing = 0
        self._draining = False

        func_or_class = cloudpickle.loads(blob)
        args, kwargs = cloudpickle.loads(init_blob)
        args = tuple(self._resolve_deep(a) for a in args)
        kwargs = {k: self._resolve_deep(v) for k, v in kwargs.items()}

        if isinstance(func_or_class, type):
            self._callable = func_or_class(*args, **kwargs)
            self._is_function = False
        else:
            self._callable = func_or_class
            self._is_function = True
        if user_config is not None:
            self._apply_user_config(user_config)

    @staticmethod
    def _resolve(arg):
        if isinstance(arg, _HandlePlaceholder):
            from ray_tpu.serve.handle import DeploymentHandle

            return DeploymentHandle(arg.app_name, arg.dep_name)
        return arg

    @classmethod
    def _resolve_deep(cls, arg):
        """Placeholders can sit inside graph nodes / containers
        (deployment-graph init args), not just at the top level."""
        from ray_tpu.serve.deployment import map_graph_values

        return map_graph_values(arg, cls._resolve)

    def _apply_user_config(self, cfg):
        fn = getattr(self._callable, "reconfigure", None)
        if fn is not None:
            fn(cfg)

    # ------------------------------------------------------------- control
    def ready(self) -> bool:
        return True

    def health_check(self) -> int:
        """Doubles as queue-len probe: returns ongoing request count."""
        check = getattr(self._callable, "check_health", None)
        if check is not None:
            check()
        return self._ongoing

    def get_queue_len(self) -> int:
        return self._ongoing

    def reconfigure(self, user_config) -> bool:
        self._apply_user_config(user_config)
        return True

    async def drain(self) -> bool:
        self._draining = True
        while self._ongoing > 0:
            await asyncio.sleep(0.02)
        return True

    def _target(self, method_name: Optional[str]):
        if self._is_function:
            return self._callable
        return getattr(self._callable, method_name or "__call__")

    # ------------------------------------------------------------- requests
    async def handle_request(self, method_name: Optional[str], args: Tuple,
                             kwargs: Dict, multiplexed_model_id: str = ""):
        if self._ongoing >= self._max_ongoing or self._draining:
            return (REJECTED, self._ongoing)
        self._ongoing += 1
        try:
            from ray_tpu.serve import multiplex

            if multiplexed_model_id:
                multiplex._set_request_model_id(multiplexed_model_id)
            target = self._target(method_name)
            if inspect.isgeneratorfunction(target) or \
                    inspect.isasyncgenfunction(target):
                # generator endpoint: the caller must re-issue through the
                # streaming path (checked BEFORE calling, so user code does
                # not run twice); reference replicas always stream (ASGI)
                return ("stream", None)
            if inspect.iscoroutinefunction(target):
                result = await target(*args, **kwargs)
            else:
                # sync user code runs off-loop so concurrent requests (and
                # the rejection check) aren't serialized behind it
                result = await asyncio.to_thread(target, *args, **kwargs)
                if inspect.iscoroutine(result):
                    result = await result
            from ray_tpu.serve.asgi import StreamingResponse, iterate_sync

            if isinstance(result, StreamingResponse) or \
                    inspect.isgenerator(result):
                # lazily-built stream object: drain it OFF-LOOP (this
                # coroutine runs on the replica's event loop; a sync drain
                # would stall concurrent requests, and iterate_sync spins a
                # private loop for async iterables which must not nest in a
                # running one). Bounded by the handle's 60s request budget;
                # declare the endpoint as a generator function for true
                # incremental streaming.
                if isinstance(result, StreamingResponse):
                    chunks = await asyncio.to_thread(
                        lambda: list(iterate_sync(result.content)))
                    return ("stream_buffered",
                            {"chunks": chunks,
                             "status_code": result.status_code,
                             "media_type": result.media_type,
                             "headers": result.headers})
                chunks = await asyncio.to_thread(lambda: list(result))
                return ("stream_buffered",
                        {"chunks": chunks, "status_code": 200,
                         "media_type": "application/octet-stream",
                         "headers": {}})
            return ("ok", result)
        finally:
            self._ongoing -= 1
            if multiplexed_model_id:
                multiplex._set_request_model_id("")

    def handle_request_streaming(self, method_name: Optional[str],
                                 args: Tuple, kwargs: Dict,
                                 multiplexed_model_id: str = ""):
        """Streaming execution path (reference: replica.py:471): a sync
        generator method — called with num_returns='streaming', each yield
        becomes an ObjectRef at the caller as it is produced. First item is
        the admission handshake."""
        if self._ongoing >= self._max_ongoing or self._draining:
            yield (REJECTED, self._ongoing)
            return
        self._ongoing += 1
        try:
            from ray_tpu.serve import multiplex
            from ray_tpu.serve.asgi import StreamingResponse, iterate_sync

            if multiplexed_model_id:
                multiplex._set_request_model_id(multiplexed_model_id)
            target = self._target(method_name)
            if inspect.isasyncgenfunction(target):
                result = target(*args, **kwargs)
            elif inspect.iscoroutinefunction(target):
                result = asyncio.run(target(*args, **kwargs))
            else:
                result = target(*args, **kwargs)
            if isinstance(result, StreamingResponse):
                yield ("start", {"status_code": result.status_code,
                                 "media_type": result.media_type,
                                 "headers": result.headers})
                for chunk in iterate_sync(result.content):
                    yield ("chunk", chunk)
            elif inspect.isgenerator(result) or hasattr(result, "__aiter__"):
                yield ("start", {"status_code": 200,
                                 "media_type": "application/octet-stream",
                                 "headers": {}})
                for chunk in iterate_sync(result):
                    yield ("chunk", chunk)
            else:
                # non-streaming endpoint called through the streaming path:
                # a single-chunk stream
                yield ("start", {"status_code": 200, "media_type": None,
                                 "headers": {}})
                yield ("chunk", result)
        finally:
            self._ongoing -= 1
            if multiplexed_model_id:
                multiplex._set_request_model_id("")
