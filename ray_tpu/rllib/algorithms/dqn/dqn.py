"""DQN — double DQN with target network and (optionally prioritized)
replay (reference: rllib/algorithms/dqn/dqn.py DQNConfig/DQN and
dqn/torch/dqn_torch_learner.py loss; Mnih 2015 / van Hasselt 2016).

TPU-first shape: the whole update — gather Q(s,a), double-DQN target from
the online argmax + target net, Huber loss, adam step — is one jitted
function; the replay buffer stays host-side numpy and ships one contiguous
minibatch per step.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, Tuple

import jax
import jax.numpy as jnp
import numpy as np
import optax

import ray_tpu
from ray_tpu.rllib.algorithms.algorithm import Algorithm
from ray_tpu.rllib.algorithms.algorithm_config import AlgorithmConfig
from ray_tpu.rllib.core.learner import Learner
from ray_tpu.rllib.utils.replay_buffer import (
    PrioritizedReplayBuffer,
    ReplayBuffer,
)


# ------------------------------------------------------------------- module
@dataclasses.dataclass
class DQNModuleSpec:
    """Q-network spec (reference: dqn/dqn_rl_module.py)."""

    obs_dim: int
    action_dim: int
    discrete: bool = True  # DQN is discrete-only
    hiddens: Tuple[int, ...] = (64, 64)
    activation: str = "relu"
    dueling: bool = True

    def build(self) -> "DQNModule":
        return DQNModule(self)


class DQNModule:
    """MLP Q-network, optionally dueling (value + advantage streams,
    reference: dqn dueling head)."""

    def __init__(self, spec: DQNModuleSpec):
        self.spec = spec
        self._act = {"tanh": jnp.tanh, "relu": jax.nn.relu}[spec.activation]

    def init(self, rng) -> Dict:
        def mlp(key, sizes):
            layers = []
            for a, b in zip(sizes[:-1], sizes[1:]):
                key, sub = jax.random.split(key)
                layers.append({
                    "w": jax.random.normal(sub, (a, b)) * jnp.sqrt(2.0 / a),
                    "b": jnp.zeros((b,)),
                })
            return layers

        k1, k2 = jax.random.split(rng)
        sizes = (self.spec.obs_dim, *self.spec.hiddens)
        params = {"q": mlp(k1, sizes + (self.spec.action_dim,))}
        if self.spec.dueling:
            params["v"] = mlp(k2, sizes + (1,))
        # exploration epsilon rides in params so the jitted env-runner
        # inference sees updates without recompilation
        params["epsilon"] = jnp.asarray(1.0, jnp.float32)
        return params

    def _tower(self, layers, x):
        for layer in layers[:-1]:
            x = self._act(x @ layer["w"] + layer["b"])
        last = layers[-1]
        return x @ last["w"] + last["b"]

    def q_values(self, params, obs) -> jnp.ndarray:
        adv = self._tower(params["q"], obs)
        if self.spec.dueling:
            v = self._tower(params["v"], obs)
            return v + adv - adv.mean(axis=-1, keepdims=True)
        return adv

    # env-runner interface (same contract as MLPModule)
    def forward(self, params, obs) -> Dict[str, jnp.ndarray]:
        q = self.q_values(params, obs)
        return {"logits": q, "vf": q.max(axis=-1)}

    def explore_action(self, params, obs, rng):
        q = self.q_values(params, obs)
        greedy = jnp.argmax(q, axis=-1)
        k1, k2 = jax.random.split(rng)
        random_a = jax.random.randint(
            k1, greedy.shape, 0, self.spec.action_dim)
        explore = (jax.random.uniform(k2, greedy.shape)
                   < params["epsilon"])
        action = jnp.where(explore, random_a, greedy)
        zeros = jnp.zeros_like(q[..., 0])
        return action, zeros, zeros  # logp/vf unused by off-policy replay


# ------------------------------------------------------------------ learner
class DQNLearner(Learner):
    """Double-DQN Huber loss with target network
    (reference: dqn_torch_learner.py compute_loss_for_module)."""

    def __init__(self, module_spec, config, use_mesh: bool = False):
        # single-mesh learner: _build_update below jits without data-axis
        # shardings (target_params riding in the batch must stay replicated)
        super().__init__(module_spec, config, use_mesh=use_mesh)
        self.target_params = jax.tree.map(jnp.copy, self.params)

    def loss(self, params, batch):
        cfg = self.config
        gamma = cfg.get("gamma", 0.99)
        q_all = self.module.q_values(params, batch["obs"])
        q_sa = jnp.take_along_axis(
            q_all, batch["actions"][:, None].astype(jnp.int32), axis=1)[:, 0]
        # double DQN: online net picks a*, target net evaluates it
        next_online = self.module.q_values(params, batch["next_obs"])
        a_star = jnp.argmax(next_online, axis=-1)
        next_target = self.module.q_values(batch["target_params"],
                                           batch["next_obs"])
        q_next = jnp.take_along_axis(
            next_target, a_star[:, None], axis=1)[:, 0]
        target = batch["rewards"] + gamma * (1.0 - batch["dones"]) * \
            jax.lax.stop_gradient(q_next)
        td = q_sa - target
        huber = jnp.where(jnp.abs(td) < 1.0, 0.5 * td ** 2,
                          jnp.abs(td) - 0.5)
        weights = batch.get("weights")
        loss = jnp.mean(huber * weights) if weights is not None \
            else jnp.mean(huber)
        return loss, {"td_error_mean": jnp.mean(jnp.abs(td)),
                      "qf_mean": jnp.mean(q_sa), "td_error": td}

    def _build_update(self):
        # epsilon is exploration state, not a trainable — mask its gradient
        def update(params, opt_state, batch):
            def masked_loss(p):
                return self.loss(p, batch)

            (loss, metrics), grads = jax.value_and_grad(
                masked_loss, has_aux=True)(params)
            grads["epsilon"] = jnp.zeros_like(params["epsilon"])
            updates, opt_state = self.tx.update(grads, opt_state, params)
            params = optax.apply_updates(params, updates)
            metrics["total_loss"] = loss
            return params, opt_state, metrics

        return jax.jit(update)

    def update(self, batch: Dict[str, np.ndarray]) -> Dict[str, float]:
        batch = dict(batch)
        idx = batch.pop("batch_indexes", None)
        batch["target_params"] = self.target_params
        self.params, self.opt_state, metrics = self._update(
            self.params, self.opt_state, batch)
        td = np.asarray(metrics.pop("td_error"))
        out = {k: float(v) for k, v in metrics.items()}
        out["_td_error"] = td
        out["_batch_indexes"] = idx
        return out

    def sync_target(self, tau: float = 1.0) -> None:
        """Hard (tau=1) or polyak target update."""
        self.target_params = jax.tree.map(
            lambda t, o: (1 - tau) * t + tau * o,
            self.target_params, self.params)

    def set_epsilon(self, eps: float) -> None:
        self.params["epsilon"] = jnp.asarray(eps, jnp.float32)

    def get_state(self) -> Dict:
        s = super().get_state()
        s["target_params"] = jax.device_get(self.target_params)
        return s

    def set_state(self, state: Dict) -> None:
        super().set_state(state)
        self.target_params = state["target_params"]


# ---------------------------------------------------------------- algorithm
class DQNConfig(AlgorithmConfig):
    def __init__(self, algo_class=None):
        super().__init__(algo_class=algo_class or DQN)
        self.lr = 5e-4
        self.train_batch_size = 32
        self.replay_buffer_capacity = 50_000
        self.num_steps_sampled_before_learning_starts = 1000
        self.target_network_update_freq = 500  # env steps
        self.training_intensity = 1.0  # updates per env step sampled
        self.epsilon = [(0, 1.0), (10_000, 0.05)]  # linear schedule
        self.double_q = True
        self.dueling = True
        self.prioritized_replay = False
        self.rollout_fragment_length = 4
        self.num_env_runners = 1

    def _training_keys(self):
        return {"replay_buffer_capacity", "target_network_update_freq",
                "num_steps_sampled_before_learning_starts", "epsilon",
                "double_q", "dueling", "prioritized_replay",
                "training_intensity"}

    def module_spec(self) -> DQNModuleSpec:
        base = super().module_spec()
        if not base.discrete:
            raise ValueError("DQN supports discrete action spaces only")
        return DQNModuleSpec(
            obs_dim=base.obs_dim, action_dim=base.action_dim,
            hiddens=tuple(self.model.get("hiddens", (64, 64))),
            activation=self.model.get("activation", "relu"),
            dueling=self.dueling)


class DQN(Algorithm):
    learner_cls = DQNLearner

    @classmethod
    def get_default_config(cls):
        return DQNConfig(algo_class=cls)

    def setup(self, _config) -> None:
        super().setup(_config)
        cfg = self.config
        self.replay = (PrioritizedReplayBuffer(cfg.replay_buffer_capacity,
                                               seed=cfg.seed)
                       if cfg.prioritized_replay
                       else ReplayBuffer(cfg.replay_buffer_capacity,
                                         seed=cfg.seed))
        self._steps_since_target_sync = 0

    def _make_runner(self, idx: int):
        cfg = self.config
        from ray_tpu.rllib.env.env_runner import SingleAgentEnvRunner

        return ray_tpu.remote(SingleAgentEnvRunner).options(
            resources={"CPU": 1}).remote(
                cfg.make_env(), cfg.num_envs_per_env_runner,
                cfg.rollout_fragment_length, self._module_spec,
                seed=cfg.seed + idx * 1000 + 1, explore=cfg.explore,
                gamma=cfg.gamma, collect_next_obs=True,
                connector=cfg.connector)

    def _epsilon_at(self, step: int) -> float:
        from ray_tpu.rllib.utils.schedules import piecewise_linear

        return piecewise_linear(self.config.epsilon, step)

    def training_step(self) -> Dict:
        cfg = self.config
        learner = self.learner_group.local_learner()
        learner.set_epsilon(self._epsilon_at(self._total_env_steps))
        weights_ref = ray_tpu.put(learner.get_weights())

        samples = self._sample_from_runners(weights_ref)
        new_steps = sum(s["env_steps"] for s in samples)
        for s in samples:
            flat = lambda a: a.reshape((-1,) + a.shape[2:])
            mask = flat(s["valid"])
            self.replay.add_batch({
                "obs": flat(s["obs"])[mask],
                "actions": flat(s["actions"])[mask],
                "rewards": flat(s["rewards"])[mask],
                "next_obs": flat(s["next_obs"])[mask],
                "dones": flat(s["dones"])[mask],
            })

        metrics: Dict = {"env_steps_this_iter": new_steps}
        if len(self.replay) < cfg.num_steps_sampled_before_learning_starts:
            return metrics

        num_updates = max(1, int(new_steps * cfg.training_intensity /
                                 max(cfg.train_batch_size, 1)))
        for _ in range(num_updates):
            batch = self.replay.sample(cfg.train_batch_size)
            out = learner.update(batch)
            td = out.pop("_td_error", None)
            idx = out.pop("_batch_indexes", None)
            if idx is not None and td is not None and hasattr(
                    self.replay, "update_priorities"):
                self.replay.update_priorities(idx, td)
            metrics.update(out)
        self._steps_since_target_sync += new_steps
        if self._steps_since_target_sync >= cfg.target_network_update_freq:
            learner.sync_target()
            self._steps_since_target_sync = 0
        metrics["epsilon"] = self._epsilon_at(self._total_env_steps)
        return metrics
