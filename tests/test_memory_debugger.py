"""Cluster object ownership ledger + memory debugger (ISSUE 15).

Five layers:

1. **ReferenceCounter edge cases** — double ``remove_local_ref``, borrow
   registered after owner death, task-pin vs local-ref interplay,
   ``_ready_to_free`` under concurrent add/remove from the GC path, and
   the no-resurrection contract of ``set_resolved`` (the late-reply leak
   the conftest ref gate caught in-PR).
2. **Provenance** — every owned object carries callsite / creator /
   size; the callsite tag is interned and cheap enough for the put path.
3. **Introspection plane e2e** — worker/agent ``GetObjectRefs``, head
   ``ObjectSummary`` groupings, the util.state API, and the ≥95%
   store-byte attribution acceptance criterion.
4. **Leak watchdog** — a deliberately leaked 16 MB object (ref dropped
   while an eviction-blocking pin wedges reclamation) is flagged within
   two scan intervals; the CLI ``memory --leaks`` surfaces it.
5. **Prometheus conformance** — HELP/TYPE lines, histogram
   ``_bucket``/``_sum``/``_count`` series, label escaping, and the
   scrape endpoint's ``text/plain; version=0.0.4`` content type.
"""

import gc
import os
import socket
import threading
import time
import urllib.request

import numpy as np
import pytest

import ray_tpu
from ray_tpu._private.ids import ObjectID, WorkerID
from ray_tpu._private.worker import (
    ReferenceCounter, _user_callsite, _CALLSITE_CACHE)


def _wait_for(fn, timeout=30.0, what="condition"):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        out = fn()
        if out:
            return out
        time.sleep(0.1)
    raise AssertionError(f"timed out waiting for {what}")


# ---------------------------------------------------------------------------
# 1. ReferenceCounter edge cases (pure unit, fake worker)
# ---------------------------------------------------------------------------
class _FakeWorker:
    """Just enough Worker for the counter: records frees/notifications
    and mimics the real free path (state -> freed, then drop)."""

    def __init__(self):
        self.freed = []
        self.notifications = []
        self.current_task_info = threading.local()
        self.reference_counter = None  # set after construction

    def _free_owned(self, binary):
        self.freed.append(binary)
        meta = self.reference_counter.get_owned_meta(binary)
        if meta is not None:
            meta.state = "freed"
        self.reference_counter.drop_owned(binary)

    def _notify_owner_async(self, owner, method, payload):
        self.notifications.append((owner, method, payload))

    def _loop_call(self, fn, *args):
        fn(*args)


class _Ref:
    def __init__(self, b):
        self._b = b

    def binary(self):
        return self._b


def _counter():
    w = _FakeWorker()
    rc = ReferenceCounter(w)
    w.reference_counter = rc
    return w, rc


def _oid(i: int = 1) -> ObjectID:
    return ObjectID.from_put(i, WorkerID.from_random())


class TestReferenceCounterEdges:
    def test_double_remove_local_ref_frees_exactly_once(self):
        w, rc = _counter()
        oid = _oid()
        rc.register_owned(oid)
        ref = _Ref(oid.binary())
        rc.add_local_ref(ref)
        rc.remove_local_ref(ref)
        assert w.freed == [oid.binary()]
        # second remove: counter must not go negative, must not double-free
        rc.remove_local_ref(ref)
        assert w.freed == [oid.binary()]
        assert oid.binary() not in rc._local
        assert oid.binary() not in rc._owned

    def test_borrow_registered_after_owner_death(self):
        # owner-side: an AddBorrow landing for an object the owner
        # already dropped (borrower raced the free) must count and
        # unwind cleanly without resurrecting or crashing
        w, rc = _counter()
        b = _oid().binary()
        rc.add_borrow(b)
        assert rc._borrows[b] == 1
        rc.remove_borrow(b)
        assert b not in rc._borrows
        assert w.freed == []  # nothing owned: nothing to free
        assert b not in rc._owned

    def test_task_pin_vs_local_ref_interplay(self):
        w, rc = _counter()
        oid = _oid()
        rc.register_owned(oid)
        ref = _Ref(oid.binary())
        rc.add_local_ref(ref)
        rc.pin_for_task(oid.binary())
        rc.remove_local_ref(ref)
        assert w.freed == []  # the in-flight task arg still pins it
        rc.pin_for_task(oid.binary())  # second task pins the same arg
        rc.unpin_for_task(oid.binary())
        assert w.freed == []
        rc.unpin_for_task(oid.binary())
        assert w.freed == [oid.binary()]
        # double unpin after free: no negative count, no second free
        rc.unpin_for_task(oid.binary())
        assert w.freed == [oid.binary()]
        assert oid.binary() not in rc._task_pins

    def test_ready_to_free_under_concurrent_add_remove(self):
        # the GC path (ObjectRef.__del__ -> remove_local_ref) races task
        # pin/unpin from the submit path; the counter must neither
        # deadlock nor leave residue, and the object must free
        w, rc = _counter()
        oid = _oid()
        rc.register_owned(oid)
        ref = _Ref(oid.binary())
        rc.add_local_ref(ref)  # anchor so mid-test zero doesn't free
        stop = threading.Event()
        errors = []

        def hammer(add, remove):
            try:
                while not stop.is_set():
                    add()
                    remove()
            except Exception as e:  # pragma: no cover
                errors.append(e)

        threads = [
            threading.Thread(target=hammer,
                             args=(lambda: rc.add_local_ref(ref),
                                   lambda: rc.remove_local_ref(ref))),
            threading.Thread(target=hammer,
                             args=(lambda: rc.pin_for_task(oid.binary()),
                                   lambda: rc.unpin_for_task(oid.binary()))),
            threading.Thread(target=hammer,
                             args=(lambda: rc.add_borrow(oid.binary()),
                                   lambda: rc.remove_borrow(oid.binary()))),
        ]
        for t in threads:
            t.start()
        time.sleep(0.5)
        stop.set()
        for t in threads:
            t.join(timeout=10)
            assert not t.is_alive(), "counter deadlocked"
        assert not errors
        rc.remove_local_ref(ref)  # drop the anchor: must free now
        assert oid.binary() in set(w.freed)
        assert oid.binary() not in rc._owned
        assert rc._local.get(oid.binary(), 0) == 0

    def test_set_resolved_never_resurrects(self):
        # the late-reply leak: resolving after every ref died must NOT
        # re-create the owned entry (found in-PR by the conftest gate)
        w, rc = _counter()
        b = _oid().binary()
        rc.set_resolved(b, "plasma", [{"host": "x", "port": 1}], size=512)
        assert b not in rc._owned

    def test_register_owned_provenance_stamped_once(self):
        w, rc = _counter()
        oid = _oid()
        meta = rc.register_owned(oid, callsite="mod:fn:1", creator="driver",
                                 creator_id="", size=100)
        again = rc.register_owned(oid, callsite="other:fn:9",
                                  creator="task:x", size=999)
        assert again is meta
        assert meta.callsite == "mod:fn:1"
        assert meta.creator == "driver"
        assert meta.size == 100
        assert meta.created_at > 0

    def test_dump_and_ref_info_shapes(self):
        w, rc = _counter()
        oid = _oid()
        rc.register_owned(oid, callsite="mod:fn:1", creator="task:f",
                          creator_id="ab" * 8, size=2048)
        rc.add_local_ref(_Ref(oid.binary()))
        rc.pin_for_task(oid.binary())
        out = rc.dump()
        (row,) = out["owned"]
        assert row["object_id"] == oid.hex()
        assert row["callsite"] == "mod:fn:1"
        assert row["creator"] == "task:f"
        assert row["size_bytes"] == 2048
        assert row["local_refs"] == 1 and row["task_pins"] == 1
        assert out["counts"]["owned"] == 1
        info = rc.ref_info([oid.binary(), b"\x00" * 20])
        assert info[oid.hex()]["owned"] and info[oid.hex()]["task_pins"] == 1
        assert not info[(b"\x00" * 20).hex()]["owned"]


# ---------------------------------------------------------------------------
# 2. callsite tag: correctness, interning, cost
# ---------------------------------------------------------------------------
def test_user_callsite_names_this_file():
    tag = _user_callsite(1)
    mod, qual, line = tag.rsplit(":", 2)
    assert mod == "test_memory_debugger"
    assert "test_user_callsite_names_this_file" in qual
    assert int(line) > 0


def test_user_callsite_interned_and_cheap():
    a = _user_callsite(1)
    b = _user_callsite(1)
    # same site on different lines differs; the SAME call site returns
    # the identical interned string (one dict probe after first hit)
    assert a is not b or a == b
    n = 2000
    t0 = time.perf_counter()
    for _ in range(n):
        _user_callsite(1)
    per_call = (time.perf_counter() - t0) / n
    # generous bound (measured ~1-3us): the put path serializes + RPCs,
    # so tens of microseconds would already be noise — but a frame-walk
    # regression to milliseconds must fail loudly
    assert per_call < 100e-6, f"callsite capture {per_call * 1e6:.1f}us/op"
    assert len(_CALLSITE_CACHE) < 4096


# ---------------------------------------------------------------------------
# 3. multi-node fan-out (own 2-node cluster, BEFORE the module cluster)
# ---------------------------------------------------------------------------
def test_object_summary_two_agents():
    """The head fan-out covers every agent: an object sealed on a
    second node is attributed from the head's view, and ≥95% of used
    store bytes across BOTH nodes trace to a creating callsite (the
    live-multi-node acceptance shape)."""
    from ray_tpu.cluster_utils import Cluster

    assert not ray_tpu.is_initialized()
    cluster = Cluster(initialize_head=True,
                      head_node_args={"num_cpus": 2})
    try:
        ray_tpu.init(_node=cluster.head_node)
        cluster.add_node(num_cpus=1, resources={"far": 1})
        cluster.wait_for_nodes()

        @ray_tpu.remote(resources={"far": 1})
        def far_produce():
            return np.ones(128 * 1024, np.float64)  # seals on far node

        near = ray_tpu.put(np.ones(128 * 1024, np.float64))
        far = far_produce.remote()
        ray_tpu.wait([far], num_returns=1, timeout=60)
        w = _worker()
        out = _wait_for(
            lambda: (lambda o: o if len([
                n for n, nd in o["nodes"].items()
                if not nd.get("error")
                and (nd.get("store") or {}).get("used", 0) > 0]) >= 2
                else None)(
                w.head_call("ObjectSummary",
                            {"group_by": "callsite", "detail": True},
                            timeout=30)),
            timeout=30, what="both agents reporting store bytes")
        rows = {r["object_id"]: r for r in out["rows"]}
        assert near.hex() in rows and far.hex() in rows
        # both objects are owned by this driver; the far one RESIDES on
        # the far node
        assert rows[far.hex()]["owner_node_id"] == w.node_id
        assert rows[far.hex()]["node_id"] != rows[near.hex()]["node_id"]
        attr = out["attribution"]
        assert attr["store_bytes"] > 0 and attr["ratio"] >= 0.95, attr
        del near, far
    finally:
        ray_tpu.shutdown()
        cluster.shutdown()


# ---------------------------------------------------------------------------
# 4. introspection plane + leak watchdog (one armed cluster)
# ---------------------------------------------------------------------------
@pytest.fixture(scope="module")
def ledger_cluster():
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
    env = {
        "RAY_TPU_OBJECT_LEAK_SCAN_INTERVAL_S": "0.4",
        "RAY_TPU_OBJECT_LEAK_MIN_BYTES": str(256 * 1024),
        "RAY_TPU_METRICS_EXPORT_PORT": str(port),
    }
    saved = {k: os.environ.get(k) for k in env}
    os.environ.update(env)
    assert not ray_tpu.is_initialized()
    ctx = ray_tpu.init(num_cpus=2)
    yield ctx, port
    ray_tpu.shutdown()
    for k, v in saved.items():
        if v is None:
            os.environ.pop(k, None)
        else:
            os.environ[k] = v


def _worker():
    from ray_tpu._private import worker as wm

    return wm.global_worker


def test_put_provenance_in_owned_dump(ledger_cluster):
    ref = ray_tpu.put(np.ones(256 * 1024, np.float64))  # 2 MB, plasma
    w = _worker()
    rows = {r["object_id"]: r
            for r in w.reference_counter.dump()["owned"]}
    row = rows[ref.hex()]
    assert row["creator"] == "driver"
    assert row["state"] == "plasma"
    assert row["size_bytes"] >= 2 * 1024 * 1024
    mod, qual, line = row["callsite"].rsplit(":", 2)
    assert mod == "test_memory_debugger"
    assert "test_put_provenance_in_owned_dump" in qual
    del ref


def test_task_return_provenance(ledger_cluster):
    @ray_tpu.remote
    def produce():
        return np.zeros(128 * 1024, np.float64)  # 1 MB: plasma return

    ref = produce.remote()
    assert ray_tpu.get(ref, timeout=60).nbytes == 1024 * 1024
    w = _worker()
    row = {r["object_id"]: r
           for r in w.reference_counter.dump()["owned"]}[ref.hex()]
    assert row["creator"].startswith("task:")
    assert row["creator"].endswith("produce")
    assert len(row["creator_id"]) > 0
    assert row["size_bytes"] >= 1024 * 1024
    assert "test_task_return_provenance" in row["callsite"]
    del ref


def test_agent_get_object_refs(ledger_cluster):
    ref = ray_tpu.put(np.ones(128 * 1024, np.float64))
    w = _worker()
    out = w._acall(w.agent.call("GetObjectRefs", {}, timeout=15),
                   timeout=20)
    assert out["node_id"] == w.node_id
    assert "shm_bytes" in out["tiers"]
    objs = {o["object_id"]: o for o in out["objects"]}
    assert ref.hex() in objs
    assert objs[ref.hex()]["owner"]["port"] == w.direct_port
    # the driver's own ref table must be among the process dumps
    dumps = [p for p in out["processes"] if not p.get("error")]
    owned_ids = {r["object_id"] for d in dumps for r in d["owned"]}
    assert ref.hex() in owned_ids
    del ref


def test_object_summary_attributes_store_bytes(ledger_cluster):
    held = [ray_tpu.put(np.ones(64 * 1024, np.float64)) for _ in range(4)]

    @ray_tpu.remote
    def produce():
        return np.zeros(64 * 1024, np.float64)

    held += [produce.remote() for _ in range(2)]
    ray_tpu.wait(held, num_returns=len(held), timeout=60)
    w = _worker()
    out = w.head_call("ObjectSummary",
                      {"group_by": "callsite", "detail": True}, timeout=30)
    attr = out["attribution"]
    assert attr["store_bytes"] > 0
    # the acceptance criterion: >= 95% of used store bytes (counted
    # per copy) trace to a creating callsite/task (here: all of them)
    assert attr["ratio"] >= 0.95, attr
    groups = out["groups"]
    assert any("test_object_summary_attributes_store_bytes" in k
               for k in groups)
    top = max(groups.items(), key=lambda kv: kv[1]["total_bytes"])
    assert top[1]["count"] >= 1
    # other grouping axes answer too
    by_tier = w.head_call("ObjectSummary", {"group_by": "tier"}, timeout=30)
    assert "shm" in by_tier["groups"]
    by_creator = w.head_call("ObjectSummary", {"group_by": "creator"},
                             timeout=30)
    assert any(k.endswith("produce") or k == "driver"
               for k in by_creator["groups"])
    by_node = w.head_call("ObjectSummary", {"group_by": "node"}, timeout=30)
    assert w.node_id in by_node["groups"]
    assert by_node["groups"][w.node_id]["refs"].get("owned", 0) >= len(held)
    del held


def test_state_api_list_and_summarize(ledger_cluster):
    ref = ray_tpu.put(np.ones(128 * 1024, np.float64))
    from ray_tpu.util import state as state_api

    rows = state_api.list_objects(
        filters=[("creator", "=", "driver")], limit=10000)
    assert any(r["object_id"] == ref.hex() for r in rows)
    summ = state_api.summarize_objects(group_by="callsite")
    assert any("test_state_api_list_and_summarize" in k for k in summ)
    by_node = state_api.summarize_objects()  # default: node
    w = _worker()
    assert by_node[w.node_id]["total_bytes"] > 0
    with pytest.raises(ValueError):
        state_api.summarize_objects(group_by="nope")
    del ref


def test_memory_cli_and_status_surface(ledger_cluster, capsys):
    held = ray_tpu.put(np.ones(128 * 1024, np.float64))
    from ray_tpu.scripts.cli import main as cli_main

    assert cli_main(["memory", "--group-by", "callsite", "--leaks"]) == 0
    out = capsys.readouterr().out
    assert "Grouped by callsite" in out
    assert "test_memory_cli_and_status_surface" in out
    assert "Leak suspects" in out
    assert cli_main(["memory", "--group-by", "tier"]) == 0
    out = capsys.readouterr().out
    assert "shm" in out
    assert cli_main(["status"]) == 0
    out = capsys.readouterr().out
    assert "Object plane" in out
    assert "owned" in out
    del held


def test_leak_watchdog_flags_wedged_object(ledger_cluster):
    """The chaos case: a 16 MB object's ref is dropped while an
    eviction-blocking pin wedges reclamation (here: the free path never
    runs because the owner's ledger lost the entry). The watchdog must
    flag it within ~2 scan intervals."""
    w = _worker()
    arr = np.ones(2 * 1024 * 1024, np.float64)  # 16 MB
    ref = ray_tpu.put(arr)
    hex_id = ref.hex()
    binary = ref.binary()
    # wedge: an eviction-blocking pin (the agent pins for a consumer
    # that will never unpin — the stuck-borrower shape)
    w._acall(w.agent.call("PinObject", {"object_id": hex_id}, timeout=15))
    # drop the ref while the free is lost: the owner's table forgets the
    # object without FreeObjects ever reaching the store
    w.reference_counter.drop_owned(binary)
    del ref
    gc.collect()

    def flagged():
        out = w._acall(w.agent.call("GetObjectRefs", {}, timeout=15),
                       timeout=20)
        return [s for s in out["leak_suspects"]
                if s["object_id"] == hex_id] or None

    # 2 scan intervals at 0.4s + RPC slack
    (suspect,) = _wait_for(flagged, timeout=15.0, what="leak suspect")
    assert suspect["reason"] == "owner_dropped"
    assert suspect["size_bytes"] >= 16 * 1024 * 1024
    assert suspect["pinned"] is True

    # the CLI surfaces it
    from ray_tpu.scripts.cli import main as cli_main
    import io
    import contextlib

    buf = io.StringIO()
    with contextlib.redirect_stdout(buf):
        assert cli_main(["memory", "--leaks", "--group-by", "node"]) == 0
    assert hex_id[:16] in buf.getvalue()

    # clean up the deliberate leak: unpin + free, and verify the
    # watchdog's suspect list drains (no sticky false positives)
    w._acall(w.agent.call("UnpinObject", {"object_id": hex_id}, timeout=15))
    w._acall(w.agent.call("FreeObjects", {"ids": [hex_id]}, timeout=15))
    _wait_for(lambda: not flagged(), timeout=15.0,
              what="suspect list to drain after free")


# ---------------------------------------------------------------------------
# 5. Prometheus conformance
# ---------------------------------------------------------------------------
def test_render_prometheus_conformance():
    from ray_tpu.util.metrics import render_prometheus

    snaps = [
        {"name": "app_requests_total", "kind": "counter",
         "description": "Requests with \\ and \n newline.",
         "values": [[[["route", 'a"b\\c\nd']], 3.0]]},
        {"name": "app_latency_seconds", "kind": "histogram",
         "description": "Latency.", "boundaries": [0.1, 1.0],
         "counts": [[[["m", "g"]], [2, 1, 1]]],
         "sums": [[[["m", "g"]], 1.7]]},
        {"name": "app_gauge", "kind": "weird-kind", "description": "",
         "values": [[[], 1.0]]},
    ]
    text = render_prometheus(snaps)
    lines = text.strip().split("\n")
    # every sample family is preceded by its HELP and TYPE lines
    families = {}
    for ln in lines:
        if ln.startswith("# TYPE "):
            _, _, name, kind = ln.split(" ", 3)
            families[name] = kind
        elif ln.startswith("# HELP "):
            continue
    for ln in lines:
        if ln.startswith("#") or not ln:
            continue
        name = ln.split("{", 1)[0].split(" ", 1)[0]
        base = name
        for suffix in ("_bucket", "_sum", "_count"):
            if name.endswith(suffix) and name[: -len(suffix)] in families:
                base = name[: -len(suffix)]
        assert base in families, f"sample {name} has no TYPE"
    # histogram conformance: cumulative buckets, +Inf, _sum and _count
    assert 'app_latency_seconds_bucket{m="g",le="0.1"} 2' in text
    assert 'app_latency_seconds_bucket{m="g",le="1.0"} 3' in text
    assert 'app_latency_seconds_bucket{m="g",le="+Inf"} 4' in text
    assert 'app_latency_seconds_count{m="g"} 4' in text
    assert 'app_latency_seconds_sum{m="g"} 1.7' in text
    # label-value escaping: backslash, quote, newline
    assert r'route="a\"b\\c\nd"' in text
    # HELP escaping: the literal newline must not split the line
    help_line = next(ln for ln in lines
                     if ln.startswith("# HELP app_requests_total"))
    assert "\\n" in help_line
    # unknown kinds degrade to untyped, not an invalid token
    assert "# TYPE app_gauge untyped" in text


def test_scrape_endpoint_content_type(ledger_cluster):
    _ctx, port = ledger_cluster

    def scrape():
        try:
            return urllib.request.urlopen(
                f"http://127.0.0.1:{port}/metrics", timeout=10)
        except (ConnectionError, OSError):
            return None

    r = _wait_for(scrape, what="scrape endpoint")
    ctype = r.headers.get("Content-Type", "")
    assert ctype.startswith("text/plain; version=0.0.4"), ctype
    body = r.read().decode()
    assert "# TYPE ray_tpu_cluster_up gauge" in body


def test_store_bytes_tier_gauges(ledger_cluster):
    """ray_tpu_store_bytes{tier=...} gauges ride the agent's node-stats
    publish (metrics_report_interval_ms tick)."""
    held = ray_tpu.put(np.ones(256 * 1024, np.float64))
    from ray_tpu.util.metrics import prometheus_text

    def has_gauges():
        text = prometheus_text()
        return text if ("ray_tpu_store_bytes" in text
                        and 'tier="shm"' in text
                        and "ray_tpu_object_leak_suspects" in text) else None

    text = _wait_for(has_gauges, timeout=30.0, what="tier gauges")
    assert 'tier="disk"' in text and 'tier="remote"' in text
    # the driver-side ledger gauges flush through the same pipeline
    _wait_for(lambda: "ray_tpu_owned_refs" in prometheus_text(),
              timeout=30.0, what="owned-refs gauge")
    del held
