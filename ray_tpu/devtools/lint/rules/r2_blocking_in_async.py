"""R2 — blocking calls inside ``async def`` bodies.

Invariant: coroutine bodies must not issue thread-blocking calls —
``time.sleep``, synchronous subprocess waits, synchronous sockets/HTTP —
because one blocked coroutine freezes the *entire* event loop: every RPC
read loop, watchdog, and heartbeat sharing it goes silent, which reads
as a node death to the rest of the cluster.

Motivating history: the agent/GCS control loops share one loop with the
RPC read path (PRs 1/5); a single stray ``time.sleep`` in a handler
stalls heartbeats long enough to trip the health-check death verdict.

Detection is a deny-list of call shapes, resolved through the module's
imports (``import time as t`` still matches). ``await
asyncio.sleep(...)`` and ``loop.run_in_executor(...)`` are the sanctioned
alternatives.
"""

from __future__ import annotations

import ast
from typing import List, Optional, Tuple

from ..callgraph import _call_name
from ..model import ModuleInfo, Violation

RULE_ID = "R2"
SUMMARY = ("blocking call (time.sleep / sync subprocess / sync HTTP) "
           "inside an async def — stalls the shared event loop; use the "
           "async equivalent or run_in_executor")

# (module, attr) call shapes that block the calling thread
_BLOCKING = {
    ("time", "sleep"),
    ("subprocess", "run"),
    ("subprocess", "call"),
    ("subprocess", "check_call"),
    ("subprocess", "check_output"),
    ("os", "system"),
    ("os", "wait"),
    ("os", "waitpid"),
    ("socket", "create_connection"),
    ("requests", "get"),
    ("requests", "post"),
    ("requests", "put"),
    ("requests", "delete"),
    ("requests", "request"),
    ("urllib.request", "urlopen"),
}


def _import_aliases(mod: ModuleInfo) -> dict:
    """alias -> real module name for plain imports (import time as t)."""
    out = {}
    for node in ast.walk(mod.tree):
        if isinstance(node, ast.Import):
            for a in node.names:
                out[a.asname or a.name.split(".")[0]] = a.name
    return out


def _resolved(base: Optional[str], attr: Optional[str],
              aliases: dict) -> Tuple[Optional[str], Optional[str]]:
    if base is None:
        return None, attr
    return aliases.get(base, base), attr


def check_module(mod: ModuleInfo, index) -> List[Violation]:
    out: List[Violation] = []
    aliases = _import_aliases(mod)
    for node in ast.walk(mod.tree):
        if not isinstance(node, ast.AsyncFunctionDef):
            continue
        for sub in _walk_async_body(node):
            if not isinstance(sub, ast.Call):
                continue
            base, attr = _call_name(sub.func)
            rbase, rattr = _resolved(base, attr, aliases)
            if (rbase, rattr) in _BLOCKING:
                out.append(mod.violation(
                    RULE_ID, sub,
                    f"blocking call '{rbase}.{rattr}()' inside async "
                    f"'{mod.qualname(node)}' freezes the shared event "
                    f"loop (heartbeats, RPC reads, watchdogs); use the "
                    f"async equivalent or loop.run_in_executor"))
    return out


def _walk_async_body(fn: ast.AsyncFunctionDef):
    """Walk the coroutine body without descending into nested *sync*
    defs (those run wherever they're called) but descending into nested
    async defs' bodies is also skipped — they're visited as their own
    AsyncFunctionDef by the outer walk."""
    stack = list(fn.body)
    while stack:
        node = stack.pop()
        yield node
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.ClassDef)):
            continue
        stack.extend(ast.iter_child_nodes(node))
