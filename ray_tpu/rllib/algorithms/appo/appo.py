"""APPO — asynchronous PPO (reference: rllib/algorithms/appo/appo.py:
IMPALA's async actor-learner architecture + PPO's clipped surrogate, with
V-trace correcting the off-policyness of in-flight fragments).

Inherits IMPALA's always-one-sample-in-flight loop; the learner swaps the
plain policy-gradient for the clipped-ratio surrogate on V-trace
advantages (reference: appo/torch/appo_torch_learner.py).
"""

from __future__ import annotations

from typing import Dict

import jax.numpy as jnp

from ray_tpu.rllib.algorithms.impala.impala import (
    IMPALA, IMPALAConfig, ImpalaLearner)
from ray_tpu.rllib.utils.vtrace import vtrace


class APPOLearner(ImpalaLearner):
    def loss(self, params, batch):
        cfg = self.config
        tT = lambda a: jnp.swapaxes(a, 0, 1)
        obs, actions = tT(batch["obs"]), tT(batch["actions"])
        behavior_logp = tT(batch["logp"])
        out = self.module.forward(params, obs)
        dist = self.module.dist
        target_logp = dist.logp(out["logits"], actions)
        vs, pg_adv = vtrace(
            behavior_logp, target_logp, tT(batch["rewards"]), out["vf"],
            tT(batch["dones"]), batch["bootstrap"],
            gamma=cfg.get("gamma", 0.99),
            clip_rho=cfg.get("vtrace_clip_rho_threshold", 1.0),
            clip_c=cfg.get("vtrace_clip_c_threshold", 1.0))
        mask = tT(batch["valid"])
        denom = jnp.maximum(mask.sum(), 1.0)
        clip = cfg.get("clip_param", 0.2)
        ratio = jnp.exp(target_logp - behavior_logp)
        surrogate = jnp.minimum(
            ratio * pg_adv, jnp.clip(ratio, 1 - clip, 1 + clip) * pg_adv)
        pi_loss = -jnp.sum(surrogate * mask) / denom
        vf_loss = 0.5 * jnp.sum((out["vf"] - vs) ** 2 * mask) / denom
        entropy = jnp.sum(dist.entropy(out["logits"]) * mask) / denom
        kl = jnp.sum((behavior_logp - target_logp) * mask) / denom
        total = (pi_loss + cfg.get("vf_loss_coeff", 0.5) * vf_loss
                 - cfg.get("entropy_coeff", 0.01) * entropy)
        return total, {"policy_loss": pi_loss, "vf_loss": vf_loss,
                       "entropy": entropy, "mean_kl": kl}


class APPOConfig(IMPALAConfig):
    def __init__(self, algo_class=None):
        super().__init__(algo_class=algo_class or APPO)
        self.clip_param = 0.2

    def _training_keys(self):
        return super()._training_keys() | {"clip_param"}

    def learner_config_dict(self) -> Dict:
        d = super().learner_config_dict()
        d["clip_param"] = self.clip_param
        return d


class APPO(IMPALA):
    learner_cls = APPOLearner

    @classmethod
    def get_default_config(cls):
        return APPOConfig(algo_class=cls)
