from ray_tpu._private.accelerators.accelerator import AcceleratorManager
from ray_tpu._private.accelerators.tpu import TPUAcceleratorManager
from ray_tpu._private.accelerators.nvidia_gpu import NvidiaGPUAcceleratorManager


def get_all_accelerator_managers():
    return {"TPU": TPUAcceleratorManager, "GPU": NvidiaGPUAcceleratorManager}


def get_accelerator_manager(resource_name: str):
    return get_all_accelerator_managers().get(resource_name)
