"""ray_tpu.data — streaming distributed datasets (reference:
python/ray/data/read_api.py public surface).
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional

import numpy as np

from ray_tpu.data import aggregate
from ray_tpu.data.block import Block, BlockAccessor, BlockMetadata
from ray_tpu.data.dataset import (
    ActorPoolStrategy, Dataset, GroupedData, MaterializedDataset, from_blocks)
from ray_tpu.data.iterator import DataIterator
from ray_tpu.data._internal.logical import Read
from ray_tpu.data import datasource as _ds

__all__ = [
    "Dataset", "MaterializedDataset", "DataIterator", "GroupedData",
    "ActorPoolStrategy", "BlockAccessor", "BlockMetadata", "aggregate",
    "range", "range_tensor", "from_items", "from_numpy", "from_pandas",
    "from_arrow", "from_blocks", "read_parquet", "read_csv", "read_json",
    "read_text", "read_binary_files", "read_numpy", "read_datasource",
]



def read_datasource(source: _ds.Datasource, *,
                    parallelism: int = 8) -> Dataset:
    return Dataset(Read(source.get_read_tasks(parallelism),
                        name=source.name))


def range(n: int, *, parallelism: int = 8) -> Dataset:  # noqa: A001
    return read_datasource(_ds.RangeDatasource(n), parallelism=parallelism)


def range_tensor(n: int, *, shape: tuple = (1,),
                 parallelism: int = 8) -> Dataset:
    return read_datasource(
        _ds.RangeDatasource(n, tensor_shape=tuple(shape), column="data"),
        parallelism=parallelism)


def from_items(items: List[Any], *, parallelism: int = 8) -> Dataset:
    return read_datasource(_ds.ItemsDatasource(list(items)),
                           parallelism=parallelism)


def from_numpy(arrays, column: str = "data") -> Dataset:
    if isinstance(arrays, np.ndarray):
        arrays = [arrays]
    return from_blocks([{column: a} for a in arrays])


def from_pandas(dfs) -> Dataset:
    import pyarrow as pa

    if not isinstance(dfs, list):
        dfs = [dfs]
    return from_blocks([
        pa.Table.from_pandas(df, preserve_index=False) for df in dfs])


def from_arrow(tables) -> Dataset:
    if not isinstance(tables, list):
        tables = [tables]
    return from_blocks(list(tables))


def read_parquet(paths, *, parallelism: int = 8, **kw) -> Dataset:
    return read_datasource(_ds.ParquetDatasource(paths, **kw),
                           parallelism=parallelism)


def read_csv(paths, *, parallelism: int = 8, **kw) -> Dataset:
    return read_datasource(_ds.CSVDatasource(paths, **kw),
                           parallelism=parallelism)


def read_json(paths, *, parallelism: int = 8, **kw) -> Dataset:
    return read_datasource(_ds.JSONDatasource(paths, **kw),
                           parallelism=parallelism)


def read_text(paths, *, parallelism: int = 8) -> Dataset:
    return read_datasource(_ds.TextDatasource(paths),
                           parallelism=parallelism)


def read_binary_files(paths, *, parallelism: int = 8) -> Dataset:
    return read_datasource(_ds.BinaryDatasource(paths),
                           parallelism=parallelism)


def read_numpy(paths, *, parallelism: int = 8) -> Dataset:
    return read_datasource(_ds.NumpyDatasource(paths),
                           parallelism=parallelism)
