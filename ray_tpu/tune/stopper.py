"""Stoppers (reference: python/ray/tune/stopper/ — Stopper ABC with
per-result ``__call__`` and experiment-wide ``stop_all``; the stock
implementations mirrored here: maximum_iteration, timeout, function,
trial_plateau, experiment_plateau, combined, noop).

``RunConfig(stop=...)`` accepts a dict, a callable, or a Stopper.
"""

from __future__ import annotations

import time
from collections import defaultdict, deque
from typing import Callable, Dict, Optional


class Stopper:
    """Decides per-result whether a trial stops; ``stop_all`` ends the
    whole experiment."""

    def __call__(self, trial_id: str, result: Dict) -> bool:
        raise NotImplementedError

    def stop_all(self) -> bool:
        return False


class NoopStopper(Stopper):
    def __call__(self, trial_id: str, result: Dict) -> bool:
        return False


class FunctionStopper(Stopper):
    def __init__(self, function: Callable[[str, Dict], bool]):
        self._fn = function

    def __call__(self, trial_id: str, result: Dict) -> bool:
        return bool(self._fn(trial_id, result))


class MaximumIterationStopper(Stopper):
    def __init__(self, max_iter: int):
        self._max_iter = max_iter

    def __call__(self, trial_id: str, result: Dict) -> bool:
        return result.get("training_iteration", 0) >= self._max_iter


class TimeoutStopper(Stopper):
    """Stops the whole experiment after a wall-clock budget."""

    def __init__(self, timeout: float):
        self._deadline = time.monotonic() + timeout

    def __call__(self, trial_id: str, result: Dict) -> bool:
        return False

    def stop_all(self) -> bool:
        return time.monotonic() >= self._deadline


class TrialPlateauStopper(Stopper):
    """Stops a trial whose metric stopped moving: std of the last
    ``num_results`` values below ``std`` (after ``grace_period`` results)."""

    def __init__(self, metric: str, *, std: float = 0.01,
                 num_results: int = 4, grace_period: int = 4,
                 metric_threshold: Optional[float] = None,
                 mode: str = "min"):
        self._metric = metric
        self._std = std
        self._num_results = num_results
        self._grace = grace_period
        self._threshold = metric_threshold
        self._mode = mode
        self._history: Dict[str, deque] = defaultdict(
            lambda: deque(maxlen=num_results))
        self._count: Dict[str, int] = defaultdict(int)

    def __call__(self, trial_id: str, result: Dict) -> bool:
        import numpy as np

        v = result.get(self._metric)
        if v is None:
            return False
        self._history[trial_id].append(float(v))
        self._count[trial_id] += 1
        if self._count[trial_id] < max(self._grace, self._num_results):
            return False
        if self._threshold is not None:
            ok = (v > self._threshold if self._mode == "max"
                  else v < self._threshold)
            if not ok:
                return False
        return float(np.std(self._history[trial_id])) < self._std


class ExperimentPlateauStopper(Stopper):
    """Stops everything when the experiment plateaued: the std of the
    ``top`` best values of ``metric`` seen so far is below ``std`` for
    more than ``patience`` consecutive results (reference:
    tune/stopper/experiment_plateau.py semantics)."""

    def __init__(self, metric: str, *, std: float = 0.001,
                 top: int = 10, mode: str = "min", patience: int = 0):
        self._metric = metric
        self._mode = mode
        self._top = top
        self._std = std
        self._patience = patience
        self._top_values: list = []
        self._stale = 0
        self._stop_all = False

    def __call__(self, trial_id: str, result: Dict) -> bool:
        import numpy as np

        v = result.get(self._metric)
        if v is None:
            return False
        v = float(v) if self._mode == "max" else -float(v)
        self._top_values.append(v)
        self._top_values = sorted(self._top_values,
                                  reverse=True)[:self._top]
        if len(self._top_values) == self._top and \
                float(np.std(self._top_values)) < self._std:
            self._stale += 1
            if self._stale > self._patience:
                self._stop_all = True
        else:
            self._stale = 0
        return False

    def stop_all(self) -> bool:
        return self._stop_all


class CombinedStopper(Stopper):
    def __init__(self, *stoppers: Stopper):
        self._stoppers = stoppers

    def __call__(self, trial_id: str, result: Dict) -> bool:
        return any(s(trial_id, result) for s in self._stoppers)

    def stop_all(self) -> bool:
        return any(s.stop_all() for s in self._stoppers)
