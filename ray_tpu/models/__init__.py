"""ray_tpu.models — JAX-native model families.

The reference ships no models of its own for Train (users bring torch models);
RLlib ships torch/tf model catalogs (reference: rllib/models/, 12.1k LoC).
TPU-native, the framework provides sharding-annotated JAX model families that
the Train/Serve/RLlib layers consume directly.
"""

from ray_tpu.models.llama import (
    LlamaConfig,
    init_llama,
    llama_forward,
    llama_decode,
    llama_loss,
    llama_logical_axes,
)
from ray_tpu.models.mlp import (
    MLPConfig, init_mlp, mlp_forward, mlp_loss, mlp_logical_axes)

__all__ = [
    "LlamaConfig", "init_llama", "llama_forward", "llama_decode",
    "llama_loss", "llama_logical_axes",
    "MLPConfig", "init_mlp", "mlp_forward", "mlp_loss", "mlp_logical_axes",
]
