"""Minimal dashboard web UI (VERDICT r2 item 10).

Reference: dashboard/client/src/App.tsx — a React SPA over the dashboard
REST API. Here: ONE static page, zero build step, vanilla JS polling the
same REST endpoints this package already serves (`/api/nodes`,
`/api/actors`, `/api/jobs`, `/api/events`, `/api/cluster_status`,
`/api/node_stats`) and rendering stat tiles, tables, and inline-SVG
sparklines (client-side history). The tables ARE the accessible data
view; sparkline colors come from a CVD-validated palette; node/actor
state is never color-alone (dot + text label).
"""

INDEX_HTML = """<!doctype html>
<html lang="en">
<head>
<meta charset="utf-8">
<meta name="viewport" content="width=device-width, initial-scale=1">
<title>ray_tpu dashboard</title>
<style>
  :root {
    color-scheme: light;
    --surface-1: #fcfcfb; --surface-2: #f1f1ef;
    --text-primary: #0b0b0b; --text-secondary: #52514e;
    --border: #dddcd8;
    --series-1: #2a78d6; --series-2: #eb6834; --series-3: #1baf7a;
    --good: #008300; --warning: #eda100; --critical: #e34948;
  }
  @media (prefers-color-scheme: dark) {
    :root {
      color-scheme: dark;
      --surface-1: #1a1a19; --surface-2: #242422;
      --text-primary: #ffffff; --text-secondary: #c3c2b7;
      --border: #3a3a37;
      --series-1: #3987e5; --series-2: #d95926; --series-3: #199e70;
      --good: #199e70; --warning: #c98500; --critical: #e66767;
    }
  }
  body { margin: 0; background: var(--surface-1); color: var(--text-primary);
         font: 14px/1.45 system-ui, sans-serif; }
  header { padding: 14px 20px; border-bottom: 1px solid var(--border);
           display: flex; align-items: baseline; gap: 14px; }
  header h1 { font-size: 17px; margin: 0; }
  header .sub { color: var(--text-secondary); font-size: 12px; }
  main { padding: 16px 20px; max-width: 1200px; margin: 0 auto; }
  .tiles { display: flex; flex-wrap: wrap; gap: 12px; margin-bottom: 20px; }
  .tile { background: var(--surface-2); border: 1px solid var(--border);
          border-radius: 8px; padding: 10px 16px; min-width: 120px; }
  .tile .v { font-size: 26px; font-weight: 600; font-variant-numeric:
             tabular-nums; }
  .tile .k { color: var(--text-secondary); font-size: 12px; }
  section { margin-bottom: 26px; }
  h2 { font-size: 14px; margin: 0 0 8px; }
  table { border-collapse: collapse; width: 100%; }
  th { text-align: left; color: var(--text-secondary); font-weight: 500;
       font-size: 12px; border-bottom: 1px solid var(--border);
       padding: 4px 10px 4px 0; }
  td { padding: 5px 10px 5px 0; border-bottom: 1px solid var(--border);
       font-variant-numeric: tabular-nums; vertical-align: middle; }
  .dot { display: inline-block; width: 8px; height: 8px; border-radius: 50%;
         margin-right: 6px; }
  .muted { color: var(--text-secondary); }
  .spark { vertical-align: middle; margin-right: 6px; }
  .err { color: var(--critical); padding: 8px 0; display: none; }
  code { background: var(--surface-2); padding: 1px 5px; border-radius: 4px; }
</style>
</head>
<body>
<header>
  <h1>ray_tpu</h1>
  <span class="sub" id="updated">connecting…</span>
</header>
<main>
  <div class="err" id="err"></div>
  <div class="tiles" id="tiles"></div>
  <section><h2>Nodes</h2>
    <table id="nodes"><thead><tr>
      <th>State</th><th>Node</th><th>CPU %</th><th>Memory</th>
      <th>Workers</th><th>TPU in use</th><th>Object store</th>
    </tr></thead><tbody></tbody></table></section>
  <section><h2>Actors</h2>
    <table id="actors"><thead><tr>
      <th>State</th><th>Name</th><th>Class</th><th>Actor ID</th><th>Node</th>
    </tr></thead><tbody></tbody></table></section>
  <section><h2>Jobs</h2>
    <table id="jobs"><thead><tr>
      <th>Status</th><th>Job</th><th>Entrypoint</th><th>Submitted</th>
    </tr></thead><tbody></tbody></table></section>
  <section><h2>Recent events</h2>
    <table id="events"><thead><tr>
      <th>Severity</th><th>Time</th><th>Source</th><th>Message</th>
    </tr></thead><tbody></tbody></table></section>
</main>
<script>
"use strict";
const HIST = {};           // node_id -> {cpu: [], mem: []}
const HLEN = 60;           // one sparkline point per poll, ~2 min window
const esc = s => String(s ?? "").replace(/[&<>"]/g,
  c => ({"&":"&amp;","<":"&lt;",">":"&gt;",'"':"&quot;"}[c]));

function spark(values, color, label) {
  if (!values || values.length < 2) return "";
  const w = 90, h = 20, max = Math.max(...values, 1e-9);
  const pts = values.map((v, i) =>
    `${(i / (values.length - 1) * w).toFixed(1)},` +
    `${(h - 2 - (v / max) * (h - 4)).toFixed(1)}`).join(" ");
  return `<svg class="spark" width="${w}" height="${h}" role="img"` +
    ` aria-label="${esc(label)}"><title>${esc(label)}</title>` +
    `<polyline points="${pts}" fill="none" stroke="${color}"` +
    ` stroke-width="2" stroke-linejoin="round"/></svg>`;
}

function dot(state) {
  const m = {ALIVE: "--good", RUNNING: "--good", SUCCEEDED: "--good",
             PENDING: "--warning", RESTARTING: "--warning",
             STOPPED: "--warning", DEAD: "--critical",
             FAILED: "--critical"};
  const v = m[state] || "--text-secondary";
  return `<span class="dot" style="background: var(${v})"></span>` +
         `${esc(state || "?")}`;
}

const fmtGB = b => (b / 2 ** 30).toFixed(1) + " GiB";

async function jget(path) {
  const r = await fetch(path);
  if (!r.ok) throw new Error(path + " -> " + r.status);
  return r.json();
}

function tiles(nodes, actors, jobs, cluster) {
  const total = cluster.total || {}, avail = cluster.available || {};
  const cpuT = total.CPU || 0, cpuA = avail.CPU || 0;
  const tpuT = total.TPU || 0, tpuA = avail.TPU || 0;
  const t = [
    [nodes.filter(n => n.alive !== false).length, "nodes alive"],
    [actors.length, "actors"],
    [jobs.filter(j => (j.status || "") === "RUNNING").length,
     "jobs running"],
    [`${(cpuT - cpuA).toFixed(0)}/${cpuT.toFixed(0)}`, "CPU in use"],
  ];
  if (tpuT > 0) t.push([`${(tpuT - tpuA).toFixed(0)}/${tpuT.toFixed(0)}`,
                        "TPU chips in use"]);
  document.getElementById("tiles").innerHTML = t.map(([v, k]) =>
    `<div class="tile"><div class="v">${esc(v)}</div>` +
    `<div class="k">${esc(k)}</div></div>`).join("");
}

function nodeRows(nodes, stats) {
  const byId = Object.fromEntries(stats.map(s => [s.node_id, s]));
  document.querySelector("#nodes tbody").innerHTML = nodes.map(n => {
    const id = n.node_id || "", s = byId[id] || {};
    const h = HIST[id] = HIST[id] || {cpu: [], mem: []};
    if (s.cpu_percent !== undefined) {
      h.cpu.push(s.cpu_percent); h.mem.push(s.mem_percent || 0);
      if (h.cpu.length > HLEN) { h.cpu.shift(); h.mem.shift(); }
    }
    const tpu = s.tpu || {};
    return `<tr><td>${dot(n.alive === false ? "DEAD" : "ALIVE")}</td>` +
      `<td><code>${esc(id.slice(0, 12))}</code></td>` +
      `<td>${spark(h.cpu, "var(--series-1)",
                   "CPU history " + esc(id.slice(0, 8)))}` +
      `${s.cpu_percent !== undefined ? s.cpu_percent.toFixed(0) : "–"}</td>` +
      `<td>${spark(h.mem, "var(--series-2)",
                   "memory history " + esc(id.slice(0, 8)))}` +
      `${s.mem_used_bytes ? fmtGB(s.mem_used_bytes) + " / " +
        fmtGB(s.mem_total_bytes) : "–"}</td>` +
      `<td>${s.num_workers ?? "–"}</td>` +
      `<td>${tpu.chips_total ? `${tpu.chips_in_use}/${tpu.chips_total}`
                             : "–"}</td>` +
      `<td>${s.object_store && s.object_store.used !== undefined
             ? fmtGB(s.object_store.used) : "–"}</td></tr>`;
  }).join("");
}

function actorRows(actors) {
  document.querySelector("#actors tbody").innerHTML =
    actors.slice(0, 200).map(a =>
      `<tr><td>${dot(a.state)}</td><td>${esc(a.name || "")}</td>` +
      `<td>${esc(a.class_name || "")}</td>` +
      `<td><code>${esc((a.actor_id || "").slice(0, 12))}</code></td>` +
      `<td><code>${esc((a.node_id || "").slice(0, 12))}</code></td></tr>`
    ).join("");
}

function jobRows(jobs) {
  document.querySelector("#jobs tbody").innerHTML = jobs.map(j =>
    `<tr><td>${dot(j.status)}</td>` +
    `<td><code>${esc(j.submission_id || j.job_id || "")}</code></td>` +
    `<td class="muted">${esc(j.entrypoint || "")}</td>` +
    `<td class="muted">${j.start_time
      ? new Date(j.start_time * 1000).toLocaleTimeString() : ""}</td></tr>`
  ).join("");
}

function sevDot(sev) {
  const v = {ERROR: "--critical", FATAL: "--critical",
             WARNING: "--warning"}[sev] || "--good";
  return `<span class="dot" style="background: var(${v})"></span>` +
         `${esc(sev || "INFO")}`;
}

function eventRows(events) {
  document.querySelector("#events tbody").innerHTML =
    events.slice(-50).reverse().map(e =>
      `<tr><td>${sevDot(e.severity)}</td>` +
      `<td class="muted">${e.timestamp
        ? new Date(e.timestamp * 1000).toLocaleTimeString() : ""}</td>` +
      `<td>${esc(e.source_type || e.component || "")}</td>` +
      `<td>${esc(e.message || "")}</td></tr>`).join("");
}

async function tick() {
  try {
    const [nodes, actors, jobs, events, cluster, stats] =
      await Promise.all([
        jget("/api/nodes"), jget("/api/actors"), jget("/api/jobs"),
        jget("/api/events"), jget("/api/cluster_status"),
        jget("/api/node_stats")]);
    tiles(nodes, actors, jobs, cluster);
    nodeRows(nodes, stats);
    actorRows(actors);
    jobRows(jobs);
    eventRows(events);
    document.getElementById("err").style.display = "none";
    document.getElementById("updated").textContent =
      "updated " + new Date().toLocaleTimeString();
  } catch (e) {
    const el = document.getElementById("err");
    el.textContent = "dashboard poll failed: " + e;
    el.style.display = "block";
  }
}
tick();
setInterval(tick, 2000);
</script>
</body>
</html>
"""
