"""Collective API tests (reference analog:
python/ray/util/collective/tests/ single_node_cpu_tests)."""

import numpy as np
import pytest

import ray_tpu
from ray_tpu.util import collective as col


@ray_tpu.remote
class Member:
    def __init__(self, rank, world_size, backend="cpu", group="g"):
        self.rank = rank
        self.ws = world_size
        self.group = group
        col.init_collective_group(world_size, rank, backend=backend,
                                  group_name=group)

    def do_allreduce(self):
        x = np.full((4,), float(self.rank + 1), np.float64)
        return col.allreduce(x, group_name=self.group)

    def do_barrier(self):
        col.barrier(group_name=self.group)
        return self.rank

    def do_broadcast(self):
        x = (np.arange(3.0) if self.rank == 0
             else np.zeros(3))
        return col.broadcast(x, src_rank=0, group_name=self.group)

    def do_allgather(self):
        x = np.array([float(self.rank)])
        return col.allgather(x, group_name=self.group)

    def do_reducescatter(self):
        shards = [np.full((2,), float(self.rank * 10 + i))
                  for i in range(self.ws)]
        return col.reducescatter(shards, group_name=self.group)

    def do_reduce(self):
        x = np.full((2,), float(self.rank + 1))
        return col.reduce(x, dst_rank=0, group_name=self.group)

    def do_sendrecv(self):
        if self.rank == 0:
            col.send(np.array([42.0]), dst_rank=1, group_name=self.group)
            return None
        return col.recv(None, src_rank=0, group_name=self.group)

    def rank_info(self):
        return col.get_rank(self.group), col.get_collective_group_size(self.group)


@pytest.fixture(scope="module")
def members(ray_start_regular):
    ms = [Member.remote(r, 2, "cpu", "g") for r in range(2)]
    ray_tpu.get([m.rank_info.remote() for m in ms])
    yield ms


def test_allreduce(members):
    out = ray_tpu.get([m.do_allreduce.remote() for m in members])
    for o in out:
        np.testing.assert_allclose(o, np.full((4,), 3.0))


def test_barrier(members):
    assert sorted(ray_tpu.get([m.do_barrier.remote() for m in members])) == [0, 1]


def test_broadcast(members):
    out = ray_tpu.get([m.do_broadcast.remote() for m in members])
    for o in out:
        np.testing.assert_allclose(o, np.arange(3.0))


def test_allgather(members):
    out = ray_tpu.get([m.do_allgather.remote() for m in members])
    for o in out:
        np.testing.assert_allclose(np.concatenate(o), [0.0, 1.0])


def test_reducescatter(members):
    out = ray_tpu.get([m.do_reducescatter.remote() for m in members])
    # rank r gets sum over members of shard r: (0*10+r) + (1*10+r) = 10+2r
    np.testing.assert_allclose(out[0], np.full((2,), 10.0))
    np.testing.assert_allclose(out[1], np.full((2,), 12.0))


def test_reduce(members):
    out = ray_tpu.get([m.do_reduce.remote() for m in members])
    np.testing.assert_allclose(out[0], np.full((2,), 3.0))  # root reduced
    np.testing.assert_allclose(out[1], np.full((2,), 2.0))  # non-root unchanged


def test_send_recv(members):
    out = ray_tpu.get([m.do_sendrecv.remote() for m in members])
    assert out[0] is None
    np.testing.assert_allclose(out[1], [42.0])


def test_declarative_group(ray_start_regular):
    @ray_tpu.remote
    class Plain:
        def ar(self):
            return col.allreduce(np.ones(2), group_name="decl_g")

    actors = [Plain.remote() for _ in range(2)]
    col.create_collective_group(actors, 2, [0, 1], backend="cpu",
                                group_name="decl_g")
    out = ray_tpu.get([a.ar.remote() for a in actors])
    for o in out:
        np.testing.assert_allclose(o, np.full((2,), 2.0))


def test_xla_backend_jax_arrays(ray_start_regular):
    @ray_tpu.remote
    class JaxMember:
        def __init__(self, rank):
            col.init_collective_group(2, rank, backend="xla",
                                      group_name="jx")

        def ar(self, rank):
            import jax.numpy as jnp

            x = jnp.full((3,), float(rank + 1), jnp.float32)
            out = col.allreduce(x, group_name="jx")
            import jax

            assert isinstance(out, jax.Array)
            return np.asarray(out)

    ms = [JaxMember.remote(r) for r in range(2)]
    out = ray_tpu.get([m.ar.remote(r) for r, m in enumerate(ms)])
    for o in out:
        np.testing.assert_allclose(o, np.full((3,), 3.0))
