"""PolicyClient — drive an external env against a served policy
(reference: rllib/env/policy_client.py PolicyClient: the inference-server
pattern where the env lives in ANOTHER process/machine — a game engine, a
simulator farm — and asks the training cluster for actions over HTTP).

stdlib-only on purpose: the client must be importable in external
processes that do not have (or want) this framework installed — the file
is self-contained enough to copy out.
"""

from __future__ import annotations

import json
import urllib.request
import uuid
from typing import Any, Dict, List, Optional


class PolicyClient:
    def __init__(self, address: str, timeout: float = 30.0):
        """address: "http://host:port" of a PolicyServerInput."""
        self.address = address.rstrip("/")
        self.timeout = timeout

    def _call(self, payload: Dict) -> Dict:
        req = urllib.request.Request(
            self.address, data=json.dumps(payload).encode(),
            headers={"Content-Type": "application/json"}, method="POST")
        with urllib.request.urlopen(req, timeout=self.timeout) as resp:
            return json.loads(resp.read())

    def start_episode(self, episode_id: Optional[str] = None) -> str:
        episode_id = episode_id or uuid.uuid4().hex
        self._call({"command": "START_EPISODE",
                    "episode_id": episode_id})
        return episode_id

    def get_action(self, episode_id: str, observation) -> Any:
        reply = self._call({"command": "GET_ACTION",
                            "episode_id": episode_id,
                            "observation": _to_jsonable(observation)})
        return reply["action"]

    def log_returns(self, episode_id: str, reward: float) -> None:
        self._call({"command": "LOG_RETURNS", "episode_id": episode_id,
                    "reward": float(reward)})

    def end_episode(self, episode_id: str, observation) -> None:
        self._call({"command": "END_EPISODE", "episode_id": episode_id,
                    "observation": _to_jsonable(observation)})


def _to_jsonable(obs) -> List:
    tolist = getattr(obs, "tolist", None)
    return tolist() if tolist else list(obs)
