"""EnvRunner — rollout actor (reference: rllib/env/env_runner.py:15 +
env/single_agent_env_runner.py; the old-stack RolloutWorker
evaluation/rollout_worker.py:159 ``sample`` :653).

CPU actor stepping a vectorized gymnasium env; policy inference is the
jitted RLModule forward on a fixed (num_envs, obs_dim) batch, so the hot
loop is numpy env stepping + one compiled apply per step.
"""

from __future__ import annotations

import time
from typing import Any, Callable, Dict, List, Optional

import numpy as np


class SingleAgentEnvRunner:
    def __init__(self, env_creator: Callable, num_envs: int,
                 rollout_fragment_length: int, module_spec,
                 seed: int = 0, explore: bool = True,
                 gamma: float = 0.99, collect_next_obs: bool = False,
                 connector=None):
        import gymnasium as gym
        import jax

        self.num_envs = num_envs
        self.T = rollout_fragment_length
        self.gamma = gamma
        self.env = gym.vector.SyncVectorEnv(
            [lambda i=i: env_creator() for i in range(num_envs)])
        self.module = module_spec.build()
        self._rng = jax.random.key(seed)
        self._explore = explore
        # obs/action transform pipeline (reference: rllib/connectors/)
        self.connector = connector
        # off-policy algos (DQN/SAC) need (s, a, r, s') tuples
        self._collect_next_obs = collect_next_obs

        # recurrent modules (R2D2's LSTM Q-net) expose explore_action_
        # recurrent + initial_state: the runner carries (h, c) across
        # steps, zeroes rows on episode reset, and records each fragment's
        # STARTING state so replay can resume it (reference:
        # rllib/algorithms/r2d2 stored-state replay)
        self._recurrent = hasattr(self.module, "explore_action_recurrent") \
            and hasattr(self.module, "initial_state")
        if self._recurrent:
            self._state = tuple(np.asarray(s) for s in
                                self.module.initial_state(num_envs))
            if explore:
                self._jit_explore_rec = jax.jit(
                    self.module.explore_action_recurrent)
            else:
                # evaluation rollouts: force greedy by zeroing the
                # module's exploration epsilon (rides in params)
                def _greedy_rec(weights, obs, state, rng):
                    import jax.numpy as jnp

                    if "epsilon" in weights:
                        weights = dict(
                            weights,
                            epsilon=jnp.zeros_like(weights["epsilon"]))
                    return self.module.explore_action_recurrent(
                        weights, obs, state, rng)

                self._jit_explore_rec = jax.jit(_greedy_rec)
        if explore:
            self._jit_explore = jax.jit(self.module.explore_action)
        else:
            # greedy/deterministic inference (ES candidate evaluation,
            # evaluation rollouts): mode of the action distribution
            self._jit_explore = jax.jit(self._greedy_action)
        self._jit_forward = jax.jit(self.module.forward)

        obs, _ = self.env.reset(seed=seed)
        if self.connector is not None:
            self.connector.on_episode_start()
            obs = self.connector.on_obs(obs)
        self._obs = obs.astype(np.float32)
        self._ep_return = np.zeros(num_envs)
        self._ep_len = np.zeros(num_envs, dtype=np.int64)
        self._completed: List[Dict] = []
        # gymnasium >= 1.0 vector envs autoreset on the step AFTER an
        # episode ends (the action there is ignored, reward is 0) — those
        # transitions are bogus training samples and get masked out
        self._prev_done = np.zeros(num_envs, dtype=bool)

    def _greedy_action(self, weights, obs, rng):
        """Deterministic action with the explore_action signature: argmax
        for discrete modules, distribution mode / deterministic policy
        output for continuous ones."""
        import jax.numpy as jnp

        if hasattr(self.module, "greedy_action"):
            return self.module.greedy_action(weights, obs)
        out = self.module.forward(weights, obs)
        logits = out["logits"]
        if getattr(self.module.spec, "discrete", False):
            action = jnp.argmax(logits, axis=-1)
            logp = self.module.dist.logp(logits, action) \
                if hasattr(self.module, "dist") else jnp.zeros(obs.shape[0])
        elif hasattr(self.module, "dist"):
            action = self.module.dist.split(logits)[0] \
                if hasattr(self.module.dist, "split") else logits
            logp = self.module.dist.logp(logits, action)
        else:
            # deterministic continuous modules (SAC/DDPG forward already
            # returns the greedy action as "logits")
            action = logits
            logp = jnp.zeros(obs.shape[0])
        return action, logp, out["vf"]

    def ping(self) -> bool:
        return True

    def sample(self, weights) -> Dict[str, Any]:
        """One (T, E) fragment using the given policy weights."""
        import jax

        t0 = time.perf_counter()
        obs_buf = np.empty((self.T, self.num_envs) + self._obs.shape[1:],
                           np.float32)
        act_buf: Optional[np.ndarray] = None
        logp_buf = np.empty((self.T, self.num_envs), np.float32)
        vf_buf = np.empty((self.T, self.num_envs), np.float32)
        rew_buf = np.empty((self.T, self.num_envs), np.float32)
        done_buf = np.empty((self.T, self.num_envs), np.float32)
        valid_buf = np.empty((self.T, self.num_envs), bool)
        next_obs_buf = (np.empty_like(obs_buf)
                        if self._collect_next_obs else None)

        # fragment-start recurrent state (rides the sample for replay)
        start_state = (tuple(s.copy() for s in self._state)
                       if self._recurrent else None)

        for t in range(self.T):
            self._rng, key = jax.random.split(self._rng)
            if self._recurrent:
                # zero state rows whose episode just reset (autoreset step)
                if self._prev_done.any():
                    mask = (~self._prev_done).astype(np.float32)[:, None]
                    self._state = tuple(s * mask for s in self._state)
                    if t == 0:
                        start_state = tuple(s.copy() for s in self._state)
                action, logp, vf, new_state = self._jit_explore_rec(
                    weights, self._obs, self._state, key)
                self._state = tuple(np.asarray(s) for s in new_state)
            else:
                action, logp, vf = self._jit_explore(weights, self._obs, key)
            action = np.asarray(action)
            if act_buf is None:
                act_buf = np.empty((self.T,) + action.shape, action.dtype)
            obs_buf[t] = self._obs
            act_buf[t] = action
            logp_buf[t] = np.asarray(logp)
            vf_buf[t] = np.asarray(vf)
            env_action = action
            if self.connector is not None:
                env_action = self.connector.on_action(env_action)
            if not self.module.spec.discrete:
                low = self.env.single_action_space.low
                high = self.env.single_action_space.high
                env_action = np.clip(env_action, low, high)
            valid_buf[t] = ~self._prev_done
            obs, rew, term, trunc, _ = self.env.step(env_action)
            if self.connector is not None:
                # transform BEFORE any forward pass so vf bootstraps and
                # the stored next obs see the same features as inference.
                # prev_done envs just autoreset: this obs begins a fresh
                # episode, so stateful connectors clear those rows
                obs = self.connector.on_obs(obs,
                                            reset_mask=self._prev_done)
            done = np.logical_or(term, trunc)
            rew = np.asarray(rew, np.float32)
            rew_raw = rew
            trunc_only = np.logical_and(trunc, ~term)
            if trunc_only.any():
                # time-limit truncation: bootstrap with V(final_obs) folded
                # into the reward (the obs returned at a truncated step IS
                # the final obs under next-step autoreset), then cut the
                # recursion like a termination
                vf_final = np.asarray(self._jit_forward(
                    weights, obs.astype(np.float32))["vf"], np.float32)
                rew = rew + self.gamma * vf_final * trunc_only
            rew_buf[t] = rew
            done_buf[t] = done.astype(np.float32)
            live = ~self._prev_done
            self._ep_return += rew_raw * live
            self._ep_len += live.astype(np.int64)
            for i in np.nonzero(np.logical_and(done, live))[0]:
                self._completed.append({
                    "episode_return": float(self._ep_return[i]),
                    "episode_len": int(self._ep_len[i]),
                })
                self._ep_return[i] = 0.0
                self._ep_len[i] = 0
            self._prev_done = done
            self._obs = obs.astype(np.float32)
            if next_obs_buf is not None:
                next_obs_buf[t] = self._obs

        last_vf = np.asarray(
            self._jit_forward(weights, self._obs)["vf"], np.float32)
        episodes, self._completed = self._completed, []
        out = {
            "obs": obs_buf, "actions": act_buf, "logp": logp_buf,
            "vf": vf_buf, "rewards": rew_buf, "dones": done_buf,
            "valid": valid_buf, "last_vf": last_vf,
            "episodes": episodes,
            "env_steps": self.T * self.num_envs,
            "sample_time_s": time.perf_counter() - t0,
        }
        if next_obs_buf is not None:
            out["next_obs"] = next_obs_buf
        if self._recurrent:
            out["state_in"] = start_state
        return out

    def stop(self):
        self.env.close()
        return True
