"""Shared-memory channel for compiled DAGs (reference:
python/ray/experimental/channel.py, 171 LoC — the fixed buffer the
accelerated-DAG prototype reuses between executions instead of allocating a
fresh object per message).

Here: a ring of pre-created slots in the node's object store. ``write``
seals slot ``i % n``, ``read`` blocks for it and deletes after consumption,
so repeated DAG executions reuse at most ``n`` allocations' worth of shm
at a time while readers stay zero-copy.
"""

from __future__ import annotations

import time
from typing import Any, Optional

import ray_tpu
from ray_tpu._private.ids import ObjectID


class Channel:
    """SPSC channel between two processes on one node."""

    def __init__(self, capacity: int = 2, _key: Optional[str] = None):
        import os

        self._key = _key or os.urandom(8).hex()
        self.capacity = capacity
        self._wseq = 0
        self._rseq = 0

    def _slot_id(self, seq: int) -> ObjectID:
        import hashlib

        h = hashlib.sha256(
            f"{self._key}:{seq}".encode()).digest()[:ObjectID.SIZE]
        return ObjectID(h)

    # ------------------------------------------------------------- writing
    def write(self, value: Any, timeout: Optional[float] = 30.0) -> None:
        from ray_tpu._private import worker as worker_mod

        w = worker_mod.global_worker
        # backpressure: wait until the slot from `capacity` writes ago has
        # been consumed (deleted) by the reader
        if self._wseq >= self.capacity:
            old = self._slot_id(self._wseq - self.capacity)
            deadline = time.monotonic() + (timeout or 1e9)
            while w.store.contains(old):
                if time.monotonic() > deadline:
                    raise TimeoutError("channel full: reader too slow")
                time.sleep(0.001)
        sobj = w._serialize_value(value)
        oid = self._slot_id(self._wseq)
        view, handle = w.store.create(oid, sobj.total_size())
        sobj.write_into(view)
        w.store.seal(oid, handle)
        self._wseq += 1

    # ------------------------------------------------------------- reading
    def read(self, timeout: Optional[float] = 30.0) -> Any:
        from ray_tpu._private import worker as worker_mod

        w = worker_mod.global_worker
        oid = self._slot_id(self._rseq)
        deadline = time.monotonic() + (timeout or 1e9)
        while True:
            view = w.store.get_view(oid)
            if view is not None:
                break
            if time.monotonic() > deadline:
                raise TimeoutError("channel read timed out")
            time.sleep(0.001)
        # copy before deserializing: the slot must be deletable immediately
        # (the native arena refuses to delete while a pinned view aliases
        # it, which would wedge the writer's backpressure loop)
        data = bytes(view)
        del view
        value = w.serialization_context.deserialize(memoryview(data))
        w.store.delete(oid)
        self._rseq += 1
        return value

    def __reduce__(self):
        return (Channel, (self.capacity, self._key))
