"""Flash attention (forward) as a Pallas TPU kernel.

Online-softmax blocked attention: the kv axis is the innermost grid dim, and
running (max, sum, acc) state lives in VMEM scratch that persists across the
sequential TPU grid — the classic FlashAttention-2 schedule mapped onto
Pallas. Causal blocks above the diagonal are skipped with ``pl.when`` (zero
MXU work, the DMA still runs; a fused skip via index_map is a later
optimization).

GQA is handled in the index maps (kv head = q head // n_rep) — no kv
materialization. Backward currently recomputes through the XLA reference path
under ``jax.custom_vjp`` (correct; Pallas dq/dkv kernels are the planned
upgrade).
"""

from __future__ import annotations

import functools
from typing import Tuple

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

_NEG_INF = -1e30


def _fwd_kernel(q_ref, k_ref, v_ref, o_ref, m_scr, l_scr, acc_scr,
                *, scale: float, causal: bool, block_q: int, block_k: int):
    iq = pl.program_id(2)
    ik = pl.program_id(3)
    nk = pl.num_programs(3)

    @pl.when(ik == 0)
    def _init():
        m_scr[:] = jnp.full_like(m_scr, _NEG_INF)
        l_scr[:] = jnp.zeros_like(l_scr)
        acc_scr[:] = jnp.zeros_like(acc_scr)

    # Skip fully-masked blocks (strictly above the causal diagonal).
    run = True
    if causal:
        run = ik * block_k <= iq * block_q + block_q - 1

    @pl.when(run)
    def _compute():
        q = q_ref[0, 0].astype(jnp.float32)            # [bq, d]
        k = k_ref[0, 0].astype(jnp.float32)            # [bk, d]
        v = v_ref[0, 0].astype(jnp.float32)            # [bk, d]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale   # [bq, bk]
        if causal:
            q_pos = iq * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0)
            k_pos = ik * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1)
            s = jnp.where(q_pos >= k_pos, s, _NEG_INF)
        m_prev = m_scr[:, :1]                        # [bq, 1]
        m_cur = jnp.max(s, axis=-1, keepdims=True)   # [bq, 1]
        m_new = jnp.maximum(m_prev, m_cur)
        p = jnp.exp(s - m_new)                       # [bq, bk]
        alpha = jnp.exp(m_prev - m_new)              # [bq, 1]
        l_new = alpha * l_scr[:, :1] + jnp.sum(p, axis=-1, keepdims=True)
        acc_scr[:] = acc_scr[:] * alpha + jax.lax.dot_general(
            p, v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        m_scr[:] = jnp.broadcast_to(m_new, m_scr.shape)
        l_scr[:] = jnp.broadcast_to(l_new, l_scr.shape)

    @pl.when(ik == nk - 1)
    def _finalize():
        l = l_scr[:, :1]
        o_ref[0, 0] = (acc_scr[:] / jnp.maximum(l, 1e-30)).astype(o_ref.dtype)


def _flash_fwd(q: jax.Array, k: jax.Array, v: jax.Array, *,
               causal: bool, block_q: int, block_k: int) -> jax.Array:
    """q [B,H,S,D], k/v [B,KVH,S,D] → o [B,H,S,D]."""
    B, H, Sq, D = q.shape
    KVH, Skv = k.shape[1], k.shape[2]
    n_rep = H // KVH
    scale = D ** -0.5
    block_q = next(b for b in (block_q, 256, 128) if Sq % b == 0 or b == 128)
    block_k = next(b for b in (block_k, 256, 128) if Skv % b == 0 or b == 128)
    if Sq % block_q or Skv % block_k:
        raise ValueError(f"seq lens ({Sq},{Skv}) must divide by 128")
    grid = (B, H, Sq // block_q, Skv // block_k)

    kernel = functools.partial(
        _fwd_kernel, scale=scale, causal=causal,
        block_q=block_q, block_k=block_k)

    return pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, 1, block_q, D),
                         lambda b, h, iq, ik: (b, h, iq, 0)),
            pl.BlockSpec((1, 1, block_k, D),
                         lambda b, h, iq, ik: (b, h // n_rep, ik, 0)),
            pl.BlockSpec((1, 1, block_k, D),
                         lambda b, h, iq, ik: (b, h // n_rep, ik, 0)),
        ],
        out_specs=pl.BlockSpec((1, 1, block_q, D),
                               lambda b, h, iq, ik: (b, h, iq, 0)),
        out_shape=jax.ShapeDtypeStruct((B, H, Sq, D), q.dtype),
        scratch_shapes=[
            pltpu.VMEM((block_q, 128), jnp.float32),   # running max
            pltpu.VMEM((block_q, 128), jnp.float32),   # running sum
            pltpu.VMEM((block_q, D), jnp.float32),     # accumulator
        ],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "parallel",
                                 "arbitrary")),
        interpret=jax.devices()[0].platform != "tpu",
    )(q, k, v)


# Kernel takes [B,H,S,D]; public API is [B,S,H,D] to match ops.attention.

@functools.partial(jax.custom_vjp, nondiff_argnums=(3,))
def flash_attention(q: jax.Array, k: jax.Array, v: jax.Array,
                    causal: bool = True) -> jax.Array:
    qt = jnp.swapaxes(q, 1, 2)
    kt = jnp.swapaxes(k, 1, 2)
    vt = jnp.swapaxes(v, 1, 2)
    o = _flash_fwd(qt, kt, vt, causal=causal, block_q=256, block_k=256)
    return jnp.swapaxes(o, 1, 2)


def _fa_fwd(q, k, v, causal):
    return flash_attention(q, k, v, causal), (q, k, v)


def _fa_bwd(causal, res, g):
    from ray_tpu.ops.attention import reference_attention
    q, k, v = res
    _, vjp = jax.vjp(
        lambda q_, k_, v_: reference_attention(q_, k_, v_, causal=causal),
        q, k, v)
    return vjp(g)


flash_attention.defvjp(_fa_fwd, _fa_bwd)
