from ray_tpu.rllib.algorithms.ddpg.ddpg import (
    DDPG, DDPGConfig, TD3, TD3Config)

__all__ = ["DDPG", "DDPGConfig", "TD3", "TD3Config"]
