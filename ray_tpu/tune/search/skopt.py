"""SkOptSearch adapter (reference: python/ray/tune/search/skopt/
skopt_search.py). Gated: `scikit-optimize` is not in this image's baked
package set — construction raises a clear ImportError; the adapter logic
activates when skopt is importable."""

from __future__ import annotations

from typing import Dict, List, Optional

from ray_tpu.tune.search.sample import Categorical, Float, Integer
from ray_tpu.tune.search.searcher import Searcher


class SkOptSearch(Searcher):
    def __init__(self, space: Optional[Dict] = None,
                 metric: Optional[str] = None, mode: Optional[str] = None,
                 **kwargs):
        try:
            import skopt  # noqa: F401
        except ImportError as e:
            raise ImportError(
                "SkOptSearch requires `scikit-optimize` (skopt), which is "
                "not installed in this environment. Use the native "
                "GP searcher (ray_tpu.tune.search.bayesopt) instead.") from e
        super().__init__(metric, mode)
        self._space = space or {}
        self._points: Dict[str, list] = {}
        self._build()

    def _build(self) -> None:
        import skopt

        self._names: List[str] = []
        self._constants: Dict[str, object] = {}
        dims = []
        for k, dom in self._space.items():
            if isinstance(dom, Categorical):
                dims.append(skopt.space.Categorical(
                    list(dom.categories), name=k))
            elif isinstance(dom, Integer):
                dims.append(skopt.space.Integer(
                    dom.lower, dom.upper - 1, name=k))
            elif isinstance(dom, Float):
                prior = "log-uniform" if getattr(dom, "log", False) \
                    else "uniform"
                dims.append(skopt.space.Real(
                    dom.lower, dom.upper, prior=prior, name=k))
            else:
                self._constants[k] = dom
                continue
            self._names.append(k)
        self._opt = skopt.Optimizer(dims)

    def set_search_properties(self, metric, mode, config) -> bool:
        """Adopt the Tuner-supplied metric/mode/param_space (reference:
        skopt_search.py set_search_properties)."""
        super().set_search_properties(metric, mode, config)
        if config and not self._space:
            self._space = dict(config)
            self._build()
        return True

    def suggest(self, trial_id: str) -> Optional[Dict]:
        point = self._opt.ask()
        self._points[trial_id] = point
        out = dict(zip(self._names, point))
        out.update(self._constants)
        return out

    def on_trial_complete(self, trial_id, result=None,
                          error: bool = False) -> None:
        point = self._points.pop(trial_id, None)
        if point is None or error or not result or \
                self.metric not in result:
            return
        val = float(result[self.metric])
        # skopt minimizes; flip for max mode
        self._opt.tell(point, -val if self.mode == "max" else val)
