"""Cross-language calls (reference: python/ray/cross_language.py — typed
function descriptors address non-Python targets by name; args/returns are
msgpack, never pickle).

Python -> C++: ``cpp_function("name").remote(args...)`` submits a task
whose lease asks for ``runtime_env={"language": "cpp"}``; the agent routes
it to an externally-registered C++ TaskWorker (cpp/include/ray_tpu/
worker.hpp), which executes the registered native function and returns a
msgpack payload.

C++ -> Python runs the other way through the same plane: the C++ driver
client's SubmitPyTask names a Python function "pkg.mod:qualname"
(cpp/src/client.cc, function_table.XLANG_PYREF_FID).
"""

from __future__ import annotations

from typing import Dict, Optional


class _XlangFunction:
    def __init__(self, name: str, language: str,
                 resources: Optional[Dict[str, float]] = None,
                 num_returns: int = 1):
        self._name = name
        self._language = language
        self._resources = resources
        self._num_returns = num_returns

    def options(self, *, resources: Optional[Dict[str, float]] = None,
                num_returns: int = 1) -> "_XlangFunction":
        return _XlangFunction(self._name, self._language,
                              resources, num_returns)

    def remote(self, *args):
        from ray_tpu._private import worker as worker_mod

        w = worker_mod.global_worker
        if w is None:
            raise RuntimeError("ray_tpu.init() first")
        refs = w.submit_xlang_task(
            self._name, args, language=self._language,
            resources=self._resources, num_returns=self._num_returns)
        return refs[0] if self._num_returns == 1 else refs

    def __repr__(self):
        return f"<{self._language} function {self._name!r}>"


def cpp_function(name: str) -> _XlangFunction:
    """Handle to a C++ function registered in a TaskWorker
    (reference: ray.cross_language.cpp_function)."""
    return _XlangFunction(name, "cpp")
