"""Accelerator detection against fake sysfs/dev trees (reference:
python/ray/tests/test_accelerators/* probe their managers the same way —
no real hardware, just the filesystem contract each driver exposes)."""

import os

import pytest

from ray_tpu._private.accelerators.other import (
    AMDGPUAcceleratorManager, HPUAcceleratorManager,
    IntelGPUAcceleratorManager, NeuronAcceleratorManager,
    NPUAcceleratorManager)


@pytest.fixture(autouse=True)
def clear_overrides(monkeypatch):
    for var in ("RAY_TPU_NUM_AMD_GPUS", "RAY_TPU_NUM_INTEL_GPUS",
                "RAY_TPU_NUM_NEURON_CORES", "RAY_TPU_NUM_HPUS",
                "RAY_TPU_NUM_NPUS"):
        monkeypatch.delenv(var, raising=False)


def test_amd_counts_only_gpu_nodes(tmp_path, monkeypatch):
    nodes = tmp_path / "class/kfd/kfd/topology/nodes"
    for i, gpu_id in enumerate(["0", "1234", "777"]):  # node 0 is the CPU
        d = nodes / str(i)
        d.mkdir(parents=True)
        (d / "gpu_id").write_text(gpu_id + "\n")
    monkeypatch.setattr(AMDGPUAcceleratorManager, "SYS_ROOT",
                        str(tmp_path))
    assert AMDGPUAcceleratorManager.get_current_node_num_accelerators() == 2


def test_intel_matches_vendor(tmp_path, monkeypatch):
    for name, vendor in [("renderD128", "0x8086"), ("renderD129", "0x10de"),
                         ("renderD130", "0x8086")]:
        d = tmp_path / "class/drm" / name / "device"
        d.mkdir(parents=True)
        (d / "vendor").write_text(vendor + "\n")
    monkeypatch.setattr(IntelGPUAcceleratorManager, "SYS_ROOT",
                        str(tmp_path))
    assert IntelGPUAcceleratorManager.\
        get_current_node_num_accelerators() == 2


def test_neuron_two_cores_per_device(tmp_path, monkeypatch):
    for name in ("neuron0", "neuron1", "neuron_monitor"):  # last not a dev
        (tmp_path / name).touch()
    monkeypatch.setattr(NeuronAcceleratorManager, "DEV_ROOT", str(tmp_path))
    assert NeuronAcceleratorManager.get_current_node_num_accelerators() == 4


def test_hpu_discriminates_from_tpu_accel_nodes(tmp_path, monkeypatch):
    drivers = tmp_path / "drivers"
    drivers.mkdir(parents=True)
    for name, drv in [("accel0", "habanalabs"), ("accel1", "tpu_common")]:
        d = tmp_path / "class/accel" / name / "device"
        d.mkdir(parents=True)
        (drivers / drv).mkdir(exist_ok=True)
        os.symlink(drivers / drv, d / "driver")
    monkeypatch.setattr(HPUAcceleratorManager, "SYS_ROOT", str(tmp_path))
    assert HPUAcceleratorManager.get_current_node_num_accelerators() == 1


def test_npu_davinci_nodes(tmp_path, monkeypatch):
    for name in ("davinci0", "davinci1", "davinci_manager"):
        (tmp_path / name).touch()
    monkeypatch.setattr(NPUAcceleratorManager, "DEV_ROOT", str(tmp_path))
    assert NPUAcceleratorManager.get_current_node_num_accelerators() == 2


def test_env_override_wins(tmp_path, monkeypatch):
    monkeypatch.setattr(NPUAcceleratorManager, "DEV_ROOT", str(tmp_path))
    (tmp_path / "davinci0").touch()
    monkeypatch.setenv("RAY_TPU_NUM_NPUS", "8")
    assert NPUAcceleratorManager.get_current_node_num_accelerators() == 8
    monkeypatch.setenv("RAY_TPU_NUM_NPUS", "0")
    assert NPUAcceleratorManager.get_current_node_num_accelerators() == 0


def test_node_detection_advertises_probed_families(monkeypatch):
    """The probe results must reach the node's resource advertisement
    (review finding: detection that never feeds scheduling is dead
    code). Uses env overrides as the probe stand-in."""
    from ray_tpu._private.node import _detect_resources

    monkeypatch.setenv("RAY_TPU_NUM_NEURON_CORES", "4")
    monkeypatch.setenv("RAY_TPU_NUM_NPUS", "2")
    resources = _detect_resources()
    assert resources["neuron_cores"] == 4.0
    assert resources["NPU"] == 2.0


def test_gpu_chain_falls_through_to_amd(tmp_path, monkeypatch):
    from ray_tpu._private.accelerators import _GPUChain

    nodes = tmp_path / "class/kfd/kfd/topology/nodes/1"
    nodes.mkdir(parents=True)
    (nodes / "gpu_id").write_text("777\n")
    monkeypatch.setattr(AMDGPUAcceleratorManager, "SYS_ROOT",
                        str(tmp_path))
    assert _GPUChain.get_current_node_num_accelerators() == 1
    assert _GPUChain.get_visible_accelerator_ids_env_var() == \
        "HIP_VISIBLE_DEVICES"


def test_visible_ids_env(monkeypatch):
    monkeypatch.setenv("HIP_VISIBLE_DEVICES", "")  # register for teardown
    AMDGPUAcceleratorManager.set_visible_accelerator_ids([0, 2])
    assert os.environ["HIP_VISIBLE_DEVICES"] == "0,2"


def test_intel_skips_boot_vga_igpu(tmp_path, monkeypatch):
    d = tmp_path / "class/drm/renderD128/device"
    d.mkdir(parents=True)
    (d / "vendor").write_text("0x8086\n")
    (d / "boot_vga").write_text("1\n")
    monkeypatch.setattr(IntelGPUAcceleratorManager, "SYS_ROOT",
                        str(tmp_path))
    assert IntelGPUAcceleratorManager.\
        get_current_node_num_accelerators() == 0
