"""Serializability inspection (reference: python/ray/util/check_serialize.py
``inspect_serializability`` — recursively finds which closure variables or
attributes make an object unpicklable, instead of a bare pickle error)."""

from __future__ import annotations

import inspect
from typing import Any, Optional, Set, Tuple


class FailureTuple:
    """One offending object found while descending."""

    def __init__(self, obj: Any, name: str, parent: Any):
        self.obj = obj
        self.name = name
        self.parent = parent

    def __repr__(self) -> str:
        return f"FailureTuple(obj={self.obj!r}, name={self.name})"


def _serializable(obj: Any) -> bool:
    from ray_tpu._private import serialization as ser

    try:
        ser.dumps(obj)
        return True
    except Exception:
        return False


def _descend(obj: Any, name: str, parent: Any, failures: list,
             seen: Set[int], depth: int) -> None:
    """Record the deepest reachable causes of unserializability under
    ``obj`` (which the CALLER has already determined to be unserializable —
    no re-pickling here). Guarantees at least one FailureTuple per call, so
    cycles and the depth cutoff can never yield a 'failed with no offending
    objects' verdict."""
    if id(obj) in seen or depth > 4:
        failures.append(FailureTuple(obj, name, parent))
        return
    seen.add(id(obj))
    children: list = []
    if inspect.isfunction(obj):
        closure = inspect.getclosurevars(obj)
        children = [*closure.nonlocals.items(), *closure.globals.items()]
    elif hasattr(obj, "__dict__") and not inspect.isclass(obj):
        children = list(vars(obj).items())
    before = len(failures)
    for child_name, child in children:
        if not _serializable(child):
            _descend(child, f"{name}.{child_name}", obj, failures, seen,
                     depth + 1)
    if len(failures) == before:
        # no child explains it: this object itself is the leaf cause
        failures.append(FailureTuple(obj, name, parent))


def inspect_serializability(
    obj: Any, name: Optional[str] = None,
    print_failures: bool = True,
) -> Tuple[bool, Set[FailureTuple]]:
    """Returns (is_serializable, failure set); prints a readable trace of
    the offending closure variables / attributes when it is not."""
    name = name or getattr(obj, "__name__", repr(obj)[:40])
    if _serializable(obj):
        return True, set()
    failures: list = []
    _descend(obj, name, None, failures, set(), 0)
    if print_failures:
        print(f"{name!r} is not serializable. Offending objects:")
        for f in failures:
            print(f"  - {f.name}: {type(f.obj).__name__} = {f.obj!r:.80}")
    return False, set(failures)
